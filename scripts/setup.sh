#!/usr/bin/env bash
# One-shot environment bootstrap (the reference's prep-instance.sh analogue,
# minus cloud provisioning): build the native engine, transcribe the bundled
# SGF corpus into training shards, and run the test suite.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== building native rules engine"
make -C native

echo "== transcribing bundled corpus"
python -m deepgo_tpu.data.transcribe --src data/sgf --out data/processed \
    --splits train,validation,test

echo "== running tests"
python -m pytest tests/ -q

echo "== smoke training run (CPU-sized)"
python -m deepgo_tpu.cli localtest --iters 20

echo "setup complete"
