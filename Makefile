# Developer entry points. `scripts/setup.sh` chains native + data + test.

.PHONY: native data test test-full lint verify verify-faults verify-serving \
    verify-resilience verify-fleet verify-distributed verify-remesh \
    verify-obs \
    verify-slo verify-trace verify-loop verify-analysis verify-xlacheck \
    verify-cost verify-quant verify-telemetry verify-workload \
    verify-chaos verify-cache verify-sessions verify-search bench bench-gate smoke clean

native:
	$(MAKE) -C native

data: native
	python -m deepgo_tpu.data.transcribe --src data/sgf --out data/processed \
	    --splits train,validation,test

test:
	python -m pytest tests/ -q

lint:  # invariant linter + code<->docs grammar drift; exit != 0 on any strict finding
	JAX_PLATFORMS=cpu python -m deepgo_tpu.cli lint

test-full:  # every golden position, not the sampled sweep
	DEEPGO_GOLDEN_FULL=1 python -m pytest tests/ -q

verify-faults:  # crash-safety + fault-injection suite, slow kill-and-resume included
	JAX_PLATFORMS=cpu python -m pytest tests/test_atomicio.py \
	    tests/test_faults.py tests/test_checkpoint.py tests/test_resume.py -q

verify-serving:  # batching engine: bucket bitwise parity, zero-recompile, lifecycle
	JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py \
	    tests/test_serving_engine.py -q

verify-resilience:  # fault-injected serving: restart+replay, poison isolation, breaker, shedding
	JAX_PLATFORMS=cpu python -m pytest tests/test_supervisor.py -q

verify-fleet:  # fleet router: failover with exclusion, respawn, rolling hot reload, tier shedding
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q

verify-distributed:  # multi-host elastic: liveness, deadlines, subprocess chaos recovery
	JAX_PLATFORMS=cpu python -m pytest tests/test_liveness.py \
	    tests/test_deadlines.py tests/test_elastic.py \
	    tests/test_distributed.py tests/test_watchdog.py -q

verify-remesh:  # reshard-on-remesh: save/restore round-trips across every dp x tp layout on 8 virtual devices, corrupt-manifest refusal, per_host_batch rebalance matrix, fault sites, slow tp-crossing SIGKILL chaos recovery
	JAX_PLATFORMS=cpu python -m pytest tests/test_reshard.py -q

verify-obs:  # observability: registry concurrency, exporter round-trip, spans, rotation
	JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q

verify-slo:  # analysis layer: SLO burn windows, sentinel gate + flight recorder, attribution coverage
	JAX_PLATFORMS=cpu python -m pytest tests/test_slo.py tests/test_sentinel.py \
	    tests/test_attribution.py -q

verify-trace:  # request tracing: cross-thread span handoff, trace continuity through restart/failover, bounded exemplar sampling, lineage chain, cli trace
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q

verify-loop:  # expert-iteration loop: replay-buffer durability, cursor-pinned bit-exact learner resume (SIGKILL included), gatekeeper, one full in-process loop turn
	JAX_PLATFORMS=cpu python -m pytest tests/test_loop.py -q

verify-analysis:  # invariant linter fixtures + clean-tree run + lock-order sanitizer
	JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py \
	    tests/test_lockcheck.py -q

verify-xlacheck:  # XLA-contract sanitizer: recompile sentinel (live storm), transfer guard, sharding claims, bench gate fold
	JAX_PLATFORMS=cpu python -m pytest tests/test_xlacheck.py -q

verify-cost:  # device cost ledger: analytic-vs-XLA cross-check, ladder monotonicity, degraded mode, /cost route, MFU-floor gate, attribution MFU join
	JAX_PLATFORMS=cpu python -m pytest tests/test_costmodel.py -q

verify-quant:  # int8 + fused-sym serving variants: po2 bitwise identity, per-rung tolerance floors, mixed-variant fleet zero-recompile, hot-swap old-or-new proof, refusal path
	JAX_PLATFORMS=cpu python -m pytest tests/test_quant.py -q

verify-telemetry:  # fleet telemetry plane: fake-clock sampler cadence, retention/downsample pinning, anomaly matrix, dead-endpoint federation, dash --once/--json, trend
	JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q

verify-workload:  # workload observatory: dihedral canonicalization, torn-line capture reads, off-mode-free recorder, open-loop replay fidelity, synthetic generator determinism, cli record/analyze/replay
	JAX_PLATFORMS=cpu python -m pytest tests/test_workload.py -q

verify-chaos:  # chaos campaigns: fault-kind/scenario/hedging/ejection/canary suite, then a seeded kill+brownout+corrupt smoke campaign on a 2-replica CPU fleet over a synthetic opening-heavy trace (exit != 0 on a failed grade)
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q
	JAX_PLATFORMS=cpu python -m deepgo_tpu.cli chaos run --preset full \
	    --sgf-dir data/sgf/test --requests 120 --rate 40 --seed 0

verify-cache:  # position cache: shared digest/augment table pinning, canonical-hit bitwise remap (all 8 views), coalescing + leader-failure promotion, reload invalidation zero-stale, surge-tier routing, cli --simulate-cache
	JAX_PLATFORMS=cpu python -m pytest tests/test_cache.py -q

verify-sessions:  # durable game sessions: superko/suicide/pass-pass legality pinned to replay ground truth, WAL acked==durable + torn-tail + checkpoint fallback, deadline-tiered replies, resumable bulk scan, per-session workload label
	JAX_PLATFORMS=cpu python -m pytest tests/test_sessions.py -q

verify-search:  # batched PUCT search: fixed-seed determinism, virtual-loss accounting, canonical-frame remap bitwise through all 8 dihedral views, anytime deadline fallback, search agent + selfplay selector, then the two-leg bench gate (transposition hit rate + replica-kill move_lost==0)
	JAX_PLATFORMS=cpu python -m pytest tests/test_search.py -q
	JAX_PLATFORMS=cpu python bench.py --mode search

verify: lint verify-faults verify-serving verify-resilience verify-fleet verify-distributed verify-remesh verify-obs verify-slo verify-trace verify-loop verify-analysis verify-xlacheck verify-cost verify-quant verify-telemetry verify-workload verify-chaos verify-cache verify-sessions verify-search  # the full failure-model suite

bench:
	python bench.py

bench-gate:  # regression sentinel: fail loud (exit != 0) past 10% vs BENCH_LAST_GOOD.json
	python bench.py --gate

smoke: data
	python -m deepgo_tpu.cli localtest --iters 20

clean:
	$(MAKE) -C native clean
	rm -rf data/processed
