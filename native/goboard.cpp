// Native Go rules engine: full-game transcription to packed feature planes.
//
// C++ twin of deepgo_tpu/go/{board,ladders,summarize,replay}.py with
// identical semantics (golden-tested against the same reference records,
// and cross-tested against the Python engine). One call transcribes an
// entire game, so Python pays a single FFI crossing per game.
//
// The reference's equivalent of this layer is its external Torch C/threads
// stack driving makedata.lua; here the whole rules+features hot path is
// native and the algorithm is group-label + bitset-union based rather than
// the reference's per-query re-flood-fill (makedata.lua:122-479).
//
// Build: make -C native   (produces native/build/libgoboard.so)

#include <atomic>
#include <bitset>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int N = 19;
constexpr int NN = N * N;
constexpr int PACKED_CHANNELS = 9;
constexpr uint8_t EMPTY = 0;

using Mask = std::bitset<NN>;

// Precomputed neighbor lists (flat indices).
struct Adjacency {
  int nbr[NN][4];
  int cnt[NN];
  Adjacency() {
    for (int x = 0; x < N; ++x)
      for (int y = 0; y < N; ++y) {
        int p = x * N + y, c = 0;
        if (x > 0) nbr[p][c++] = p - N;
        if (x < N - 1) nbr[p][c++] = p + N;
        if (y > 0) nbr[p][c++] = p - 1;
        if (y < N - 1) nbr[p][c++] = p + 1;
        cnt[p] = c;
      }
  }
};
const Adjacency ADJ;

struct Board {
  uint8_t stones[NN];
  int32_t age[NN];
};

// Flood-fill the chain containing p; fills group/libs masks.
void group_and_libs(const uint8_t* stones, int p, Mask& group, Mask& libs) {
  group.reset();
  libs.reset();
  uint8_t player = stones[p];
  if (player == EMPTY) return;
  int stack[NN];
  int top = 0;
  stack[top++] = p;
  group.set(p);
  while (top) {
    int a = stack[--top];
    for (int i = 0; i < ADJ.cnt[a]; ++i) {
      int n = ADJ.nbr[a][i];
      if (stones[n] == player) {
        if (!group.test(n)) {
          group.set(n);
          stack[top++] = n;
        }
      } else if (stones[n] == EMPTY) {
        libs.set(n);
      }
    }
  }
}

using Undo = std::vector<std::pair<int, uint8_t>>;

// Remove dead opposing chains around p, then p's own chain if dead
// (suicide). Returns opposing stones removed. age/undo optional.
int remove_dead_neighbors(uint8_t* stones, int32_t* age, int p, Undo* undo) {
  uint8_t player = stones[p];
  uint8_t opp = 3 - player;
  int kills = 0;
  Mask checked, group, libs;
  for (int i = 0; i < ADJ.cnt[p]; ++i) {
    int n = ADJ.nbr[p][i];
    if (stones[n] == opp && !checked.test(n)) {
      group_and_libs(stones, n, group, libs);
      checked |= group;
      if (libs.none()) {
        for (int q = 0; q < NN; ++q)
          if (group.test(q)) {
            if (undo) undo->push_back({q, stones[q]});
            stones[q] = EMPTY;
            if (age) age[q] = 1;
            ++kills;
          }
      }
    }
  }
  group_and_libs(stones, p, group, libs);
  if (stones[p] != EMPTY && libs.none()) {
    for (int q = 0; q < NN; ++q)
      if (group.test(q)) {
        if (undo) undo->push_back({q, stones[q]});
        stones[q] = EMPTY;
        if (age) age[q] = 1;
      }
  }
  return kills;
}

// Real move with aging (deepgo_tpu.go.board.play). Returns kills, or -1 if
// the point is occupied.
int play(Board& b, int p, uint8_t player) {
  if (b.stones[p] != EMPTY) return -1;
  for (int q = 0; q < NN; ++q)
    if (b.age[q] > 0 && b.age[q] < 255) ++b.age[q];
  b.stones[p] = player;
  b.age[p] = 1;
  return remove_dead_neighbors(b.stones, b.age, p, nullptr);
}

void play_with_undo(uint8_t* stones, int p, uint8_t player, Undo& undo) {
  undo.push_back({p, stones[p]});
  stones[p] = player;
  remove_dead_neighbors(stones, nullptr, p, &undo);
}

void unwind(uint8_t* stones, Undo& undo, size_t from) {
  for (size_t i = undo.size(); i-- > from;) stones[undo[i].first] = undo[i].second;
  undo.resize(from);
}

// Hypothetical play at empty p: kills + liberties of the new chain
// (deepgo_tpu.go.board.simulate_play).
void simulate_play(uint8_t* stones, int p, uint8_t player, int* kills,
                   int* libs_after) {
  Undo undo;
  undo.push_back({p, stones[p]});
  stones[p] = player;
  *kills = remove_dead_neighbors(stones, nullptr, p, &undo);
  Mask group, libs;
  group_and_libs(stones, p, group, libs);
  *libs_after = static_cast<int>(libs.count());
  unwind(stones, undo, 0);
}

// Recursive ladder search (deepgo_tpu.go.ladders.ladder_moves): for the
// 2-liberty chain at p, which liberties let the opponent capture it in a
// ladder? Results pushed onto out.
void ladder_moves(uint8_t* stones, int p, const Mask& liberties,
                  std::vector<int>& out) {
  uint8_t player = stones[p];
  uint8_t opp = 3 - player;
  int libs[2], nl = 0;
  for (int q = 0; q < NN && nl < 2; ++q)
    if (liberties.test(q)) libs[nl++] = q;

  Undo undo;
  Mask group, glibs;
  for (int i = 0; i < 2; ++i) {
    int chase = libs[i], escape = libs[1 - i];
    size_t mark = undo.size();
    play_with_undo(stones, chase, opp, undo);
    group_and_libs(stones, chase, group, glibs);
    if (glibs.count() > 2) {
      play_with_undo(stones, escape, player, undo);
      group_and_libs(stones, escape, group, glibs);
      size_t n = glibs.count();
      if (n == 1) {
        out.push_back(chase);
      } else if (n == 2) {
        Mask escaped_libs = glibs;
        group_and_libs(stones, chase, group, glibs);
        if (glibs.count() > 1) {
          std::vector<int> sub;
          ladder_moves(stones, p, escaped_libs, sub);
          if (!sub.empty()) out.push_back(chase);
        }
      }
    }
    unwind(stones, undo, mark);
  }
}

inline uint8_t clip255(size_t v) { return v > 255 ? 255 : static_cast<uint8_t>(v); }

// Fan `worker(i)` over [0, n) with up to n_threads std::threads
// (work-stealing via an atomic counter). Small batches run serially: the
// per-board work is a few µs, so thread create/join would dominate.
template <typename F>
void run_batch(int n, int n_threads, F&& body) {
  if (n_threads <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    n_threads = hw ? static_cast<int>(hw) : 1;
  }
  if (n_threads > n) n_threads = n > 0 ? n : 1;
  constexpr int SERIAL_CUTOFF = 16;
  if (n_threads == 1 || n < SERIAL_CUTOFF) {
    for (int i = 0; i < n; ++i) body(i);
    return;
  }
  std::atomic<int> next(0);
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) body(i);
  };
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

// Full position summary -> packed (9, 19, 19) record
// (deepgo_tpu.go.summarize.summarize).
void summarize(Board& b, uint8_t* out) {
  uint8_t* stones = b.stones;
  uint8_t* o_stones = out + 0 * NN;
  uint8_t* o_libs = out + 1 * NN;
  uint8_t* o_la = out + 2 * NN;    // 2 channels
  uint8_t* o_kills = out + 4 * NN; // 2 channels
  uint8_t* o_age = out + 6 * NN;
  uint8_t* o_ladd = out + 7 * NN;  // 2 channels
  std::memset(out, 0, PACKED_CHANNELS * NN);

  for (int q = 0; q < NN; ++q) {
    o_stones[q] = stones[q];
    o_age[q] = clip255(static_cast<size_t>(b.age[q]));
  }

  // One labeling pass: liberties plane, group label + lib masks for reuse.
  std::vector<Mask> group_libs;
  int label[NN];
  for (int q = 0; q < NN; ++q) label[q] = -1;
  Mask group, libs;
  std::vector<int> lmoves;
  for (int q = 0; q < NN; ++q) {
    if (stones[q] != EMPTY && label[q] < 0) {
      group_and_libs(stones, q, group, libs);
      int idx = static_cast<int>(group_libs.size());
      size_t nlibs = libs.count();
      size_t gsize = group.count();
      for (int r = 0; r < NN; ++r)
        if (group.test(r)) {
          label[r] = idx;
          o_libs[r] = clip255(nlibs);
        }
      group_libs.push_back(libs);
      if (nlibs == 2) {
        lmoves.clear();
        ladder_moves(stones, q, libs, lmoves);
        uint8_t chaser = 3 - stones[q];  // the capturing player
        for (int mv : lmoves) o_ladd[(chaser - 1) * NN + mv] = clip255(gsize);
      }
    }
  }

  // kills / liberties-after per empty point per player: bitset-union fast
  // path, simulation only when a capture occurs.
  for (int q = 0; q < NN; ++q) {
    if (stones[q] != EMPTY) continue;
    for (uint8_t player = 1; player <= 2; ++player) {
      uint8_t opp = 3 - player;
      bool captures = false;
      Mask lib_union;
      lib_union.set(q);
      int own[4], n_own = 0;
      for (int i = 0; i < ADJ.cnt[q]; ++i) {
        int n = ADJ.nbr[q][i];
        if (stones[n] == EMPTY) {
          lib_union.set(n);
        } else if (stones[n] == opp) {
          if (group_libs[label[n]].count() == 1) captures = true;
        } else {
          bool seen = false;
          for (int j = 0; j < n_own; ++j) seen |= (own[j] == label[n]);
          if (!seen) own[n_own++] = label[n];
        }
      }
      int kills = 0, la = 0;
      if (captures) {
        simulate_play(stones, q, player, &kills, &la);
      } else {
        for (int j = 0; j < n_own; ++j) lib_union |= group_libs[own[j]];
        la = static_cast<int>(lib_union.count()) - 1;
      }
      o_kills[(player - 1) * NN + q] = clip255(static_cast<size_t>(kills));
      o_la[(player - 1) * NN + q] = clip255(static_cast<size_t>(la));
    }
  }
}

}  // namespace

extern "C" {

// Transcribe one game. moves/handicaps are flat (player, x, y) int32
// triples with 0-based coordinates. out must hold n_moves*9*19*19 bytes:
// the packed record of the board *before* each move. Returns 0, or
// -(1+move_index) if a placement was illegal (occupied point).
int goboard_transcribe(const int32_t* handicaps, int n_handicaps,
                       const int32_t* moves, int n_moves, uint8_t* out) {
  Board b;
  std::memset(b.stones, 0, sizeof(b.stones));
  std::memset(b.age, 0, sizeof(b.age));
  for (int i = 0; i < n_handicaps; ++i) {
    int p = handicaps[i * 3 + 1] * N + handicaps[i * 3 + 2];
    if (play(b, p, static_cast<uint8_t>(handicaps[i * 3])) < 0) return -(1 + i) - 1000000;
  }
  for (int i = 0; i < n_moves; ++i) {
    summarize(b, out + static_cast<size_t>(i) * PACKED_CHANNELS * NN);
    int p = moves[i * 3 + 1] * N + moves[i * 3 + 2];
    if (play(b, p, static_cast<uint8_t>(moves[i * 3])) < 0) return -(1 + i);
  }
  return 0;
}

// Single-position summary for tests/tools: stones (361 bytes), age
// (361 int32) -> packed record.
void goboard_summarize(const uint8_t* stones, const int32_t* age, uint8_t* out) {
  Board b;
  std::memcpy(b.stones, stones, sizeof(b.stones));
  std::memcpy(b.age, age, sizeof(b.age));
  summarize(b, out);
}

// Batch move application for the self-play/arena hot path: board i plays
// moves[i] (flat index, or -1 = pass: board untouched) for players[i],
// with full capture resolution and aging, plus simple-ko detection
// (deepgo_tpu.selfplay.apply_move): when the move captures exactly one
// stone and the new stone sits as a lone chain with exactly one liberty,
// ko_out[i] = that captured point, else -1. Returns 0, or -(1+i) for the
// first board whose move landed on an occupied point.
int goboard_play_batch(uint8_t* stones, int32_t* age, const int32_t* moves,
                       const int32_t* players, int n, int32_t* ko_out,
                       int n_threads) {
  std::atomic<int> err(0);
  run_batch(n, n_threads, [&](int i) {
    Mask checked, group, libs, would_die;
    Board b;
    ko_out[i] = -1;
    int p = moves[i];
    if (p < 0) return;
    uint8_t player = static_cast<uint8_t>(players[i]);
    uint8_t opp = 3 - player;
    uint8_t* st = stones + static_cast<size_t>(i) * NN;
    int32_t* ag = age + static_cast<size_t>(i) * NN;
    if (st[p] != EMPTY) {
      int expect = 0;
      err.compare_exchange_strong(expect, i + 1);
      return;
    }
    // opposing chains whose sole liberty is p die with this move
    for (int k = 0; k < ADJ.cnt[p]; ++k) {
      int nb = ADJ.nbr[p][k];
      if (st[nb] == opp && !checked.test(nb)) {
        group_and_libs(st, nb, group, libs);
        checked |= group;
        if (libs.count() == 1 && libs.test(p)) would_die |= group;
      }
    }
    std::memcpy(b.stones, st, sizeof(b.stones));
    std::memcpy(b.age, ag, sizeof(b.age));
    play(b, p, player);
    std::memcpy(st, b.stones, sizeof(b.stones));
    std::memcpy(ag, b.age, sizeof(b.age));
    if (would_die.count() == 1) {
      group_and_libs(st, p, group, libs);
      if (group.count() == 1 && libs.count() == 1)
        for (int q = 0; q < NN; ++q)
          if (would_die.test(q)) {
            ko_out[i] = q;
            break;
          }
    }
  });
  return err.load() ? -err.load() : 0;
}

// Batch summary for the self-play/arena hot path: n boards (stones
// n*361 bytes, age n*361 int32) -> n packed records, one FFI crossing for
// the whole fleet of live games instead of one per board. Boards are
// independent, so a work-stealing counter fans them across n_threads
// std::threads (<=0 picks hardware_concurrency).
void goboard_summarize_batch(const uint8_t* stones, const int32_t* age,
                             int n, uint8_t* out, int n_threads) {
  run_batch(n, n_threads, [&](int i) {
    Board b;
    std::memcpy(b.stones, stones + static_cast<size_t>(i) * NN, sizeof(b.stones));
    std::memcpy(b.age, age + static_cast<size_t>(i) * NN, sizeof(b.age));
    summarize(b, out + static_cast<size_t>(i) * PACKED_CHANNELS * NN);
  });
}

}  // extern "C"
