"""Canary integrity probes: known-answer sentinels against every replica.

A corrupt replica is the gray failure that latency defenses cannot
see — it answers fast, stays "healthy", and is simply *wrong*. The
``CanaryProber`` closes that gap: it holds a small set of sentinel
positions whose correct outputs were computed before chaos started,
and periodically submits one to EACH replica's engine directly
(``FleetRouter.probe_targets`` — pinned placement, bypassing the
router, because a canary must test the replica it aimed at). A probe
whose answer drifts past tolerance ejects the replica through the
fleet's standard recycle path (``eject_replica(reason="canary")``),
so detection and remediation share one counter and one respawn
machinery with the latency-outlier defense.

Probes ride the ordinary dispatch path inside each replica, so an
injected ``serving_corrupt.<name>`` window corrupts canary answers
exactly as it corrupts user answers — which is the point.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..obs.registry import get_registry


def make_sentinels(positions: list[dict], expected: dict,
                   limit: int = 4) -> list[dict]:
    """Sentinels from trace positions + a digest->known-good-answer
    map: the first ``limit`` distinct digests that have an answer.
    Each sentinel is ``{packed, player, rank, digest, expected}``."""
    out: list[dict] = []
    seen: set[str] = set()
    for item in positions:
        digest = item.get("digest")
        if digest is None or digest in seen or digest not in expected:
            continue
        seen.add(digest)
        out.append({"packed": item["packed"], "player": item["player"],
                    "rank": item["rank"], "digest": digest,
                    "expected": np.asarray(expected[digest])})
        if len(out) >= limit:
            break
    return out


class CanaryProber:
    """Background sentinel prober over a fleet's replicas.

    One daemon thread; every ``interval_s`` it walks the current
    ``probe_targets()`` and submits one sentinel (round-robin over the
    sentinel set, so a replica that only corrupts SOME positions is
    still caught) to each replica, blocking on the answer with a
    bounded timeout. Wrong answer -> eject. Probe *errors* (replica
    mid-respawn, timeout) are not integrity failures — the latency
    and failover defenses own those — so they only tick the probe
    counter, never the failure counter."""

    def __init__(self, fleet, sentinels: list[dict],
                 interval_s: float = 0.25, timeout_s: float = 2.0,
                 rtol: float = 1e-4, atol: float = 1e-5,
                 eject: bool = True, clock=time.monotonic):
        if not sentinels:
            raise ValueError("canary prober needs at least one sentinel")
        self.fleet = fleet
        self.sentinels = list(sentinels)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.rtol = float(rtol)
        self.atol = float(atol)
        self.eject = eject
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._cursor = 0
        self.probes = 0
        self.failures = 0
        self.detected: list[dict] = []
        reg = get_registry()
        self._obs_probes = reg.counter(
            "deepgo_fleet_canary_probes_total",
            "sentinel integrity probes submitted to fleet replicas")
        self._obs_failures = reg.counter(
            "deepgo_fleet_canary_failures_total",
            "canary probes answered wrong (replica ejected)")

    # -- one probe round -----------------------------------------------------

    def probe_once(self) -> int:
        """Probe every current replica once; returns how many probes
        FAILED this round. Public so tests and the campaign's final
        sweep can force a deterministic round."""
        failed = 0
        for idx, engine in self.fleet.probe_targets():
            s = self.sentinels[self._cursor % len(self.sentinels)]
            self._cursor += 1
            self.probes += 1
            self._obs_probes.inc(fleet=self.fleet.name, replica=str(idx))
            try:
                f = engine.submit(s["packed"], s["player"], s["rank"],
                                  timeout_s=self.timeout_s)
                got = np.asarray(f.result(timeout=self.timeout_s))
            except Exception:  # noqa: BLE001 — availability, not integrity
                continue
            if np.allclose(got, s["expected"], rtol=self.rtol,
                           atol=self.atol, equal_nan=True):
                continue
            failed += 1
            self.failures += 1
            self._obs_failures.inc(fleet=self.fleet.name,
                                   replica=str(idx))
            self.detected.append({"replica": idx, "digest": s["digest"],
                                  "t": self._clock()})
            if self.eject:
                try:
                    self.fleet.eject_replica(idx, reason="canary")
                except Exception:  # noqa: BLE001 — already respawning
                    pass
        return failed

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "CanaryProber":
        if self._thread is not None:
            raise RuntimeError("prober already started")
        self._thread = threading.Thread(
            target=self._run, name=f"canary-{self.fleet.name}",
            daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — a closing fleet mid-round
                if self._stop.is_set():
                    return

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def report(self) -> dict:
        return {"probes": self.probes, "failures": self.failures,
                "detected": [{"replica": d["replica"],
                              "digest": d["digest"]}
                             for d in self.detected]}
