"""Deterministic chaos campaigns for the serving fleet.

A chaos campaign replays a captured (or synthetic) workload trace
against a live fleet while a *scenario* — a declarative timeline of
faults — browns out, kills, and corrupts replicas mid-trace, then
grades the run against SLO burn objectives and integrity invariants:
no lost futures, no wrong answers returned to callers, the
latency-critical tier's budget holds. The defenses it validates
(request hedging, latency-outlier ejection, canary integrity probes)
live in serving/fleet.py; this package owns the attack and the grade.

  scenario.py  FaultEvent / Scenario (JSON round-trip) and the
               ScenarioScheduler thread that opens and closes fault
               windows on the timeline via utils/faults add/remove
  canary.py    CanaryProber — sentinel positions with known-good
               answers probed against every replica; a wrong answer
               ejects the replica through FleetRouter.eject_replica
  campaign.py  CampaignRunner — ground truth, trace replay, grading,
               and the JSON campaign report

Operator surfaces: ``cli chaos run|report`` and ``bench.py --mode
chaos`` (the hedging+ejection ON-vs-OFF A/B gate). docs/robustness.md
"Chaos campaigns" specifies the scenario format and the grade.
"""

from .campaign import (CampaignConfig, CampaignRunner,
                       acceptance_scenario, brownout_scenario,
                       defended_config, grade_report, log_prob_integrity)
from .canary import CanaryProber, make_sentinels
from .scenario import EVENT_KINDS, FaultEvent, Scenario, ScenarioScheduler

__all__ = [
    "CampaignConfig",
    "CampaignRunner",
    "CanaryProber",
    "EVENT_KINDS",
    "FaultEvent",
    "Scenario",
    "ScenarioScheduler",
    "acceptance_scenario",
    "brownout_scenario",
    "defended_config",
    "grade_report",
    "log_prob_integrity",
    "make_sentinels",
]
