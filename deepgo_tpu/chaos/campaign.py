"""The campaign runner: trace replay under a fault schedule, graded.

A campaign answers one question with a JSON report: *under this
scenario, did the fleet lose headroom or did it lose answers?* The
runner:

  1. computes ground truth — every unique position in the trace is
     evaluated through the fleet BEFORE chaos starts (this also seeds
     the router's latency windows, so hedge delays are p99-derived
     from the first faulted request, not cold floors);
  2. builds canary sentinels from that ground truth and starts the
     ``CanaryProber`` and the ``ScenarioScheduler``;
  3. replays the trace open-loop (serving/replay.WorkloadReplayer),
     checking every "ok" answer against ground truth as it resolves;
  4. grades: integrity invariants (zero lost futures, zero wrong
     answers returned to callers, corrupt replicas canary-detected)
     AND the latency objective (obs/slo.HistogramLatencyObjective over
     ``deepgo_serving_request_seconds`` for the fleet's interactive
     tier, sampled as a before/after delta so the process-cumulative
     registry never bleeds one arm — or one earlier campaign — into
     the next).

The grade's shape is the robustness contract in docs/robustness.md:
a brownout mid-trace may cost headroom (the SLO side, defenses earn
it back) but must never cost an answer (the integrity side, always).
"""

from __future__ import annotations

import dataclasses
import json
import time
from dataclasses import dataclass

import numpy as np

from ..obs import workload as workload_mod
from ..obs.slo import HistogramLatencyObjective
from ..serving.fleet import FleetConfig
from ..serving.replay import WorkloadReplayer
from ..utils.atomicio import atomic_write
from .canary import CanaryProber, make_sentinels
from .scenario import FaultEvent, Scenario, ScenarioScheduler


@dataclass(frozen=True)
class CampaignConfig:
    """Grading and probe knobs; defaults fit a CPU smoke fleet.

    ``slo_threshold_s``/``slo_target`` define the interactive-tier
    objective ("target of requests complete within threshold").
    ``ground_truth_tier`` is the tier ground-truth evaluation submits
    under — interactive by default, ON PURPOSE: those pre-chaos
    completions fill the router's interactive latency window so the
    first hedge delay is measured, not a floor guess."""

    slo_threshold_s: float = 0.15
    slo_target: float = 0.9
    slo_tier: str = "interactive"
    canary: bool = True
    canary_interval_s: float = 0.2
    canary_timeout_s: float = 2.0
    sentinels: int = 4
    answer_rtol: float = 1e-4
    answer_atol: float = 1e-5
    request_timeout_s: float = 5.0
    collect_timeout_s: float = 15.0
    speed: float = 1.0
    ground_truth_tier: str = "interactive"
    saturate_tier: str = "batch"


def log_prob_integrity(row) -> bool:
    """Fleet-level integrity predicate for log-probability outputs: a
    real row is never positive (log_softmax), while the injected
    corruption (``1 - out``) flips it overwhelmingly positive. Cheap
    enough to run on every response."""
    arr = np.atleast_1d(np.asarray(row))
    return bool(np.max(arr) <= 1e-3)


def defended_config(base: FleetConfig | None = None,
                    integrity_check=log_prob_integrity) -> FleetConfig:
    """The gray-failure defense posture over ``base``: interactive-tier
    hedging (generous cap — campaigns WANT the hedge budget), straggler
    ejection tuned to catch a brownout within a short trace, and the
    per-response integrity guard."""
    base = base or FleetConfig()
    return dataclasses.replace(
        base, hedge_tiers=("interactive",), hedge_min_delay_s=0.03,
        hedge_max_frac=0.5, eject_stragglers=True, eject_min_samples=8,
        eject_consecutive=2, eject_factor=3.0,
        integrity_check=integrity_check)


def brownout_scenario(span_s: float, seed: int = 0,
                      brownout_ms: int = 200, replica: int = 0
                      ) -> Scenario:
    """The A/B gate's attack: one replica brownouts for ~85% of the
    trace. Hedging + ejection must hold the interactive SLO; without
    them the round-robin tiebreak keeps feeding the straggler."""
    return Scenario(name="brownout", seed=seed, events=(
        FaultEvent(at_s=0.06 * span_s, kind="slow", replica=replica,
                   duration_s=0.88 * span_s, arg=brownout_ms),))


def acceptance_scenario(span_s: float, seed: int = 0,
                        brownout_ms: int = 200,
                        corrupt_batches: int = 40) -> Scenario:
    """The full campaign: replica 0 brownouts then dies mid-window
    (its respawn re-enters the open window — a bad host back in
    rotation), while replica 1 silently corrupts until the canary
    catches it. The integrity invariants must hold throughout."""
    return Scenario(name="kill-brownout-corrupt", seed=seed, events=(
        FaultEvent(at_s=0.10 * span_s, kind="slow", replica=0,
                   duration_s=0.75 * span_s, arg=brownout_ms),
        FaultEvent(at_s=0.25 * span_s, kind="corrupt", replica=1,
                   duration_s=0.35 * span_s, arg=corrupt_batches),
        FaultEvent(at_s=0.45 * span_s, kind="kill", replica=0),))


def grade_report(report: dict) -> dict:
    """The verdict, derived from a report's measurements alone (so
    ``cli chaos report`` can re-grade a stored report file). Integrity
    failures are absolute; the SLO verdict is the defense A/B's axis."""
    reasons: list[str] = []
    counts = report.get("answers", {})
    if counts.get("lost", 0) > 0:
        reasons.append(f"{counts['lost']} future(s) lost — a caller "
                       "hung with no verdict")
    if counts.get("wrong", 0) > 0:
        reasons.append(f"{counts['wrong']} wrong answer(s) returned "
                       "to callers")
    slo = report.get("slo", {})
    if not slo.get("ok", True):
        reasons.append(
            f"interactive SLO missed: {slo.get('good_frac')} within "
            f"{slo.get('threshold_s')}s < target {slo.get('target')}")
    canary = report.get("canary")
    if report.get("expects_corruption") and canary is not None:
        if not canary.get("detected"):
            reasons.append("corruption injected but never "
                           "canary-detected")
    cache = report.get("cache")
    if cache is not None and cache.get("stale_hits", 0) > 0:
        reasons.append(f"{cache['stale_hits']} stale cache hit(s) — an "
                       "answer served from a pre-reload generation")
    for r in report.get("reloads", ()):
        if not r.get("ok"):
            reasons.append(f"mid-trace reload failed: {r.get('error')}")
    return {"pass": not reasons, "reasons": reasons}


class CampaignRunner:
    """One fleet, one trace, one scenario, one graded report.

    ``fleet`` is a live FleetRouter (the caller owns its lifecycle —
    the runner never closes it); ``trace`` is replay items (``{t,
    packed, player, rank, tier}``) from serving/replay.load_trace or
    build_synthetic_requests."""

    def __init__(self, fleet, trace: list[dict], scenario: Scenario,
                 config: CampaignConfig | None = None,
                 reload_params=None):
        if not trace:
            raise ValueError("empty trace: nothing to campaign against")
        self.fleet = fleet
        self.trace = trace
        self.scenario = scenario
        self.config = config or CampaignConfig()
        # the params tree a scheduled ``reload`` event rolls through the
        # fleet mid-trace (a cache-armed campaign proves zero stale hits
        # across the invalidation); None = reload events are no-ops
        self._reload_params = reload_params
        self._reload_results: list[dict] = []

    # -- ground truth --------------------------------------------------------

    def _digest(self, item: dict) -> str:
        return workload_mod.exact_digest(
            item["packed"], item["player"], item["rank"])

    def ground_truth(self) -> dict:
        """digest -> known-good answer, evaluated through the healthy
        fleet. Must run before the scheduler starts — ground truth from
        a corrupt fleet would bless the corruption."""
        cfg = self.config
        expected: dict = {}
        pending: list[tuple[str, object]] = []
        for item in self.trace:
            digest = self._digest(item)
            if digest in expected:
                continue
            expected[digest] = None
            f = self.fleet.submit(item["packed"], item["player"],
                                  item["rank"],
                                  tier=cfg.ground_truth_tier,
                                  timeout_s=cfg.request_timeout_s)
            pending.append((digest, f))
        for digest, f in pending:
            expected[digest] = np.asarray(
                f.result(timeout=cfg.collect_timeout_s))
        return expected

    # -- the campaign --------------------------------------------------------

    def run(self, report_path: str | None = None) -> dict:
        cfg = self.config
        expected = self.ground_truth()
        items = [dict(it, digest=self._digest(it)) for it in self.trace]

        wrong: list[dict] = []

        def on_result(item, outcome, value, exc):
            if outcome != "ok":
                return
            want = expected.get(item["digest"])
            if want is None:
                return
            if not np.allclose(np.asarray(value), want,
                               rtol=cfg.answer_rtol,
                               atol=cfg.answer_atol, equal_nan=True):
                wrong.append({"digest": item["digest"],
                              "tier": item.get("tier")})

        objective = HistogramLatencyObjective(
            "chaos_interactive_latency", "deepgo_serving_request_seconds",
            cfg.slo_threshold_s, target=cfg.slo_target,
            engine=self.fleet.name, tier=cfg.slo_tier)
        good0, total0 = objective.sample()
        counter_keys = ("failovers", "respawns", "poisoned", "hedges",
                        "hedge_wins", "ejections", "integrity_failures",
                        "reloads")
        h0 = self.fleet.health()
        counters0 = {k: h0.get(k, 0) for k in counter_keys}
        cache0 = h0.get("cache") or {}

        prober = None
        if cfg.canary:
            sentinels = make_sentinels(items, expected,
                                       limit=cfg.sentinels)
            if sentinels:
                prober = CanaryProber(
                    self.fleet, sentinels,
                    interval_s=cfg.canary_interval_s,
                    timeout_s=cfg.canary_timeout_s,
                    rtol=cfg.answer_rtol, atol=cfg.answer_atol)

        def submit_burst(n: int) -> None:
            # queue pressure only: junk load on the non-critical tier,
            # futures deliberately dropped (they resolve server-side)
            for i in range(n):
                item = items[i % len(items)]
                try:
                    self.fleet.submit(item["packed"], item["player"],
                                      item["rank"],
                                      tier=cfg.saturate_tier,
                                      timeout_s=cfg.request_timeout_s)
                except Exception:  # noqa: BLE001 — shed IS saturation
                    pass

        def reload_params() -> None:
            t0 = time.time()
            try:
                out = self.fleet.reload(self._reload_params)
                self._reload_results.append(
                    {"ok": True, "replicas": out["replicas"],
                     "seconds": round(time.time() - t0, 4)})
            except Exception as e:  # noqa: BLE001 — reported in the grade
                self._reload_results.append({"ok": False,
                                             "error": repr(e)})

        scheduler = ScenarioScheduler(
            self.scenario, fleet_name=self.fleet.name,
            submit_burst=submit_burst,
            reload_params=(reload_params if self._reload_params is not None
                           else None))
        replayer = WorkloadReplayer(
            self.fleet, items, speed=cfg.speed,
            timeout_s=cfg.request_timeout_s,
            collect_timeout_s=cfg.collect_timeout_s,
            on_result=on_result)
        t_start = time.time()
        if prober is not None:
            prober.start()
        scheduler.start()
        try:
            replay_report = replayer.run()
        finally:
            scheduler.stop()
            if prober is not None:
                prober.stop()
        # a fired reload runs past the timeline on its own thread (it
        # blocks on the rolling drain); the report must not snapshot
        # counters mid-roll
        fired_reloads = sum(1 for e in scheduler.executed
                            if e["kind"] == "reload")
        deadline = time.time() + cfg.collect_timeout_s
        while (len(self._reload_results) < fired_reloads
               and time.time() < deadline):
            time.sleep(0.01)

        good1, total1 = objective.sample()
        d_total = total1 - total0
        d_good = good1 - good0
        good_frac = (d_good / d_total) if d_total > 0 else None
        bad_frac = (1.0 - good_frac) if good_frac is not None else 0.0
        outcomes = replay_report.get("outcomes", {})
        health = self.fleet.health()
        counters = {k: health.get(k, 0) - counters0[k]
                    for k in counter_keys}
        cache1 = health.get("cache")
        cache_block = None
        if cache1 is not None:
            # campaign-scoped deltas: the integrity claim is about THIS
            # run — stale_hits must not move across the mid-trace reload
            cache_block = {
                "keying": cache1.get("keying"),
                "hits": cache1.get("hits", 0) - cache0.get("hits", 0),
                "misses": (cache1.get("misses", 0)
                           - cache0.get("misses", 0)),
                "coalesced": (cache1.get("coalesced", 0)
                              - cache0.get("coalesced", 0)),
                "invalidations": (cache1.get("invalidations", 0)
                                  - cache0.get("invalidations", 0)),
                "stale_hits": (cache1.get("stale_hits", 0)
                               - cache0.get("stale_hits", 0)),
                "entries": cache1.get("entries"),
                "generation": cache1.get("generation"),
            }
        report = {
            "scenario": self.scenario.to_dict(),
            "executed": list(scheduler.executed),
            "started_unix": round(t_start, 3),
            "fleet": {"name": self.fleet.name,
                      "replicas": self.fleet.replicas},
            "defenses": {
                "hedge_tiers": list(self.fleet.config.hedge_tiers),
                "eject_stragglers":
                    bool(self.fleet.config.eject_stragglers),
                "integrity_check":
                    self.fleet.config.integrity_check is not None,
                "canary": prober is not None,
            },
            "replay": replay_report,
            "answers": {
                "checked": int(outcomes.get("ok", 0)),
                "wrong": len(wrong),
                "wrong_detail": wrong[:16],
                "lost": int(outcomes.get("lost", 0)),
            },
            "slo": {
                "tier": cfg.slo_tier,
                "threshold_s": cfg.slo_threshold_s,
                "target": cfg.slo_target,
                "requests": d_total,
                "good_frac": (round(good_frac, 4)
                              if good_frac is not None else None),
                "bad_frac": round(bad_frac, 4),
                "burn": round(bad_frac / max(1.0 - cfg.slo_target, 1e-9),
                              3),
                "ok": good_frac is not None
                      and good_frac >= cfg.slo_target,
            },
            "canary": prober.report() if prober is not None else None,
            "counters": counters,
            "cache": cache_block,
            "reloads": list(self._reload_results),
            "expects_corruption": any(e.kind == "corrupt"
                                      for e in self.scenario.events),
        }
        report["grade"] = grade_report(report)
        if report_path is not None:
            with atomic_write(report_path, mode="w") as fh:
                json.dump(report, fh, indent=2, sort_keys=True)
                fh.write("\n")
        return report
