"""Declarative fault timelines, executed against the live fault plan.

A ``Scenario`` is a named, seeded list of ``FaultEvent``s — *what
breaks, where, when, for how long* — serializable to JSON so a
campaign is reproducible from its report alone. The
``ScenarioScheduler`` is the small thread that walks the timeline and
mutates the process-wide fault plan (utils/faults ``add``/``remove``)
at the scheduled moments:

  kill      arm ``serving_dispatch.<replica>:fail@1`` — the next
            coalescing window of that replica's dispatcher dies (the
            supervisor-restart / fleet-respawn path)
  slow      open ``serving_slow.<replica>:slow@MS`` at ``at_s`` and
            close it ``duration_s`` later — a brownout window: the
            replica stays "healthy" while every batch it serves eats
            MS milliseconds
  corrupt   open ``serving_corrupt.<replica>:corrupt@N`` — the next N
            batches return silently wrong output (the canary-probe
            prey); closed early when ``duration_s`` > 0
  saturate  submit ``arg`` junk batch-tier requests in one burst
            through the campaign's ``submit_burst`` hook — queue
            pressure, not replica damage
  reload    fire the campaign's ``reload_params`` hook — a rolling
            weight reload mid-trace (the position cache's invalidation
            path). Spawned on its own thread: a reload blocks on the
            per-replica drain, and the timeline must keep walking
  wal       open ``session_wal:transient@N`` — the next N session-store
            WAL appends fail transiently (the ack barrier's retry
            path); closed early when ``duration_s`` > 0. Process-wide:
            the session store is not a replica
  reply     open ``session_reply:transient@N`` — the next N engine-reply
            submits fail transiently (the deadline-tier escalation
            path); closed early when ``duration_s`` > 0

Events target replicas by index; the scheduler maps an index to the
engine name (``<fleet>-<idx>`` by convention, overridable) because the
fault plan is keyed by the *engine's* name — which survives respawn,
so a recycled replica re-enters any still-open fault window, exactly
like a bad host re-entering rotation.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..analysis.lockcheck import make_lock
from ..obs.spans import span
from ..utils import faults

EVENT_KINDS = ("kill", "slow", "corrupt", "saturate", "reload",
               "wal", "reply")

# wal/reply target the session layer's process-wide fault sites, not a
# replica-indexed engine site
_SESSION_SITE_OF = {"wal": "session_wal", "reply": "session_reply"}


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault. ``arg`` is kind-specific: slow = delay in
    milliseconds per batch, corrupt = number of corrupted batches,
    saturate = burst size; kill ignores it. ``duration_s`` bounds the
    open window for slow (required) and corrupt (optional)."""

    at_s: float
    kind: str
    replica: int = 0
    duration_s: float = 0.0
    arg: int = 1

    def __post_init__(self):
        if self.kind not in EVENT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}: expected one of "
                f"{EVENT_KINDS}")
        if self.at_s < 0:
            raise ValueError(f"at_s must be >= 0, got {self.at_s}")
        if self.kind != "saturate" and self.replica < 0:
            raise ValueError(f"replica must be >= 0, got {self.replica}")
        if self.kind == "slow" and self.duration_s <= 0:
            raise ValueError("slow events need duration_s > 0: an "
                             "unbounded brownout is a config bug, not "
                             "a scenario")
        if (self.kind in ("slow", "corrupt", "saturate", "wal", "reply")
                and self.arg < 1):
            raise ValueError(
                f"{self.kind} events need arg >= 1, got {self.arg}")

    def to_dict(self) -> dict:
        return {"at_s": self.at_s, "kind": self.kind,
                "replica": self.replica, "duration_s": self.duration_s,
                "arg": self.arg}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultEvent":
        return cls(at_s=float(d["at_s"]), kind=str(d["kind"]),
                   replica=int(d.get("replica", 0)),
                   duration_s=float(d.get("duration_s", 0.0)),
                   arg=int(d.get("arg", 1)))


@dataclass(frozen=True)
class Scenario:
    """A named fault timeline plus the seed that makes the whole
    campaign (trace, schedule, grading) reproducible."""

    name: str
    events: tuple = ()
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "events", tuple(self.events))

    def to_dict(self) -> dict:
        return {"name": self.name, "seed": self.seed,
                "events": [e.to_dict() for e in self.events]}

    @classmethod
    def from_dict(cls, d: dict) -> "Scenario":
        return cls(name=str(d["name"]), seed=int(d.get("seed", 0)),
                   events=tuple(FaultEvent.from_dict(e)
                                for e in d.get("events", ())))

    def span_s(self) -> float:
        """When the last fault window closes, relative to t=0."""
        return max((e.at_s + e.duration_s for e in self.events),
                   default=0.0)


class ScenarioScheduler:
    """Execute a scenario's timeline against the active fault plan.

    One daemon thread sleeps to each action's offset and fires it;
    ``start()`` stamps t=0. Actions are derived up front: each slow
    (and bounded corrupt) event contributes an *open* and a *close*
    action, so stopping the scheduler early (or a crashed campaign)
    can still sweep every window shut via ``stop()`` — chaos must
    never outlive its campaign. ``executed`` records what actually
    fired, with offsets, for the campaign report."""

    def __init__(self, scenario: Scenario, fleet_name: str = "fleet",
                 engine_name_of=None, submit_burst=None,
                 reload_params=None, clock=time.monotonic):
        self.scenario = scenario
        self._engine_name_of = (engine_name_of
                                or (lambda i: f"{fleet_name}-{i}"))
        self._submit_burst = submit_burst
        self._reload_params = reload_params
        self._clock = clock
        self._stop = threading.Event()
        self._lock = make_lock("chaos.scheduler")
        self._thread: threading.Thread | None = None
        self._opened: list[tuple[str, str]] = []  # (site, kind) to sweep
        self.executed: list[dict] = []
        self._actions = self._expand()

    # -- timeline expansion --------------------------------------------------

    def _expand(self) -> list[tuple]:
        acts: list[tuple] = []
        for ev in self.scenario.events:
            name = self._engine_name_of(ev.replica)
            if ev.kind == "kill":
                site = f"serving_dispatch.{name}"
                acts.append((ev.at_s, ev, "open",
                             lambda s=site: faults.add(f"{s}:fail@1")))
            elif ev.kind == "slow":
                site = f"serving_slow.{name}"
                acts.append((ev.at_s, ev, "open",
                             lambda s=site, a=ev.arg:
                             self._open(s, "slow", a)))
                acts.append((ev.at_s + ev.duration_s, ev, "close",
                             lambda s=site: self._close(s, "slow")))
            elif ev.kind == "corrupt":
                site = f"serving_corrupt.{name}"
                acts.append((ev.at_s, ev, "open",
                             lambda s=site, a=ev.arg:
                             self._open(s, "corrupt", a)))
                if ev.duration_s > 0:
                    acts.append((ev.at_s + ev.duration_s, ev, "close",
                                 lambda s=site:
                                 self._close(s, "corrupt")))
            elif ev.kind in _SESSION_SITE_OF:
                site = _SESSION_SITE_OF[ev.kind]
                acts.append((ev.at_s, ev, "open",
                             lambda s=site, a=ev.arg:
                             self._open(s, "transient", a)))
                if ev.duration_s > 0:
                    acts.append((ev.at_s + ev.duration_s, ev, "close",
                                 lambda s=site:
                                 self._close(s, "transient")))
            elif ev.kind == "saturate":
                acts.append((ev.at_s, ev, "open",
                             lambda n=ev.arg: self._saturate(n)))
            elif ev.kind == "reload":
                acts.append((ev.at_s, ev, "open", self._reload))
        acts.sort(key=lambda a: a[0])
        return acts

    def _open(self, site: str, kind: str, arg: int) -> None:
        faults.add(f"{site}:{kind}@{arg}")
        with self._lock:
            self._opened.append((site, kind))

    def _close(self, site: str, kind: str) -> None:
        faults.remove(site, kind)
        with self._lock:
            self._opened = [(s, k) for s, k in self._opened
                            if (s, k) != (site, kind)]

    def _saturate(self, n: int) -> None:
        if self._submit_burst is not None:
            self._submit_burst(n)

    def _reload(self) -> None:
        # a rolling reload blocks on every replica's drain — fired on
        # its own thread so the fault timeline keeps walking behind it
        if self._reload_params is not None:
            threading.Thread(target=self._reload_params,
                             name=f"chaos-reload-{self.scenario.name}",
                             daemon=True).start()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ScenarioScheduler":
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._thread = threading.Thread(
            target=self._run, name=f"chaos-{self.scenario.name}",
            daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        t0 = self._clock()
        for at_s, ev, phase, fn in self._actions:
            while not self._stop.is_set():
                lead = at_s - (self._clock() - t0)
                if lead <= 0:
                    break
                self._stop.wait(min(lead, 0.05))
            if self._stop.is_set():
                return
            with span("chaos_event", kind=ev.kind, phase=phase,
                      replica=ev.replica, scenario=self.scenario.name):
                fn()
            self.executed.append({
                "t_s": round(self._clock() - t0, 4), "kind": ev.kind,
                "phase": phase, "replica": ev.replica, "arg": ev.arg})

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def stop(self, timeout: float = 5.0) -> None:
        """Halt the timeline and sweep every still-open fault window
        shut. Idempotent; always safe to call from ``finally``."""
        self._stop.set()
        self.join(timeout=timeout)
        with self._lock:
            opened, self._opened = self._opened, []
        for site, kind in opened:
            faults.remove(site, kind)
