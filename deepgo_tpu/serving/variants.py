"""Named serving variants: f32 | int8 | sym | int8+sym, hot-swappable.

A *variant* is a (forward program, params preparation) pair sharing the
engine-facing signature ``forward(params, packed, player, rank) ->
(B, 361)``, so every layer above — bucket ladder, engine, supervisor,
fleet router, agents — runs it unchanged:

  f32        the reference ``make_log_prob_fn`` forward (identity prep)
  int8       per-output-channel symmetric int8 weights with power-of-two
             scales, dequant folded into the conv epilogue
             (models/quant.py) — prep quantizes the f32 pytree
  sym        the fused 8-fold dihedral ensemble over f32 weights
             (``make_fused_sym_policy_fn``): one jitted program stacks
             all eight views on the batch axis
  int8+sym   the ensemble over int8 weights — both savings compose

Variants are assigned PER REPLICA (``fleet_policy_engine(variants=...)``
round-robins the list across replicas), so one fleet can serve a
quantized champion next to the full-precision one and the arena /
``cli serve`` can A/B them live. Hot reload rides the existing
``fleet.reload`` path: the router keeps BASE f32 params as the source
of truth and each replica's engine carries a ``prepare_params`` hook the
router applies during reloads and respawns — an int8 replica re-
quantizes the new checkpoint in place, with zero dropped futures and
zero recompiles (the quantized pytree's shapes/dtypes never change).

Lossy variants are gated: :func:`verify_variant` runs the tolerance
harness (models/quant.check_tolerance — per-rung top-1 agreement +
max-abs log-prob drift vs the exact reference of the same program
shape) and a failure raises the typed ``VariantToleranceError`` — the
variant REFUSES to serve rather than silently costing dan rank. The
arena strength gate (``match.standard_gate`` via ``arena --variant-a/
--variant-b``) and the bench regression gate (``bench --mode serving
--variant``) are the other two legs of the triple gate
(docs/serving.md "Serving variants").
"""

from __future__ import annotations

import dataclasses

from .buckets import DEFAULT_BUCKETS

VARIANTS = ("f32", "int8", "sym", "int8+sym")

# models/quant (and with it jax) loads lazily: `import deepgo_tpu.serving`
# must stay jax-free — the fleet/engine tests drive duck-typed replicas
# with no device stack at all
_LAZY = ("ToleranceConfig", "VariantToleranceError")


def __getattr__(name: str):
    if name in _LAZY:
        from ..models import quant

        return getattr(quant, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# gauge: which variants this process is serving, how many replicas each
_g_serving = None


def _note_serving(variant: str, replicas: int) -> None:
    global _g_serving
    if _g_serving is None:
        from ..obs import get_registry

        _g_serving = get_registry().gauge(
            "deepgo_quant_variants_serving",
            "replicas currently built per serving variant")
    _g_serving.set(replicas, variant=variant)


def variant_fn_name(variant: str) -> str:
    """The cost-ledger entrypoint name for one variant's forward — one
    definition so bench joins and ``cli cost`` rows can never drift."""
    return {"f32": "policy_forward", "int8": "quant_forward",
            "sym": "fused_sym_forward",
            "int8+sym": "fused_sym_int8_forward"}[variant]


@dataclasses.dataclass(frozen=True)
class VariantSpec:
    """One variant, resolved for a model config: the jitted forward (one
    per process per (cfg, variant) — every replica of a variant shares
    its warm jit cache), the base->serving params preparation, and the
    reference pair the tolerance harness compares against (None for
    exact variants: nothing to gate)."""

    name: str
    forward: object
    prepare: object                  # base f32 params -> serving params
    reference: object | None         # exact forward of the SAME shape
    reference_prepare: object | None

    @property
    def lossy(self) -> bool:
        return self.reference is not None


# one jitted program per (cfg, variant, expand_backend) per process —
# replicas, respawns, and reloads all reuse the same warm jit cache
_SPECS: dict[tuple, VariantSpec] = {}


def variant_spec(cfg, variant: str,
                 expand_backend: str = "xla") -> VariantSpec:
    """Resolve (and memoize) one variant for a model config."""
    if variant not in VARIANTS:
        raise ValueError(f"unknown variant {variant!r}; valid: {VARIANTS}")
    key = (cfg, variant, expand_backend)
    spec = _SPECS.get(key)
    if spec is not None:
        return spec
    from ..models.quant import (make_fused_sym_policy_fn,
                                make_quant_log_prob_fn, quantize_params)
    from ..models.serving import make_log_prob_fn

    ident = lambda p: p  # noqa: E731
    if variant == "f32":
        spec = VariantSpec(variant, make_log_prob_fn(cfg, expand_backend),
                           ident, None, None)
    elif variant == "int8":
        spec = VariantSpec(variant, make_quant_log_prob_fn(cfg,
                                                           expand_backend),
                           quantize_params,
                           make_log_prob_fn(cfg, expand_backend), ident)
    elif variant == "sym":
        spec = VariantSpec(variant,
                           make_fused_sym_policy_fn(
                               cfg, expand_backend=expand_backend),
                           ident, None, None)
    else:  # int8+sym
        spec = VariantSpec(variant,
                           make_fused_sym_policy_fn(
                               cfg, quant=True,
                               expand_backend=expand_backend),
                           quantize_params,
                           make_fused_sym_policy_fn(
                               cfg, expand_backend=expand_backend), ident)
    _SPECS[key] = spec
    return spec


def verify_variant(cfg, params, variant: str,
                   buckets=DEFAULT_BUCKETS,
                   tolerance=None,
                   expand_backend: str = "xla", sample=None) -> dict:
    """The serve gate for one variant over one checkpoint: exact
    variants pass trivially (``{"verdict": "pass", "exact": True}``);
    lossy ones run the tolerance harness against their exact reference
    and RAISE the typed ``VariantToleranceError`` below the floors —
    callers never get a serving handle for a variant that failed.
    ``sample(n)`` supplies measurement boards (pass real positions for
    production gating — see models/quant.tolerance_report)."""
    from ..models.quant import check_tolerance

    spec = variant_spec(cfg, variant, expand_backend)
    if not spec.lossy:
        return {"variant": variant, "verdict": "pass", "exact": True}
    return check_tolerance(
        spec.reference, spec.reference_prepare(params),
        spec.forward, spec.prepare(params),
        buckets=buckets, config=tolerance, variant=variant, sample=sample)
