"""SupervisedEngine: the resilience layer over the micro-batching engine.

PR 1 made *training* treat failure as the steady state (atomic
checkpoints, DEEPGO_FAULTS, auto-resume); this module does the same for
*serving*. A bare ``InferenceEngine`` has three production gaps:

  1. a dispatcher-thread death is permanent — ``_check_alive`` re-raises
     forever, so one crash takes the engine down for every later caller;
  2. a single poisoned request fails every coalesced neighbor that
     happened to ride its dispatch;
  3. an overloaded queue makes no admission decision beyond blocking or
     ``EngineBusy`` — requests that can no longer meet their deadline
     still consume a dispatch slot, then time out anyway.

``SupervisedEngine`` wraps an engine *factory* (not an engine) and closes
all three:

  restart   dispatcher death is detected (on a failed future or a failed
            submit), the corpse is torn down, and a fresh engine is built
            after a bounded-exponential full-jitter backoff
            (resilience.full_jitter_delay). In-flight requests whose
            deadline is still live are REPLAYED on the new engine — the
            forward is pure, so replay is idempotent and submitters ride
            through the restart untouched, with bit-identical results.
  poison    a failed coalesced dispatch (engine.BatchDispatchError) is
            bisected through the engine's solo lane: every member retries
            strictly alone, so a bad row fails alone while its neighbors
            succeed. A request that keeps failing alone
            (``poison_threshold`` lone failures) is declared poison: its
            future gets a typed PoisonedRequest and its inputs are dumped
            atomically to ``quarantine_dir`` (training's bad_batch
            discipline, applied to serving).
  breaker   every dispatch failure / engine death feeds a closed/open/
            half-open circuit breaker (resilience.CircuitBreaker). A
            persistently failing device flips it open and submit() sheds
            instantly with CircuitOpen instead of timing every caller
            out; one probe per ``breaker_reset_s`` closes it again.
  shedding  deadline-aware admission control: when the estimated queue
            wait (rolling p50 dispatch latency x pending dispatch
            windows) already exceeds a request's deadline, submit()
            rejects with EngineOverloaded up front — the caller learns in
            microseconds what the queue would have told it at its
            deadline.

The contract the chaos tests assert: every submitted future RESOLVES —
success, typed shed, typed poison, or typed restart-budget exhaustion —
never strands. Clock, sleep, and RNG are injectable so every backoff
bound and breaker transition is testable without wall time.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import random
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..analysis.lockcheck import make_lock
from ..obs import get_registry
from ..obs.sentinel import flight_dump
from .engine import (BatchDispatchError, EngineBusy, EngineClosed,
                     EngineError, InferenceEngine)
from .resilience import (CircuitBreaker, CircuitOpen, EngineOverloaded,
                         PoisonedRequest, RestartsExhausted,
                         full_jitter_delay)


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Knobs for one SupervisedEngine.

    ``max_restarts`` bounds CONSECUTIVE rebuilds (any served request
    resets the count): a permanently broken device must eventually fail
    loudly, not restart forever. ``poison_threshold`` is how many times a
    request must fail ALONE before it is declared poison rather than the
    victim of transient weather (2+ keeps a one-shot transient from
    condemning an innocent request)."""

    max_restarts: int = 8
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    breaker_failures: int = 5
    breaker_reset_s: float = 30.0
    poison_threshold: int = 2
    admission_control: bool = True
    warm_on_restart: bool = False
    quarantine_dir: str | None = None


class _SupRequest:
    __slots__ = ("packed", "player", "rank", "deadline", "future",
                 "solo", "solo_failures", "trace", "workload")

    def __init__(self, packed, player, rank, deadline, trace=None,
                 workload=None):
        self.packed = packed
        self.player = player
        self.rank = rank
        self.deadline = deadline          # absolute, supervisor clock
        self.future: Future = Future()
        self.solo = False                 # isolation-lane retry
        self.solo_failures = 0            # times it failed dispatching alone
        self.trace = trace                # TraceContext riding every retry
        self.workload = workload          # WorkloadToken, same discipline


class SupervisedEngine:
    """One engine factory, one supervisor thread, many resilient callers.

    Duck-types the InferenceEngine surface every consumer uses (submit /
    evaluate / warmup / stats / compile_cache_size / close / context
    manager), so selfplay fleets, arena agents, and the shared-engine
    registry ride it unchanged.
    """

    def __init__(self, factory, config: SupervisorConfig | None = None,
                 name: str = "supervised", metrics=None,
                 clock=time.monotonic, sleep=time.sleep, rng=None):
        """``factory() -> InferenceEngine`` builds (and rebuilds) the inner
        engine. Build the jitted forward ONCE outside the factory and
        close over it — then a restart reuses the warm jit cache and
        replayed requests never recompile (serving.supervised_policy_engine
        does exactly this)."""
        self.config = config or SupervisorConfig()
        self.name = name
        self._factory = factory
        self._metrics = metrics
        self._clock = clock
        self._sleep = sleep
        # lint: allow[determinism] backoff jitter only — replay-bearing results never depend on it; tests inject rng=
        self._rng = rng if rng is not None else random.Random()
        self._lock = make_lock(f"supervisor.{name}")
        self._breaker = CircuitBreaker(
            self.config.breaker_failures, self.config.breaker_reset_s,
            clock=clock, on_transition=self._on_breaker_transition,
            name=f"breaker.{name}")
        self._events: queue.Queue = queue.Queue()
        self._replay: list[_SupRequest] = []
        self._restarts = 0
        self._consec_restarts = 0
        self._replayed = 0
        self._shed_overload = 0
        self._shed_breaker = 0
        self._poisoned = 0
        self._quarantined: list[str] = []
        self._closing = threading.Event()
        self._failed: EngineError | None = None
        self._params_override = None      # set_params survives restarts
        # resilience aggregates on the process registry: the counters
        # /metrics serves live and health() already snapshots. Breaker
        # state renders as a gauge (0 closed / 1 half-open / 2 open) so
        # a scrape sees the transition, not just its transition count.
        reg = get_registry()
        self._obs_restarts = reg.counter(
            "deepgo_serving_restarts_total", "engine rebuilds after death")
        self._obs_shed = reg.counter(
            "deepgo_serving_shed_total",
            "requests shed at admission (reason=overload|breaker)")
        self._obs_poisoned = reg.counter(
            "deepgo_serving_poisoned_total",
            "requests declared poison after isolated failures")
        self._obs_replayed = reg.counter(
            "deepgo_serving_replayed_total",
            "in-flight requests replayed onto a fresh engine")
        self._obs_breaker = reg.gauge(
            "deepgo_serving_breaker_state",
            "circuit breaker state (0 closed, 1 half-open, 2 open)")
        self._obs_breaker.set(0, engine=name)
        self._engine = factory()
        self._thread = threading.Thread(
            target=self._supervise_loop, name=f"supervisor-{name}",
            daemon=True)
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------

    def warmup(self) -> int:
        return self._engine.warmup()

    def compile_cache_size(self) -> int | None:
        return self._engine.compile_cache_size()

    @property
    def params(self):
        """The weights the CURRENT inner engine dispatches with."""
        return self._engine.params

    def set_params(self, params) -> None:
        """Hot-swap weights through the supervision layer.

        Forwards the pointer swap to the live inner engine AND pins the
        override for every future restart: the factory closure was built
        over the original weights, so without the override a post-reload
        dispatcher death would silently resurrect the old checkpoint."""
        self._params_override = params
        self._engine.set_params(params)

    @property
    def ladder(self):
        return self._engine.ladder

    def _check_alive(self) -> None:
        if self._failed is not None:
            raise RestartsExhausted(
                f"SupervisedEngine[{self.name}] gave up: {self._failed}"
            ) from self._failed
        if self._closing.is_set():
            raise EngineClosed(f"SupervisedEngine[{self.name}] is closed")

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop supervising and shut the inner engine down.

        Same contract as InferenceEngine.close(): returns with every
        outstanding future resolved — drained results, or typed
        EngineClosed — never stranded waiters."""
        self._closing.set()
        self._events.put(("stop", None))
        self._thread.join(timeout=timeout)
        self._engine.close(drain=drain, timeout=timeout)
        # anything the loop left behind (parked replays, queued retries)
        # must not strand its waiters
        with self._lock:
            leftovers, self._replay = self._replay, []
        while True:
            try:
                kind, payload = self._events.get_nowait()
            except queue.Empty:
                break
            if kind == "retry":
                leftovers.append(payload)
        exc = EngineClosed(
            f"SupervisedEngine[{self.name}] closed with request pending")
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(exc)
        if self._metrics is not None:
            self._metrics.write("serving_supervisor_close", engine=self.name,
                                **self._health_counters())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- submission --------------------------------------------------------

    def submit(self, packed: np.ndarray, player: int, rank: int,
               timeout_s: float | None = None, block: bool = True,
               trace=None, workload=None) -> Future:
        """Queue one board; returns a Future that ALWAYS resolves.

        Outcomes: the result row (possibly after transparent engine
        restarts and replays); TimeoutError (deadline expired);
        EngineOverloaded (admission control shed it at the door);
        CircuitOpen (breaker shedding a persistently failing engine);
        PoisonedRequest (this request fails the forward on its own);
        EngineBusy (non-blocking submit, queue full). ``trace`` is the
        caller's TraceContext; the SAME id rides every restart replay
        and isolation retry (obs/tracing.py)."""
        self._check_alive()
        engine = self._engine
        if timeout_s is None:
            timeout_s = engine.config.timeout_s
        if timeout_s is not None and self.config.admission_control:
            est = self.estimated_wait_s()
            if est is not None and est > timeout_s:
                with self._lock:
                    self._shed_overload += 1
                self._obs_shed.inc(engine=self.name, reason="overload")
                raise EngineOverloaded(
                    f"SupervisedEngine[{self.name}] estimated queue wait "
                    f"{est:.3f}s exceeds the request deadline {timeout_s}s "
                    "(deadline-aware shed)")
        if not self._breaker.allow():
            with self._lock:
                self._shed_breaker += 1
            self._obs_shed.inc(engine=self.name, reason="breaker")
            raise CircuitOpen(
                f"SupervisedEngine[{self.name}] circuit breaker is "
                f"{self._breaker.state}: engine failing persistently, "
                "shedding instead of queueing")
        # trace creation sits BEHIND the door sheds: a shed raise is its
        # own answer; timelines trace requests that entered the system
        owned = None
        if trace is None:
            from ..obs import tracing

            trace = owned = tracing.start_request(engine=self.name)
        wl_owned = None
        if workload is None:
            from ..obs import workload as workload_mod

            workload = wl_owned = workload_mod.note_request(
                packed, player, rank, engine=self.name)
        deadline = None if timeout_s is None else self._clock() + timeout_s
        req = _SupRequest(np.asarray(packed), int(player), int(rank),
                          deadline, trace=trace, workload=workload)
        if owned is not None:
            req.future.add_done_callback(owned.finish_future)
        if wl_owned is not None:
            req.future.add_done_callback(wl_owned.finish_future)
        try:
            self._submit_inner(req, block=block)
        except EngineBusy:
            # the breaker may have granted THE half-open probe to this
            # submit; a request that never went out must hand it back
            self._breaker.cancel_probe()
            if owned is not None:
                owned.finish("error", error="EngineBusy")
            if wl_owned is not None:
                wl_owned.finish("shed")
            raise
        return req.future

    def evaluate(self, packed: np.ndarray, players: np.ndarray,
                 ranks: np.ndarray, timeout_s: float | None = None
                 ) -> np.ndarray:
        """Blocking convenience, same shape as InferenceEngine.evaluate."""
        futures = [self.submit(packed[i], int(players[i]), int(ranks[i]),
                               timeout_s=timeout_s)
                   for i in range(len(packed))]
        return np.stack([f.result() for f in futures])

    def estimated_wait_s(self) -> float | None:
        """Admission control's load estimate: rolling p50 FULL-window
        dispatch latency x pending dispatch windows (queue depth / top
        bucket, rounded up) — the backlog drains in max-bucket windows,
        so their cost is the right multiplier even when small
        interactive dispatches dominate the recent mix. None until the
        first dispatch has been measured."""
        engine = self._engine
        p50 = engine.window_p50_s()
        if p50 is None:
            return None
        depth = engine.queue_depth()
        windows = -(-depth // engine.ladder.max_bucket)  # ceil div
        return p50 * windows

    def _submit_inner(self, req: _SupRequest, block: bool = True) -> None:
        """Hand one request to the current inner engine.

        A dead/closing engine parks the request for post-restart replay
        instead of failing it; only EngineBusy (explicit non-blocking
        backpressure) propagates."""
        engine = self._engine
        remaining = None
        if req.deadline is not None:
            remaining = req.deadline - self._clock()
            if remaining <= 0:
                if not req.future.done():
                    req.future.set_exception(TimeoutError(
                        f"request deadline expired before dispatch in "
                        f"SupervisedEngine[{self.name}]"))
                return
        try:
            inner = engine.submit(req.packed, req.player, req.rank,
                                  timeout_s=remaining, block=block,
                                  solo=req.solo, trace=req.trace,
                                  workload=req.workload)
        except EngineBusy:
            raise
        except EngineError:
            # dispatcher dead or engine closing under us: park + wake the
            # supervisor; the caller's future resolves after the replay
            self._park(req, engine)
            return
        inner.add_done_callback(
            lambda f, eng=engine: self._on_inner_done(req, f, eng))

    def _park(self, req: _SupRequest, engine: InferenceEngine) -> None:
        with self._lock:
            self._replay.append(req)
        self._events.put(("died", engine))

    # -- completion classification ----------------------------------------

    def _on_inner_done(self, req: _SupRequest, f: Future,
                       engine: InferenceEngine) -> None:
        """Classify one inner-engine completion.

        Runs on whatever thread resolved the inner future (dispatcher,
        closer, or supervisor) — so it never blocks and never submits;
        retries and restarts are handed to the supervisor thread."""
        exc = f.exception()
        if req.future.done():
            if exc is None:
                self._breaker.record_success()
            return
        if exc is None:
            self._breaker.record_success()
            with self._lock:
                self._consec_restarts = 0
            req.future.set_result(f.result())
        elif isinstance(exc, TimeoutError):
            # the deadline expired in the queue: a final, typed outcome
            req.future.set_exception(exc)
        elif isinstance(exc, BatchDispatchError):
            self._breaker.record_failure()
            if exc.batch_size == 1:
                req.solo_failures += 1
            if req.solo_failures >= self.config.poison_threshold:
                self._declare_poison(req, exc)
            else:
                req.solo = True  # bisect: retry strictly alone
                if req.trace is not None:
                    req.trace.mark("isolated", engine=self.name,
                                   failures=req.solo_failures)
                self._events.put(("retry", req))
        else:
            # raw error = dispatcher death (or closed under the request):
            # the members are innocent, the engine is the casualty
            self._park(req, engine)

    def _declare_poison(self, req: _SupRequest, exc: BaseException) -> None:
        with self._lock:
            self._poisoned += 1
            n = self._poisoned
        self._obs_poisoned.inc(engine=self.name)
        path = self._quarantine(req, exc, n)
        if self._metrics is not None:
            self._metrics.write("serving_poison", engine=self.name,
                                error=repr(exc.__cause__ or exc), path=path)
        err = PoisonedRequest(
            f"request fails the forward on its own ({req.solo_failures} "
            f"isolated attempts) in SupervisedEngine[{self.name}]"
            + (f"; inputs quarantined at {path}" if path else ""))
        err.__cause__ = exc
        req.future.set_exception(err)

    def _quarantine(self, req: _SupRequest, exc: BaseException,
                    n: int) -> str | None:
        """Atomic postmortem dump of the poisoned inputs — training's
        bad_batch discipline applied to serving. Returns the path, or
        None when no quarantine_dir is configured (or the dump itself
        fails: the postmortem must never mask the poison verdict)."""
        if not self.config.quarantine_dir:
            return None
        from ..utils.atomicio import atomic_write

        path = os.path.join(self.config.quarantine_dir,
                            f"poison-{n:04d}.npz")
        try:
            os.makedirs(self.config.quarantine_dir, exist_ok=True)
            with atomic_write(path) as fh:
                np.savez(fh, packed=req.packed,
                         player=np.int32(req.player),
                         rank=np.int32(req.rank),
                         error=np.array(repr(exc.__cause__ or exc)))
        except OSError:
            return None
        with self._lock:
            self._quarantined.append(path)
        return path

    # -- the supervisor thread ---------------------------------------------

    def _supervise_loop(self) -> None:
        while True:
            try:
                kind, payload = self._events.get(timeout=0.05)
            except queue.Empty:
                if self._closing.is_set():
                    return
                continue
            if kind == "stop":
                return
            if kind == "retry":
                if self._failed is not None:
                    if not payload.future.done():
                        payload.future.set_exception(self._failed)
                else:
                    self._submit_inner(payload, block=True)
            elif kind == "died":
                self._handle_death(payload)

    def _handle_death(self, dead: InferenceEngine) -> None:
        if self._failed is not None or self._closing.is_set():
            self._flush_replay()
            return
        if dead is self._engine:
            self._breaker.record_failure()
            with self._lock:
                self._restarts += 1
                self._consec_restarts += 1
                attempt = self._consec_restarts
            self._obs_restarts.inc(engine=self.name)
            # ship the black box with the incident: the ring buffer holds
            # the dispatch latencies and spans that preceded the death
            # (a no-op unless obs.sentinel.configure_flight armed it)
            flight_dump("serving_restart", engine=self.name,
                        attempt=attempt, total_restarts=self._restarts)
            if attempt > self.config.max_restarts:
                self._give_up(RestartsExhausted(
                    f"SupervisedEngine[{self.name}] engine died "
                    f"{attempt} times without serving a request in "
                    f"between (max_restarts={self.config.max_restarts})"))
                return
            delay = full_jitter_delay(
                attempt - 1, self.config.backoff_base_s,
                self.config.backoff_cap_s, self._rng)
            if self._metrics is not None:
                self._metrics.write(
                    "serving_restart", engine=self.name, attempt=attempt,
                    delay_s=round(delay, 4), total_restarts=self._restarts)
            self._sleep(delay)
            # tear the corpse down WITHOUT draining: its queued requests
            # fail with EngineClosed, which the done-callbacks classify as
            # engine death and park for replay below
            try:
                dead.close(drain=False, timeout=1.0)
            except Exception:  # pragma: no cover — corpse cleanup only
                pass
            if self._closing.is_set():
                self._flush_replay()
                return
            self._engine = self._factory()
            if self._params_override is not None:
                self._engine.set_params(self._params_override)
            if self.config.warm_on_restart:
                self._engine.warmup()
        # stale death notice (engine already replaced) still flushes: late
        # parks from the old corpse's callbacks land in the same list
        self._flush_replay()

    def _flush_replay(self) -> None:
        with self._lock:
            reqs, self._replay = self._replay, []
        err = self._failed or (
            EngineClosed(f"SupervisedEngine[{self.name}] closed with "
                         "request pending")
            if self._closing.is_set() else None)
        for req in reqs:
            if req.future.done():
                continue
            if err is not None:
                req.future.set_exception(err)
                continue
            with self._lock:
                self._replayed += 1
            self._obs_replayed.inc(engine=self.name)
            if req.trace is not None:
                req.trace.mark("replayed", engine=self.name)
            self._submit_inner(req, block=True)

    def _give_up(self, err: RestartsExhausted) -> None:
        with self._lock:
            self._failed = err
        if self._metrics is not None:
            self._metrics.write("serving_supervisor_failed",
                                engine=self.name, error=str(err))
        self._flush_replay()

    # -- observability -----------------------------------------------------

    def _on_breaker_transition(self, old: str, new: str) -> None:
        self._obs_breaker.set(
            {"closed": 0, "half_open": 1, "open": 2}.get(new, -1),
            engine=self.name)
        if self._metrics is not None:
            self._metrics.write("serving_breaker", engine=self.name,
                                from_state=old, to_state=new)

    def _health_counters(self) -> dict:
        with self._lock:
            return {
                "restarts": self._restarts,
                "consecutive_restarts": self._consec_restarts,
                "replayed": self._replayed,
                "shed_overload": self._shed_overload,
                "shed_breaker": self._shed_breaker,
                "poisoned": self._poisoned,
                "quarantined": list(self._quarantined),
            }

    def breaker_snapshot(self) -> dict:
        """The circuit breaker's state dict (state / consecutive_failures
        / transitions) — the fleet router republishes it per replica as
        the ``deepgo_fleet_breaker_state`` gauge, so breaker flaps are
        telemetry, not just a ``health()`` field."""
        return self._breaker.snapshot()

    def health(self) -> dict:
        """One snapshot of the whole resilience layer: supervisor state,
        breaker state, restart/shed/poison counters, the load estimate,
        and the inner engine's own stats()."""
        state = ("failed" if self._failed is not None
                 else "closed" if self._closing.is_set() else "serving")
        out = {"state": state, "breaker": self._breaker.snapshot(),
               "estimated_wait_s": self.estimated_wait_s()}
        out.update(self._health_counters())
        out["engine"] = self._engine.stats()
        return out

    def stats(self) -> dict:
        """The inner engine's stats() plus a ``supervisor`` block, so
        existing consumers (selfplay's stats["engine"], bench) surface
        resilience counters without a second call site."""
        s = self._engine.stats()
        s["supervisor"] = self._health_counters()
        s["supervisor"]["breaker"] = self._breaker.snapshot()["state"]
        return s
