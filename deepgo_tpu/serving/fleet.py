"""FleetRouter: N supervised engine replicas behind one failure-absorbing
front door.

One ``SupervisedEngine`` survives everything PR 3 threw at it, but it is
still one dispatcher on one chip — a single failure domain and a single
chip's ceiling. FireCaffe's scale-out framing (arXiv:1511.00175) says the
serving answer is N replicas whose aggregate absorbs the failure of any
one of them, and at fleet sizes failure is the steady state, so the
router — not the operator — must do the absorbing. This module is that
router, plus the two things a fleet needs that a single engine does not:

  placement   least-estimated-wait: each submit routes to the serving
              replica whose ``estimated_wait_s()`` (rolling p50 dispatch
              latency x pending windows, the PR 3 admission estimate) is
              smallest, with pending-count and round-robin tie-breaks, so
              load follows capacity instead of a static hash.
  failover    a request whose replica dies under it (RestartsExhausted,
              dispatcher death past the replica's restart budget, closed
              mid-flight) or trips its breaker is transparently re-routed
              to a healthy replica WITH EXCLUSION — the failed replica is
              struck from that request's candidate set, so a poisoned
              placement can't bounce back to the same corpse. Retries are
              bounded (``max_failovers``); a ``PoisonedRequest`` is final
              — the request's own content fails the forward, and retrying
              it fleet-wide would poison every replica in turn.
  respawn     a replica that exhausted its supervisor's restart budget is
              rebuilt in the background (bounded full-jitter backoff, the
              resilience.py discipline) while traffic routes around it;
              the fleet never blocks a caller on a rebuild.
  hot reload  ``reload(params_or_checkpoint)`` rolls new weights through
              the replicas ONE AT A TIME: drain (placement skips the
              replica, its in-flight requests finish on the old weights),
              pointer-swap the params into the warm jit cache
              (``set_params`` — the bucket ladder shapes are unchanged,
              so nothing recompiles), rejoin. In-flight futures never
              drop and the fleet never goes below N-1 capacity.
  QoS tiers   every request carries a priority class, ``interactive >
              selfplay > batch``. Fleet admission control sheds the cheap
              tier first: each tier's headroom factor scales how much of
              its deadline the estimated queue wait may consume before
              the request is shed at the door (batch sheds at 30% of its
              deadline, interactive only when the deadline is genuinely
              unmeetable), with per-tier shed counters and a ``tier``
              label on the request-latency histogram.
  hedging     gray-failure defense #1 (docs/robustness.md): a request on
              a latency-critical tier (``hedge_tiers``) that has not
              resolved after a p99-derived delay is DUPLICATED onto a
              second replica — first result wins, the loser is
              cancelled, and the duplicate rate is capped
              (``hedge_max_frac``) so a sick fleet can't double its own
              load. A slow-but-alive replica costs one hedge delay, not
              one brownout.
  ejection    gray-failure defense #2: the router keeps a per-replica
              latency window; a replica whose median stays above
              ``eject_factor`` x the median of its peers for
              ``eject_consecutive`` scans is force-recycled through the
              existing respawn path (``eject_replica`` — also the
              entry point the canary prober uses when a replica starts
              returning wrong answers, deepgo_tpu/chaos/canary.py).
  integrity   gray-failure defense #3: an optional per-response
              ``integrity_check`` predicate; a row that fails it is
              treated as a replica failure (excluded, failed over,
              counted) instead of being handed to the caller — corrupt
              output becomes lost headroom, never a wrong answer.
  caching     an optional content-addressed result cache in FRONT of
              placement (serving/cache.py): repeat positions are served
              from memory, concurrent same-position submits coalesce
              onto one forward with leader-failure promotion, and
              ``reload`` invalidates at both ends of the roll so a
              stale-weights row is never served.
  surge tier  heterogeneous replica platforms (``fleet_policy_engine``'s
              ``platforms=``): batch-tier traffic prefers CPU surge
              replicas and the latency tiers avoid them, by PREFERENCE —
              failover crosses platforms when a tier's preferred set
              dies — and the straggler scan baselines each replica
              against same-platform peers only.

Fault sites: ``fleet_route`` fires inside each placement attempt (an
injected fault there is absorbed like a replica failure — excluded,
re-routed, counted); ``fleet_reload`` fires per replica swap during a
rolling reload (a fault surfaces as a typed ``FleetReloadError`` while
the replica rejoins and the fleet keeps serving).

The contract is the supervisor's, widened to the fleet: every submitted
future RESOLVES — a result (possibly after transparent failovers and
respawns), or a typed shed / poison / timeout / exhaustion — never a
stranded waiter. Clock, sleep, and RNG are injectable; the chaos tests
drive every transition deterministically.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import os
import queue
import random
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

import numpy as np

from ..analysis.lockcheck import make_lock
from ..obs import get_registry
from ..obs.sentinel import flight_dump
from ..utils import faults
from .cache import PositionCache, Waiter
from .engine import EngineBusy, EngineClosed, EngineError
from .resilience import (CircuitOpen, EngineOverloaded, PoisonedRequest,
                         full_jitter_delay)

# priority classes, most- to least-important: overload sheds from the
# right end first (per-tier headroom factors in FleetConfig)
TIERS = ("interactive", "selfplay", "batch")


class FleetUnavailable(EngineError):
    """No replica could take the request: everything is failed,
    respawning, or excluded by this request's own failover history."""


class FailoverExhausted(EngineError):
    """The request's bounded failover budget ran out; the last replica
    failure rides as ``__cause__``."""


class FleetReloadError(EngineError):
    """A rolling weight reload failed mid-roll. Replicas already swapped
    keep the new weights, the failing replica rejoined on its old ones,
    and every later respawn/restart converges on the new checkpoint —
    re-invoking ``reload`` is idempotent."""


class IntegrityViolation(EngineError):
    """A replica returned a response that failed the fleet's
    ``integrity_check`` predicate — silently wrong output (the gray
    failure). The router treats it as a replica failure: the request
    fails over with exclusion and the caller never sees the bad row."""


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Knobs for one FleetRouter.

    ``*_headroom`` is the fraction of a request's deadline the estimated
    fleet queue wait may consume before that tier is shed at admission:
    1.0 sheds interactive only when its deadline is already unmeetable,
    while batch backs off at 30% — so overload drains the cheap tier
    first and the expensive tier last. ``max_failovers`` bounds how many
    replica FAILURES one request may ride through (shed-reroutes don't
    count); ``max_respawns`` bounds CONSECUTIVE background rebuilds of
    one replica (any request it serves resets the count).

    The gray-failure knobs (docs/robustness.md, "Gray failures") are OFF
    by default — ``hedge_tiers=()`` disables hedging,
    ``eject_stragglers=False`` disables outlier ejection,
    ``integrity_check=None`` disables response validation — so a plain
    fleet behaves exactly as before; the chaos campaign's defenses-ON
    arm (and any production config) opts in explicitly. A request on a
    hedged tier duplicates after ``hedge_factor`` x that tier's rolling
    p99 (floored at ``hedge_min_delay_s`` while the tier has no data),
    with at most ``hedge_max_frac`` of submits hedged. A replica whose
    per-replica latency median exceeds ``eject_factor`` x the median of
    its peers (each over ``eject_min_samples``+ completions) for
    ``eject_consecutive`` consecutive scans is force-recycled.
    ``integrity_check(row) -> bool`` validates every response row.

    ``surge_platforms`` names the CPU surge tier on a heterogeneous
    fleet: replicas whose engine carries a matching ``platform`` stamp
    (``fleet_policy_engine(platforms=...)``) are PREFERRED for batch-tier
    traffic and avoided by the latency tiers — but preference, not
    partition: when a tier's preferred set is empty (all TPU replicas
    dead, or a CPU-only fleet) placement falls back to every candidate,
    so failover crosses platforms automatically. On a homogeneous fleet
    (no platform stamps) the knob is inert."""

    max_failovers: int = 3
    default_tier: str = "interactive"
    interactive_headroom: float = 1.0
    selfplay_headroom: float = 0.6
    batch_headroom: float = 0.3
    admission_control: bool = True
    min_serving: int = 1
    max_respawns: int = 8
    respawn_base_s: float = 0.05
    respawn_cap_s: float = 2.0
    warm_on_respawn: bool = True
    drain_timeout_s: float = 30.0
    hedge_tiers: tuple = ()
    hedge_factor: float = 1.0
    hedge_min_delay_s: float = 0.02
    hedge_max_frac: float = 0.2
    eject_stragglers: bool = False
    eject_factor: float = 3.0
    eject_min_samples: int = 20
    eject_consecutive: int = 2
    integrity_check: object = None
    surge_platforms: tuple = ("cpu",)

    def headroom(self, tier: str) -> float:
        return {"interactive": self.interactive_headroom,
                "selfplay": self.selfplay_headroom,
                "batch": self.batch_headroom}[tier]


class _FleetRequest:
    __slots__ = ("packed", "player", "rank", "tier", "deadline", "future",
                 "excluded", "failovers", "t_submit", "t_first_failure",
                 "last_error", "trace", "workload", "placed", "inners",
                 "hedge_state", "hedge_idx", "parked")

    def __init__(self, packed, player, rank, tier, deadline, t_submit,
                 trace=None, workload=None):
        self.packed = packed
        self.player = player
        self.rank = rank
        self.tier = tier
        self.deadline = deadline          # absolute, router clock
        self.future: Future = Future()
        self.excluded: set[int] = set()   # replicas this request fled
        self.failovers = 0
        self.t_submit = t_submit
        self.t_first_failure: float | None = None
        self.last_error: BaseException | None = None
        self.trace = trace                # one id across every hop
        self.workload = workload          # WorkloadToken, fleet-owned
        self.placed: int | None = None    # latest primary placement
        self.inners: dict[int, Future] = {}  # replica idx -> inner future
        self.hedge_state: str | None = None  # None|scheduled|launched
        self.hedge_idx: int | None = None    # the hedge copy's replica
        self.parked = False               # waiting out a respawn in flight


class _Replica:
    __slots__ = ("idx", "engine", "state", "pending", "consec_respawns",
                 "respawns", "lat", "eject_strikes")

    def __init__(self, idx, engine):
        self.idx = idx
        self.engine = engine
        self.state = "serving"   # serving | draining | respawning | failed
        self.pending = 0         # in-flight requests routed here
        self.consec_respawns = 0
        self.respawns = 0
        self.lat: deque = deque(maxlen=128)  # per-replica completion times
        self.eject_strikes = 0   # consecutive outlier scans


class FleetRouter:
    """N replicas, one router thread, the full SupervisedEngine surface.

    ``make_replica(i) -> SupervisedEngine`` builds (and rebuilds) replica
    ``i``; build the jitted forward ONCE outside and close over it so all
    replicas share one warm jit cache — then warmup compiles each rung
    once for the whole fleet, and neither restarts, respawns, nor weight
    reloads ever recompile. Duck-types the engine surface every consumer
    uses (submit / evaluate / warmup / stats / compile_cache_size /
    health / close / context manager), so selfplay, arena agents, the
    shared registry, and /healthz adapters ride it unchanged.
    """

    def __init__(self, make_replica, replicas: int,
                 config: FleetConfig | None = None, name: str = "fleet",
                 metrics=None, clock=time.monotonic, sleep=time.sleep,
                 rng=None, params=None, cache=None):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.config = config or FleetConfig()
        if self.config.default_tier not in TIERS:
            raise ValueError(
                f"default_tier {self.config.default_tier!r} not in {TIERS}")
        self.name = name
        # the position cache sits in FRONT of placement (serving/cache.py:
        # keying, coalescing, invalidation-on-reload); None keeps the
        # pre-cache door byte-for-byte. A CacheConfig is wrapped here so
        # callers never have to touch PositionCache directly.
        if cache is None or isinstance(cache, PositionCache):
            self.cache = cache
        else:
            self.cache = PositionCache(cache, name=f"{name}-cache",
                                       metrics=metrics)
        self._make_replica = make_replica
        self._metrics = metrics
        self._clock = clock
        self._sleep = sleep
        # lint: allow[determinism] backoff jitter only — placement and results never depend on it; tests inject rng=
        self._rng = rng if rng is not None else random.Random()
        self._lock = make_lock(f"fleet.{name}")
        self._closing = threading.Event()
        self._events: queue.Queue = queue.Queue()
        self._rr = 0                       # round-robin tie-break cursor
        # the BASE (full-precision) params, the reload/respawn source of
        # truth: replicas serving a lossy variant carry a
        # ``prepare_params`` hook (serving/variants.py) the router
        # applies before every swap, so the base tree — not a variant's
        # int8 pytree — is always the checkpoint-load template
        self._current_params = params      # updated by reload; respawns converge
        self._reload_mutex = make_lock(f"fleet.{name}.reload")
        self._failovers = 0
        self._respawns = 0
        self._reloads = 0
        self._poisoned = 0
        self._submits = 0
        self._hedges = 0
        self._hedge_wins = 0
        self._ejections = 0
        self._integrity_failures = 0
        self._respawn_threads: list[threading.Thread] = []
        self._parked: list[_FleetRequest] = []
        self._parks = 0
        self._shed = {t: 0 for t in TIERS}
        self._tier_lat: dict[str, deque] = {t: deque(maxlen=4096)
                                            for t in TIERS}
        self._failover_lat: deque = deque(maxlen=1024)
        reg = get_registry()
        self._obs_failovers = reg.counter(
            "deepgo_fleet_failovers_total",
            "requests re-routed off a failed replica")
        self._obs_shed = reg.counter(
            "deepgo_fleet_shed_total",
            "requests shed at the fleet door (tier, reason)")
        self._obs_respawns = reg.counter(
            "deepgo_fleet_respawns_total",
            "replicas rebuilt in the background after supervisor give-up")
        self._obs_reloads = reg.counter(
            "deepgo_fleet_reloads_total", "rolling weight reloads completed")
        self._obs_serving = reg.gauge(
            "deepgo_fleet_replicas_serving",
            "replicas currently accepting placement")
        self._obs_replica_state = reg.gauge(
            "deepgo_fleet_replica_state",
            "per-replica lifecycle: 1 serving, 0.5 draining/respawning, "
            "0 failed — the dash health grid's rows")
        self._obs_failover_s = reg.histogram(
            "deepgo_fleet_failover_seconds",
            "first replica failure to final resolution, failed-over "
            "requests only")
        self._obs_hedges = reg.counter(
            "deepgo_fleet_hedges_total",
            "hedge duplicates launched for latency-critical tiers")
        self._obs_hedge_wins = reg.counter(
            "deepgo_fleet_hedge_wins_total",
            "hedged requests whose hedge copy resolved first")
        self._obs_ejections = reg.counter(
            "deepgo_fleet_ejections_total",
            "replicas force-recycled (latency outlier, canary, operator)")
        self._obs_integrity = reg.counter(
            "deepgo_fleet_integrity_failures_total",
            "responses rejected by the fleet integrity check")
        self._obs_parks = reg.counter(
            "deepgo_fleet_parks_total",
            "unroutable requests parked to wait out a respawn in flight "
            "instead of resolving typed exhaustion against a fleet that "
            "is only temporarily below strength")
        self._obs_breaker = reg.gauge(
            "deepgo_fleet_breaker_state",
            "per-replica circuit breaker: 0 closed, 1 half-open, 2 open")
        # the EXISTING request histogram gains a tier label at fleet
        # level: per-tier latency scrapes next to the engines' own series
        self._obs_request = reg.histogram(
            "deepgo_serving_request_seconds",
            "request latency submit-to-result")
        self._replicas = [_Replica(i, make_replica(i))
                          for i in range(replicas)]
        self._update_serving_gauge()
        self._hedge_q: list = []       # heap of (due, seq, request)
        self._hedge_cv = threading.Condition()
        self._hedge_seq = itertools.count()
        self._hedge_thread = None
        if self.config.hedge_tiers and replicas > 1:
            self._hedge_thread = threading.Thread(
                target=self._hedge_loop, name=f"fleet-{name}-hedger",
                daemon=True)
            self._hedge_thread.start()
        self._thread = threading.Thread(
            target=self._router_loop, name=f"fleet-{name}", daemon=True)
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------

    def warmup(self) -> int:
        """Warm every replica; with a shared jitted forward the first
        replica compiles the ladder and the rest hit the cache. Returns
        the per-replica rung count (the engine warmup contract)."""
        warmed = 0
        for rep in self._replicas:
            warmed = rep.engine.warmup()
        return warmed

    def compile_cache_size(self) -> int | None:
        """Fleet-wide compile count: the SUM over replicas (None when no
        replica exposes a cache). Summing keeps the zero-recompile
        contract assertable at the fleet surface — any replica compiling
        post-warmup moves the total."""
        sizes = [s for s in self.compile_cache_sizes() if s is not None]
        return sum(sizes) if sizes else None

    def compile_cache_sizes(self) -> list[int | None]:
        """Per-replica compile counts, index-aligned with replica ids —
        what lets the recompile sentinel (analysis/xlacheck.py,
        DEEPGO_XLACHECK=1) attribute a storm to the replica that
        actually compiled instead of reporting replica 0 for everyone."""
        return [rep.engine.compile_cache_size() for rep in self._replicas]

    @property
    def ladder(self):
        return self._replicas[0].engine.ladder

    @property
    def replicas(self) -> int:
        return len(self._replicas)

    def _check_alive(self) -> None:
        if self._closing.is_set():
            raise EngineClosed(f"FleetRouter[{self.name}] is closed")

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop routing and shut every replica down. Same contract as the
        layers below: returns with every outstanding future resolved —
        drained results or typed EngineClosed, never stranded waiters.

        Respawn threads are joined (bounded by ``timeout``) BEFORE the
        replica engines close: a respawn that already built its
        replacement engine swaps it in under the lock, and closing the
        replica list while that swap is in flight would close the corpse
        and leak the live replacement. ``_respawn`` checks ``_closing``
        after the build and discards its engine, so after the join there
        is exactly one engine per replica left to close."""
        self._closing.set()
        self._events.put(("stop", None))
        with self._hedge_cv:
            self._hedge_cv.notify_all()
        self._thread.join(timeout=timeout)
        if self._hedge_thread is not None:
            self._hedge_thread.join(timeout=timeout)
        with self._lock:
            spawners = list(self._respawn_threads)
        for t in spawners:
            t.join(timeout=timeout)
        for rep in self._replicas:
            try:
                rep.engine.close(drain=drain, timeout=timeout)
            except Exception:  # pragma: no cover — corpse cleanup only
                pass
        exc = EngineClosed(
            f"FleetRouter[{self.name}] closed with request pending")
        while True:
            try:
                kind, payload = self._events.get_nowait()
            except queue.Empty:
                break
            if kind == "failover" and not payload.future.done():
                payload.future.set_exception(exc)
        with self._lock:
            parked, self._parked = self._parked, []
        for req in parked:
            if not req.future.done():
                req.future.set_exception(exc)
        if self.cache is not None:
            # failing the queued internal leaders above already walked
            # complete_err/promotion for most flights; this sweep catches
            # waiters whose leader future resolved before the callback
            # could re-dispatch — the no-stranded-waiter contract holds
            # through the cached door too
            for key in self.cache.inflight_keys():
                for w in self.cache.drop_flight(key):
                    self._resolve_waiter(w, exc)
        if self._metrics is not None:
            self._metrics.write("fleet_close", fleet=self.name,
                                **self._counters())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- submission --------------------------------------------------------

    def submit(self, packed: np.ndarray, player: int, rank: int,
               tier: str | None = None, timeout_s: float | None = None,
               block: bool = True, session: str | None = None) -> Future:
        """Queue one board on the least-loaded replica; the Future ALWAYS
        resolves: the result row (possibly after transparent failovers,
        replica restarts, and background respawns), TimeoutError,
        EngineOverloaded (tier shed at the fleet door), CircuitOpen /
        EngineBusy (every replica shedding), PoisonedRequest (the request
        itself fails the forward — final, never retried fleet-wide),
        FailoverExhausted, or FleetUnavailable."""
        self._check_alive()
        tier = tier or self.config.default_tier
        if tier not in TIERS:
            raise ValueError(f"tier {tier!r} not in {TIERS}")
        if timeout_s is not None and self.config.admission_control:
            est = self.estimated_wait_s()
            if est is not None and est > timeout_s * self.config.headroom(tier):
                self._count_shed(tier, "admission")
                raise EngineOverloaded(
                    f"FleetRouter[{self.name}] estimated queue wait "
                    f"{est:.3f}s exceeds tier {tier!r} headroom "
                    f"({self.config.headroom(tier):g} x {timeout_s}s "
                    "deadline); shed at the fleet door")
        now = self._clock()
        deadline = None if timeout_s is None else now + timeout_s
        # the fleet door is the outermost serving layer: it owns the
        # request's TraceContext — one trace id across every placement,
        # failover hop, replica restart, and the final resolution —
        # and, under the same ownership rule, the request's
        # WorkloadToken (obs/workload.py): arrival + tier recorded
        # here, the bucket stamped by whichever engine dispatches it
        from ..obs import tracing
        from ..obs import workload as workload_mod

        trace = tracing.start_request(fleet=self.name, tier=tier)
        wl = workload_mod.note_request(packed, player, rank, tier=tier,
                                       fleet=self.name, session=session)
        if self.cache is not None and not self.cache.bypass(tier):
            return self._submit_cached(packed, player, rank, tier,
                                       deadline, now, trace, wl, block)
        req = _FleetRequest(np.asarray(packed), int(player), int(rank),
                            tier, deadline, now, trace=trace, workload=wl)
        with self._lock:
            self._submits += 1  # the hedge-rate cap's denominator
        if trace is not None:
            trace.mark("queued", fleet=self.name, tier=tier)
            req.future.add_done_callback(trace.finish_future)
        if wl is not None:
            req.future.add_done_callback(wl.finish_future)
        self._dispatch(req, block=block)
        if req.future.done():
            exc = req.future.exception()
            if isinstance(exc, (EngineOverloaded, CircuitOpen, EngineBusy,
                                FleetUnavailable)):
                raise exc  # door-shed surface, same as SupervisedEngine
        return req.future

    # -- the cached door ---------------------------------------------------

    def _submit_cached(self, packed, player, rank, tier, deadline, now,
                       trace, wl, block) -> Future:
        """Route one request through the position cache:

        hit       — the stored row (remapped to this view under canonical
                    keying) resolves the caller immediately; no replica
                    sees the request.
        follower  — a leader forward for this key is in flight; the
                    caller rides it and is resolved by the leader's
                    completion. Exactly one forward for N submits.
        leader    — dispatch through the normal placement/failover path;
                    the internal request's future is DECOUPLED from the
                    caller's so a leader failure can promote a follower
                    instead of poisoning everyone (``_on_leader_done``).

        Cache hits deliberately do NOT feed the per-replica/tier latency
        windows — hedging delays and ejection baselines measure forwards,
        and letting near-zero hit latencies in would hedge everything."""
        cache = self.cache
        caller: Future = Future()
        with self._lock:
            self._submits += 1  # the hedge-rate cap's denominator
        if trace is not None:
            trace.mark("queued", fleet=self.name, tier=tier)
            caller.add_done_callback(trace.finish_future)
        if wl is not None:
            caller.add_done_callback(wl.finish_future)
        key, disp_packed, k = cache.prepare(np.asarray(packed),
                                            int(player), int(rank))
        waiter = Waiter(caller, k, tier, deadline, trace)
        role, row = cache.join(key, waiter)
        if role == "hit":
            if trace is not None:
                trace.mark("cache_hit", key=key)
            self._resolve_waiter(waiter, row)
            return caller
        if role == "follower":
            if trace is not None:
                trace.mark("cache_coalesced", key=key)
            return caller
        if trace is not None:
            trace.mark("cache_miss", key=key)
        cache.lead(key, disp_packed, int(player), int(rank), waiter)
        req = _FleetRequest(np.asarray(disp_packed), int(player),
                            int(rank), tier, deadline, now, trace=trace,
                            workload=wl)
        req.future.add_done_callback(
            lambda f: self._on_leader_done(key, f))
        self._dispatch(req, block=block)
        if caller.done():
            exc = caller.exception()
            if isinstance(exc, (EngineOverloaded, CircuitOpen, EngineBusy,
                                FleetUnavailable)):
                raise exc  # door-shed surface, same as the uncached path
        return caller

    @staticmethod
    def _resolve_waiter(waiter: Waiter, value) -> bool:
        """Resolve one cache waiter's caller future exactly once; a
        CacheKeyingError value (an output shape the canonical remap
        cannot serve across views) resolves as the typed exception."""
        try:
            if isinstance(value, BaseException):
                waiter.future.set_exception(value)
            else:
                waiter.future.set_result(value)
            return True
        except InvalidStateError:
            return False

    def _on_leader_done(self, key: str, f: Future) -> None:
        """The leader's internal forward resolved. Success publishes the
        fill (same generation only) and resolves every waiter with its
        per-view remap. Failure is the LEADER'S OWN — its caller gets
        the error, the next follower is promoted and re-dispatched on
        the router thread (never this resolver thread), and the chain
        terminates because each promotion consumes a waiter."""
        cache = self.cache
        exc = (EngineClosed(f"FleetRouter[{self.name}] cancelled a "
                            "cached leader") if f.cancelled()
               else f.exception())
        if exc is None:
            for w, value in cache.complete_ok(key, f.result()):
                self._resolve_waiter(w, value)
            return
        leader, promoted, dispatch = cache.complete_err(key)
        if leader is not None:
            self._resolve_waiter(leader, exc)
        if promoted is None:
            return
        if promoted.trace is not None:
            promoted.trace.mark("cache_promoted", key=key)
        if self._closing.is_set():
            closed = EngineClosed(
                f"FleetRouter[{self.name}] closed with request pending")
            self._resolve_waiter(promoted, closed)
            for w in cache.drop_flight(key):
                self._resolve_waiter(w, closed)
            return
        packed, player, rank = dispatch
        # the promoted waiter's OWN deadline/trace ride the re-dispatch;
        # its workload token keeps finishing through its caller future
        # (the bucket stamp of the failed leader's forward is lost —
        # acceptable: promotions are failure-path rare)
        req = _FleetRequest(packed, player, rank, promoted.tier,
                            promoted.deadline, self._clock(),
                            trace=promoted.trace, workload=None)
        req.future.add_done_callback(
            lambda f2: self._on_leader_done(key, f2))
        self._events.put(("failover", req))

    def evaluate(self, packed: np.ndarray, players: np.ndarray,
                 ranks: np.ndarray, timeout_s: float | None = None,
                 tier: str | None = None) -> np.ndarray:
        """Blocking convenience, same shape as InferenceEngine.evaluate."""
        futures = [self.submit(packed[i], int(players[i]), int(ranks[i]),
                               tier=tier, timeout_s=timeout_s)
                   for i in range(len(packed))]
        return np.stack([f.result() for f in futures])

    def estimated_wait_s(self) -> float | None:
        """The fleet's load estimate: the MINIMUM replica estimate — a
        new request goes to the least-loaded replica, so the best replica
        is the wait the request will actually see. Replicas with no
        dispatch data yet are UNKNOWN, not idle — they are skipped, so a
        freshly (re)spawned replica cannot zero the fleet-wide minimum
        and blind the admission door while its siblings drown. None when
        no serving replica has data (an idle fleet never sheds)."""
        with self._lock:
            reps = [r for r in self._replicas if r.state == "serving"]
        vals = []
        for r in reps:
            try:
                v = r.engine.estimated_wait_s()
            except Exception:  # a dying replica must not poison admission
                continue
            if v is not None:
                vals.append(v)
        return min(vals) if vals else None

    # -- routing -----------------------------------------------------------

    def _pick(self, req: _FleetRequest, tried: set[int]):
        """Least-estimated-wait placement over serving replicas, skipping
        this request's exclusions. Draining replicas (mid-reload) are a
        last resort: better one more old-weights request than a shed."""
        avoid = req.excluded | tried
        with self._lock:
            cands = [r for r in self._replicas
                     if r.state == "serving" and r.idx not in avoid]
            if not cands:
                cands = [r for r in self._replicas
                         if r.state == "draining" and r.idx not in avoid]
            self._rr += 1
            rr = self._rr
        if not cands:
            return None
        cands = self._platform_preference(cands, req.tier)
        n = len(self._replicas)

        def key(r):
            try:
                est = r.engine.estimated_wait_s()
            except Exception:
                est = None
            return (est if est is not None else 0.0, r.pending,
                    (r.idx - rr) % n)

        return min(cands, key=key)

    def _platform_preference(self, cands: list, tier: str) -> list:
        """The CPU surge tier's routing rule: batch-tier traffic prefers
        ``surge_platforms`` replicas (bulk scans tolerate CPU latency and
        free the accelerators), every other tier avoids them. Preference
        only — an empty preferred set falls back to all candidates, so a
        fleet whose TPU replicas all died keeps serving interactive
        traffic on the surge tier, and a homogeneous fleet (no platform
        stamps) is untouched."""
        surge = self.config.surge_platforms
        if not surge:
            return cands
        if tier == "batch":
            pref = [r for r in cands
                    if getattr(r.engine, "platform", None) in surge]
        else:
            pref = [r for r in cands
                    if getattr(r.engine, "platform", None) not in surge]
        return pref or cands

    def _dispatch(self, req: _FleetRequest, block: bool = True) -> None:
        """Route one request: try candidates best-first until a replica
        accepts it. Hard failures exclude the replica for this request's
        lifetime (failover-with-exclusion) and wake the respawn scanner;
        sheds only skip the replica for this routing round."""
        tried: set[int] = set()
        shed_error: BaseException | None = None
        while True:
            if req.future.done():
                return
            if req.deadline is not None and self._clock() >= req.deadline:
                self._resolve(req, exc=TimeoutError(
                    f"request deadline expired before placement in "
                    f"FleetRouter[{self.name}]"))
                return
            rep = self._pick(req, tried)
            if rep is None:
                self._resolve_unroutable(req, shed_error)
                return
            remaining = (None if req.deadline is None
                         else req.deadline - self._clock())
            if req.trace is not None:
                # the placement decision, stamped before the handoff so a
                # submit-time death renders as routed -> hop
                req.trace.mark("routed", replica=rep.idx)
                req.trace.set(replica=rep.idx)
            try:
                faults.check("fleet_route")
                # the trace/workload kwargs only travel when armed, so
                # scripted duck-typed replicas keep their plain signature
                kw = {} if req.trace is None else {"trace": req.trace}
                if req.workload is not None:
                    kw["workload"] = req.workload
                inner = rep.engine.submit(req.packed, req.player, req.rank,
                                          timeout_s=remaining, block=block,
                                          **kw)
            except (EngineOverloaded, CircuitOpen, EngineBusy) as e:
                # replica-level shed: transparent reroute, no exclusion —
                # the replica is healthy, just full (or probing)
                tried.add(rep.idx)
                shed_error = e
                continue
            except (EngineError, faults.FaultError) as e:
                # replica dead/dying under the submit (RestartsExhausted,
                # EngineClosed, injected route fault): exclude + re-route
                self._note_failure(req, rep, e)
                if req.future.done():
                    return
                continue
            with self._lock:
                rep.pending += 1
            req.placed = rep.idx
            req.inners[rep.idx] = inner
            inner.add_done_callback(
                lambda f, rep=rep: self._on_replica_done(req, rep, f))
            if (self._hedge_thread is not None
                    and req.tier in self.config.hedge_tiers
                    and req.hedge_state is None):
                self._schedule_hedge(req)
            return

    def _resolve_unroutable(self, req: _FleetRequest,
                            shed_error: BaseException | None) -> None:
        """Every candidate is gone: a shed if replicas shed us, typed
        exhaustion if this request already fled failures, else the fleet
        is simply down — UNLESS a respawn is in flight and the deadline
        still has headroom, in which case the request parks and the
        router re-dispatches it when the rebuild lands."""
        if shed_error is not None:
            self._count_shed(req.tier, "replicas")
            self._resolve(req, exc=shed_error)
        elif self._park(req):
            return
        elif req.failovers > 0:
            err = FailoverExhausted(
                f"FleetRouter[{self.name}] request failed over "
                f"{req.failovers} time(s) and no healthy replica remains")
            err.__cause__ = req.last_error
            self._resolve(req, exc=err)
        else:
            self._count_shed(req.tier, "unroutable")
            self._resolve(req, exc=FleetUnavailable(
                f"FleetRouter[{self.name}] has no serving replica "
                f"({self._serving_count()}/{len(self._replicas)} serving)"))

    def _park(self, req: _FleetRequest) -> bool:
        """Park one unroutable request while any replica is mid-respawn
        (the PR 12 fleet-2 chaos fix): the fleet is temporarily below
        strength, not down, so resolving FailoverExhausted /
        FleetUnavailable here burns a typed error against capacity that
        is seconds from returning. Parked requests are re-dispatched by
        the router when a respawn lands or gives up (``"respawned"``
        events), and swept on idle ticks so a lapsed deadline resolves
        its TimeoutError promptly; ``close()`` drains the parking lot
        with EngineClosed — no stranded waiters."""
        if self._closing.is_set():
            return False
        if req.deadline is not None and self._clock() >= req.deadline:
            return False
        with self._lock:
            respawning = sum(r.state == "respawning"
                             for r in self._replicas)
            if not respawning:
                return False
            req.parked = True
            self._parked.append(req)
            self._parks += 1
        self._obs_parks.inc(fleet=self.name)
        if req.trace is not None:
            req.trace.mark("parked", respawning=respawning)
        return True

    def _unpark(self, rep: _Replica | None = None,
                respawned: bool = False) -> None:
        """Re-dispatch every parked request (router thread only). A
        respawn that LANDED also clears the fresh replica from each
        parked request's exclusion set — the rebuilt engine is not the
        corpse the request fled. Requests that are still unroutable and
        still covered by another in-flight respawn simply park again;
        lapsed deadlines resolve TimeoutError inside ``_dispatch``."""
        with self._lock:
            parked, self._parked = self._parked, []
        for req in parked:
            req.parked = False
            if req.future.done():
                continue
            if respawned and rep is not None:
                req.excluded.discard(rep.idx)
            self._dispatch(req, block=True)

    def _note_failure(self, req: _FleetRequest, rep: _Replica,
                      exc: BaseException) -> None:
        """Account one replica failure against the request's bounded
        failover budget and schedule the replica health check."""
        req.excluded.add(rep.idx)
        req.last_error = exc
        req.failovers += 1
        if req.trace is not None:
            req.trace.hop(rep.idx, type(exc).__name__)
        if req.t_first_failure is None:
            req.t_first_failure = self._clock()
        with self._lock:
            self._failovers += 1
            respawning = sum(r.state == "respawning"
                             for r in self._replicas)
            rep_serving = rep.state == "serving"
        self._obs_failovers.inc(fleet=self.name)
        self._events.put(("check", rep))
        # the budget is respawn-aware (the PR 12 fleet-2 chaos flake):
        # hops burned while replicas are mid-rebuild are hops against a
        # fleet TEMPORARILY below strength, not against this request —
        # each respawn in flight widens the budget by one, and once the
        # rebuilds land (or give up) the configured bound is back
        if (rep_serving
                and req.failovers > self.config.max_failovers + respawning):
            # the corpse that just failed us may not have flipped to
            # "respawning" yet (its check event is queued, not yet
            # processed): peek at the engine so the budget widens on the
            # same failure that killed the replica, not one hop later
            try:
                if rep.engine.health().get("state") in ("failed", "closed"):
                    respawning += 1
            except Exception:  # noqa: BLE001 — a corpse that can't even
                respawning += 1  # report health is certainly dead
        if req.failovers > self.config.max_failovers + respawning:
            err = FailoverExhausted(
                f"FleetRouter[{self.name}] request exhausted its failover "
                f"budget ({self.config.max_failovers}); last error: {exc!r}")
            err.__cause__ = exc
            self._resolve(req, exc=err)

    @staticmethod
    def _resolve(req: _FleetRequest, value=None,
                 exc: BaseException | None = None) -> bool:
        """Resolve the caller's future exactly once. With hedging, the
        primary and the hedge copy race to this point from different
        resolver threads; the loser gets False and stands down."""
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(value)
            return True
        except InvalidStateError:
            return False

    @staticmethod
    def _cancel_losers(req: _FleetRequest, winner_idx: int) -> None:
        """Best-effort cancel of the losing placements of a resolved
        request: a still-queued duplicate is withdrawn before dispatch
        (``set_running_or_notify_cancel`` skips it); one already in a
        forward just completes into a done caller-future and is
        discarded on arrival."""
        for idx, inner in list(req.inners.items()):
            if idx != winner_idx and not inner.done():
                inner.cancel()

    def _on_replica_done(self, req: _FleetRequest, rep: _Replica,
                         f: Future, hedge: bool = False) -> None:
        """Classify one replica completion. Runs on whatever thread
        resolved the replica future — never blocks, never submits;
        failovers are handed to the router thread. With hedging a
        request can complete twice: first result wins, the duplicate is
        accounted (pending, per-replica latency) and dropped."""
        with self._lock:
            rep.pending -= 1
        if f.cancelled():
            return  # a withdrawn hedge loser; the winner already resolved
        exc = f.exception()
        dt = self._clock() - req.t_submit
        if exc is None:
            # per-replica latency tap — winners AND hedge losers: the
            # loser's slow completion is exactly the straggler signal
            # the outlier ejection scan feeds on
            with self._lock:
                rep.lat.append(dt)
        if req.future.done():
            return
        if exc is None:
            check = self.config.integrity_check
            if check is not None and not self._integrity_ok(check, f):
                with self._lock:
                    self._integrity_failures += 1
                self._obs_integrity.inc(fleet=self.name)
                bad = IntegrityViolation(
                    f"FleetRouter[{self.name}] replica {rep.idx} returned "
                    "a response failing the integrity check; failing over")
                self._note_failure(req, rep, bad)
                self._failover_or_ride_hedge(req, rep)
                return
            rep.consec_respawns = 0
            if not self._resolve(req, value=f.result()):
                return  # lost the hedge race after the done-check
            if hedge:
                with self._lock:
                    self._hedge_wins += 1
                self._obs_hedge_wins.inc(fleet=self.name)
            self._cancel_losers(req, rep.idx)
            self._obs_request.observe(dt, engine=self.name, tier=req.tier)
            with self._lock:
                self._tier_lat[req.tier].append(dt)
            if req.t_first_failure is not None:
                lat = self._clock() - req.t_first_failure
                self._obs_failover_s.observe(lat, fleet=self.name)
                with self._lock:
                    self._failover_lat.append(lat)
        elif isinstance(exc, TimeoutError):
            # the deadline is the request's own: final wherever it expired
            self._resolve(req, exc=exc)
        elif isinstance(exc, PoisonedRequest):
            # the request's content fails the forward — retrying it on
            # another replica would just poison the whole fleet in turn
            with self._lock:
                self._poisoned += 1
            self._resolve(req, exc=exc)
        else:
            # replica died under the request (RestartsExhausted, closed,
            # or an unclassified engine error): failover with exclusion
            self._note_failure(req, rep, exc)
            self._failover_or_ride_hedge(req, rep)

    @staticmethod
    def _integrity_ok(check, f: Future) -> bool:
        try:
            return bool(check(f.result()))
        except Exception:  # noqa: BLE001 — a broken check must fail closed
            return False

    def _failover_or_ride_hedge(self, req: _FleetRequest,
                                rep: _Replica) -> None:
        """Queue a failover re-dispatch unless a sibling placement of
        this request is still in flight — the hedge IS the retry. If
        that sibling later fails too, its own completion callback sees
        this placement done and queues the failover then; the last
        sibling standing always either resolves the future or queues,
        so no waiter strands."""
        if req.future.done():
            return
        live = [i for idx, i in req.inners.items()
                if idx != rep.idx and not i.done()]
        if not live:
            self._events.put(("failover", req))

    # -- request hedging ---------------------------------------------------

    def _hedge_delay_s(self, tier: str) -> float:
        """The p99-derived hedge delay: duplicate only once the request
        is already past what a HEALTHY replica's slowest percentile
        would have taken — hedging the median request would double load
        for nothing (the tail-at-scale rule). The bar is the fastest
        serving replica's p99, not the pooled tier window: a browning
        replica drags the pooled p99 up to its own latency, so a pooled
        delay self-disables hedging exactly when it is needed (the
        duplicate would fire only after the straggler already blew the
        budget)."""
        floor = self.config.hedge_min_delay_s
        with self._lock:
            windows = [np.array(rep.lat, dtype=np.float64)
                       for rep in self._replicas
                       if rep.state == "serving" and len(rep.lat) >= 16]
            if not windows:
                pooled = self._tier_lat[tier]
                if len(pooled) < 16:
                    return floor
                windows = [np.array(pooled, dtype=np.float64)]
        p99 = min(float(np.percentile(w, 99)) for w in windows)
        return max(p99 * self.config.hedge_factor, floor)

    def _schedule_hedge(self, req: _FleetRequest) -> None:
        """Arm one hedge timer for a freshly placed request, subject to
        the rate cap: at most ``hedge_max_frac`` of submits may hedge, so
        a fleet-wide slowdown degrades into capped duplicate load
        instead of a self-inflicted doubling."""
        with self._lock:
            over_cap = (self._hedges + 1
                        > self.config.hedge_max_frac * max(self._submits, 1))
        if over_cap:
            return
        req.hedge_state = "scheduled"
        due = self._clock() + self._hedge_delay_s(req.tier)
        with self._hedge_cv:
            heapq.heappush(self._hedge_q, (due, next(self._hedge_seq), req))
            self._hedge_cv.notify()

    def _hedge_loop(self) -> None:
        """The hedger thread: pops due timers; a request still
        unresolved at its deadline gets a duplicate placement."""
        while not self._closing.is_set():
            with self._hedge_cv:
                if not self._hedge_q:
                    self._hedge_cv.wait(timeout=0.2)
                    continue
                due, _, req = self._hedge_q[0]
                now = self._clock()
                if due > now:
                    self._hedge_cv.wait(timeout=min(due - now, 0.05))
                    continue
                heapq.heappop(self._hedge_q)
            if not req.future.done():
                self._launch_hedge(req)

    def _launch_hedge(self, req: _FleetRequest) -> None:
        """Place the duplicate on a second replica (primary excluded).
        First result wins — ``_on_replica_done`` resolves exactly once
        and cancels the loser. A hedge that cannot place (one replica
        serving, replica full, closing) is silently dropped: hedging
        only ever adds a chance, never a failure mode."""
        if self._closing.is_set():
            return
        avoid = set() if req.placed is None else {req.placed}
        rep = self._pick(req, avoid)
        if rep is None or rep.idx == req.placed:
            return
        remaining = (None if req.deadline is None
                     else req.deadline - self._clock())
        if remaining is not None and remaining <= 0:
            return
        try:
            kw = {} if req.trace is None else {"trace": req.trace}
            inner = rep.engine.submit(req.packed, req.player, req.rank,
                                      timeout_s=remaining, block=False, **kw)
        except Exception:  # noqa: BLE001 — a failed hedge must stay silent
            return
        req.hedge_state = "launched"
        req.hedge_idx = rep.idx
        req.inners[rep.idx] = inner
        with self._lock:
            rep.pending += 1
            self._hedges += 1
        self._obs_hedges.inc(fleet=self.name, tier=req.tier)
        if req.trace is not None:
            req.trace.mark("hedged", replica=rep.idx)
        inner.add_done_callback(
            lambda f, rep=rep: self._on_replica_done(req, rep, f,
                                                     hedge=True))

    # -- the router thread -------------------------------------------------

    def _router_loop(self) -> None:
        ticks = 0
        while True:
            try:
                kind, payload = self._events.get(timeout=0.05)
            except queue.Empty:
                if self._closing.is_set():
                    return
                ticks += 1
                if ticks % 5 == 0:  # idle backstop: catch silent deaths
                    self._scan_replicas()
                continue
            if kind == "stop":
                return
            if kind == "failover":
                self._dispatch(payload, block=True)
            elif kind == "check":
                self._check_replica(payload)
            elif kind == "respawned":
                self._unpark(*payload)

    def _scan_replicas(self) -> None:
        for rep in self._replicas:
            self._check_replica(rep)
        if self.config.eject_stragglers:
            self._eject_outliers()
        self._update_breaker_gauge()
        self._unpark()  # deadline sweep for the parking lot

    def _check_replica(self, rep: _Replica) -> None:
        with self._lock:
            if rep.state != "serving":
                return
        try:
            state = rep.engine.health().get("state")
        except Exception:
            state = "failed"
        if state in ("failed", "closed") and not self._closing.is_set():
            with self._lock:
                if rep.state != "serving":
                    return
                rep.state = "respawning"
            self._update_serving_gauge()
            self._start_respawn(rep)

    def _start_respawn(self, rep: _Replica) -> None:
        """Spawn (and TRACK) one background respawn thread — close()
        joins the tracked set so a shutdown racing an in-flight rebuild
        neither hangs on it nor leaks its engine."""
        t = threading.Thread(target=self._respawn, args=(rep,),
                             name=f"fleet-{self.name}-respawn-{rep.idx}",
                             daemon=True)
        with self._lock:
            self._respawn_threads = [x for x in self._respawn_threads
                                     if x.is_alive()]
            self._respawn_threads.append(t)
        t.start()

    # -- gray-failure defenses: ejection + canary entry point --------------

    def eject_replica(self, idx: int, reason: str = "operator") -> bool:
        """Force one SERVING replica through the respawn path: placement
        stops immediately, in-flight requests on it fail over as its
        engine closes, and a fresh replica rejoins in the background.
        The recycling half of the gray-failure story — the latency
        outlier scan and the canary prober (deepgo_tpu/chaos/canary.py)
        both land here. Returns False when the replica is not currently
        serving (already draining/respawning/failed) or the fleet is
        closing."""
        if not 0 <= idx < len(self._replicas):
            raise ValueError(f"replica {idx} not in fleet of "
                             f"{len(self._replicas)}")
        rep = self._replicas[idx]
        if self._closing.is_set():
            return False
        with self._lock:
            if rep.state != "serving":
                return False
            rep.state = "respawning"
            rep.lat.clear()
            rep.eject_strikes = 0
            self._ejections += 1
        self._update_serving_gauge()
        self._obs_ejections.inc(fleet=self.name, reason=reason)
        flight_dump("fleet_eject", fleet=self.name, replica=idx,
                    why=reason)
        if self._metrics is not None:
            self._metrics.write("fleet_eject", fleet=self.name,
                                replica=idx, reason=reason)
        self._start_respawn(rep)
        return True

    def _eject_outliers(self) -> None:
        """The straggler scan (router thread, idle ticks): a replica
        whose median completion latency exceeds ``eject_factor`` x the
        median of its PEERS — its own window excluded, so one straggler
        can't drag the baseline up to its own level — for
        ``eject_consecutive`` consecutive scans is recycled. Persistence
        gating keeps one GC pause or one unlucky batch from costing a
        respawn. On a heterogeneous fleet the baseline is SAME-PLATFORM
        peers only — a CPU surge replica is slower than its TPU peers by
        design, not by gray failure, and a platform singleton (no peer to
        compare against) is never ejected for latency."""
        cfg = self.config
        with self._lock:
            meds = {rep.idx: float(np.median(np.array(rep.lat)))
                    for rep in self._replicas
                    if rep.state == "serving"
                    and len(rep.lat) >= cfg.eject_min_samples}
            plats = {rep.idx: getattr(rep.engine, "platform", None)
                     for rep in self._replicas}
        if len(meds) < 2:
            return
        for rep in self._replicas:
            mine = meds.get(rep.idx)
            if mine is None:
                continue
            peers = [v for k, v in meds.items()
                     if k != rep.idx and plats.get(k) == plats.get(rep.idx)]
            if not peers:
                rep.eject_strikes = 0
                continue
            base = float(np.median(np.array(peers)))
            if base > 0.0 and mine > cfg.eject_factor * base:
                rep.eject_strikes += 1
                if rep.eject_strikes >= cfg.eject_consecutive:
                    self.eject_replica(rep.idx, reason="straggler")
            else:
                rep.eject_strikes = 0

    _BREAKER_VALUE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

    def _update_breaker_gauge(self) -> None:
        """Republish each replica's CircuitBreaker.snapshot() as the
        ``deepgo_fleet_breaker_state`` gauge (0 closed / 1 half-open /
        2 open) so breaker flaps reach the watchlist and dash, not just
        health()."""
        for rep in self._replicas:
            snap_fn = getattr(rep.engine, "breaker_snapshot", None)
            if snap_fn is None:
                continue
            try:
                state = (snap_fn() or {}).get("state")
            except Exception:  # noqa: BLE001 — a corpse mid-respawn
                continue
            self._obs_breaker.set(
                self._BREAKER_VALUE.get(state, 0.0),
                fleet=self.name, replica=str(rep.idx))

    def _respawn(self, rep: _Replica) -> None:
        """Background rebuild of one dead replica: bounded consecutive
        attempts with full-jitter backoff; the fleet keeps serving on the
        survivors the whole time."""
        flight_dump("fleet_respawn", fleet=self.name, replica=rep.idx,
                    consec=rep.consec_respawns + 1)
        while not self._closing.is_set():
            rep.consec_respawns += 1
            if rep.consec_respawns > self.config.max_respawns:
                with self._lock:
                    rep.state = "failed"
                self._update_serving_gauge()
                if self._metrics is not None:
                    self._metrics.write(
                        "fleet_replica_failed", fleet=self.name,
                        replica=rep.idx, respawns=rep.respawns)
                # a respawn giving up still wakes the parking lot: with
                # no rebuild left in flight the parked requests resolve
                # their typed exhaustion instead of waiting for a tick
                self._events.put(("respawned", (rep, False)))
                return
            # backoff waits on the closing event, not a bare sleep, so a
            # concurrent close() interrupts the wait instead of hanging
            # its join on a full backoff cap
            self._closing.wait(full_jitter_delay(
                rep.consec_respawns - 1, self.config.respawn_base_s,
                self.config.respawn_cap_s, self._rng))
            try:
                rep.engine.close(drain=False, timeout=1.0)
            except Exception:  # pragma: no cover — corpse cleanup only
                pass
            try:
                eng = self._make_replica(rep.idx)
                if self._current_params is not None:
                    self._apply_params(eng, self._current_params)
                if self.config.warm_on_respawn:
                    eng.warmup()
            except Exception:
                continue  # burns one consecutive-respawn budget slot
            if self._closing.is_set():
                try:
                    eng.close(drain=False, timeout=1.0)
                except Exception:
                    pass
                return
            with self._lock:
                rep.engine = eng
                rep.state = "serving"
                rep.respawns += 1
                rep.lat.clear()       # a fresh engine starts a fresh window
                rep.eject_strikes = 0
                self._respawns += 1
                total = self._respawns
            self._update_serving_gauge()
            self._obs_respawns.inc(fleet=self.name)
            if self._metrics is not None:
                self._metrics.write("fleet_respawn", fleet=self.name,
                                    replica=rep.idx,
                                    attempt=rep.consec_respawns,
                                    total_respawns=total)
            # the landed respawn re-dispatches the parking lot, and the
            # fresh engine gets a clean slate in each parked request's
            # exclusion set (it is not the corpse the request fled)
            self._events.put(("respawned", (rep, True)))
            return

    # -- hot weight reload -------------------------------------------------

    def reload(self, new_params, drain_timeout_s: float | None = None
               ) -> dict:
        """Roll new weights through the fleet, one replica at a time.

        ``new_params`` is a params pytree matching the serving model's
        structure/shapes (the bucket ladder and jit cache stay warm — the
        swap never recompiles), or a checkpoint path loaded against the
        current params as template. Protocol per replica: drain
        (placement skips it; in-flight requests finish on the weights
        they were submitted under), pointer-swap, rejoin — so in-flight
        futures never drop and capacity never dips below N-1. Replicas
        mid-respawn are skipped: the respawn itself applies the new
        weights, as does every later supervisor restart (the
        ``set_params`` override). Returns ``{"replicas": swapped,
        "seconds": wall}``. Concurrent reloads serialize."""
        self._check_alive()
        with self._reload_mutex:
            params = self._resolve_params(new_params)
            t0 = self._clock()
            # from this instant every respawn/rebuild converges on the
            # new weights, even for replicas the roll hasn't reached yet
            self._current_params = params
            # stale-weights answers are wrong answers: clear BEFORE the
            # roll (old-weights entries must not outlive the moment the
            # new checkpoint became the source of truth) and AFTER it
            # (forwards that ran mid-roll on a not-yet-swapped replica
            # filled under the new generation — legitimate old-or-new
            # answers while rolling, stale the instant the roll is done).
            # Generation capture in the cache refuses fills from flights
            # led before each clear, so no mixed-weights row survives.
            if self.cache is not None:
                self.cache.invalidate("reload_start")
            budget = (self.config.drain_timeout_s
                      if drain_timeout_s is None else drain_timeout_s)
            swapped = 0
            try:
                for rep in self._replicas:
                    if self._closing.is_set():
                        raise EngineClosed(
                            f"FleetRouter[{self.name}] closed mid-reload "
                            f"({swapped} replica(s) already swapped)")
                    with self._lock:
                        if rep.state != "serving":
                            continue  # respawn path applies the new weights
                        rep.state = "draining"
                    self._update_serving_gauge()
                    try:
                        deadline = self._clock() + budget
                        while (rep.pending > 0 and self._clock() < deadline
                               and not self._closing.is_set()):
                            self._sleep(0.002)
                        try:
                            faults.check("fleet_reload")
                        except faults.FaultError as e:
                            raise FleetReloadError(
                                f"FleetRouter[{self.name}] reload failed at "
                                f"replica {rep.idx} ({swapped} already "
                                "swapped; restarts/respawns will converge on "
                                "the new weights)") from e
                        self._apply_params(rep.engine, params)
                        swapped += 1
                    finally:
                        with self._lock:
                            if rep.state == "draining":
                                rep.state = "serving"
                        self._update_serving_gauge()
            finally:
                if self.cache is not None:
                    self.cache.invalidate("reload_end")
            dt = self._clock() - t0
            with self._lock:
                self._reloads += 1
            self._obs_reloads.inc(fleet=self.name)
            if self._metrics is not None:
                self._metrics.write("fleet_reload", fleet=self.name,
                                    replicas=swapped,
                                    seconds=round(dt, 4))
            return {"replicas": swapped, "seconds": dt}

    @staticmethod
    def _apply_params(engine, base_params) -> None:
        """Swap BASE params into one replica, through its variant's
        ``prepare_params`` hook when it carries one (an int8 replica
        re-quantizes the new checkpoint; an f32 replica takes it as
        is) — the single point reload and respawn share, so a variant
        replica can never be handed the raw f32 tree by one path and
        the prepared one by the other."""
        prepare = getattr(engine, "prepare_params", None)
        engine.set_params(prepare(base_params) if prepare is not None
                          else base_params)

    def _resolve_params(self, new):
        if isinstance(new, (str, os.PathLike)):
            from ..experiments import checkpoint as ckpt

            path = str(new)
            template = self._current_params
            if template is None:
                template = self._replicas[0].engine.params
            _, p_leaves, _ = ckpt.load_checkpoint(path)
            return ckpt.unflatten_like(template, p_leaves, path)
        return new

    # -- observability -----------------------------------------------------

    def _serving_count(self) -> int:
        with self._lock:
            return sum(r.state == "serving" for r in self._replicas)

    _STATE_VALUE = {"serving": 1.0, "draining": 0.5, "respawning": 0.5,
                    "failed": 0.0}

    def _update_serving_gauge(self) -> None:
        self._obs_serving.set(self._serving_count(), fleet=self.name)
        with self._lock:
            states = [(r.idx, r.state) for r in self._replicas]
        for idx, state in states:
            self._obs_replica_state.set(
                self._STATE_VALUE.get(state, 0.0),
                fleet=self.name, replica=str(idx))

    def _count_shed(self, tier: str, reason: str) -> None:
        with self._lock:
            self._shed[tier] += 1
        self._obs_shed.inc(fleet=self.name, tier=tier, reason=reason)

    def _counters(self) -> dict:
        with self._lock:
            return {
                "failovers": self._failovers,
                "respawns": self._respawns,
                "reloads": self._reloads,
                "poisoned": self._poisoned,
                "hedges": self._hedges,
                "hedge_wins": self._hedge_wins,
                "ejections": self._ejections,
                "integrity_failures": self._integrity_failures,
                "parks": self._parks,
                "shed": dict(self._shed),
            }

    def probe_targets(self) -> list:
        """(idx, engine) for every SERVING replica — the canary prober's
        placement-pinned view (deepgo_tpu/chaos/canary.py submits its
        sentinels directly to each engine, bypassing placement, so a
        corrupt replica can't hide behind a healthy peer)."""
        with self._lock:
            return [(r.idx, r.engine) for r in self._replicas
                    if r.state == "serving"]

    def _tier_latency(self) -> dict:
        out = {}
        with self._lock:
            snap = {t: list(lat) for t, lat in self._tier_lat.items()}
        for tier, lat in snap.items():
            arr = np.array(lat, dtype=np.float64)
            out[tier] = {
                "requests": int(arr.size),
                "p50_ms": round(float(np.percentile(arr, 50)) * 1000, 3)
                if arr.size else None,
                "p99_ms": round(float(np.percentile(arr, 99)) * 1000, 3)
                if arr.size else None,
            }
        return out

    def health(self) -> dict:
        """One snapshot of the whole fleet. ``state`` is strict on
        purpose: "serving" only at FULL strength, "degraded" while any
        replica is down-but-covered, "down" below ``min_serving`` — so a
        composed /healthz (health_from_engine) flips 503 the moment a
        replica dies and back to 200 when the respawn lands, and an
        orchestrator watching the endpoint sees the incident even though
        the fleet absorbed it."""
        with self._lock:
            reps = list(self._replicas)
        detail = []
        serving = 0
        for r in reps:
            entry = {"replica": r.idx, "state": r.state,
                     "pending": r.pending, "respawns": r.respawns}
            variant = getattr(r.engine, "variant", None)
            if variant is not None:
                entry["variant"] = variant
            platform = getattr(r.engine, "platform", None)
            if platform is not None:
                entry["platform"] = platform
                realized = getattr(r.engine, "platform_realized", None)
                if realized is not None:
                    entry["platform_realized"] = realized
            if r.state in ("serving", "draining"):
                try:
                    h = r.engine.health()
                except Exception as e:  # noqa: BLE001 — reported inline
                    entry["error"] = repr(e)
                    h = {"state": "failed"}
                entry["engine_state"] = h.get("state")
                entry["breaker"] = (h.get("breaker") or {}).get("state")
                entry["estimated_wait_s"] = h.get("estimated_wait_s")
                # a DRAINING replica still counts as healthy: a planned
                # sub-second reload drain is not an incident, and /healthz
                # must flip 503 for deaths, not for rolling upgrades
                if h.get("state") == "serving":
                    serving += 1
            detail.append(entry)
        if self._closing.is_set():
            state = "closed"
        elif serving == len(reps):
            state = "serving"
        elif serving >= self.config.min_serving:
            state = "degraded"
        else:
            state = "down"
        out = {"state": state, "replicas_serving": serving,
               "replicas_total": len(reps),
               "estimated_wait_s": self.estimated_wait_s(),
               "tiers": self._tier_latency(), "replicas": detail}
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        out.update(self._counters())
        return out

    def stats(self) -> dict:
        """Per-replica engine stats plus a ``fleet`` block — existing
        consumers (selfplay's stats["engine"], bench) surface the fleet
        counters without a second call site."""
        with self._lock:
            reps = list(self._replicas)
        replica_stats = []
        boards = 0
        for r in reps:
            try:
                s = r.engine.stats()
            except Exception as e:  # noqa: BLE001 — a corpse mid-respawn
                s = {"error": repr(e)}
            s["replica"] = r.idx
            s["state"] = r.state
            variant = getattr(r.engine, "variant", None)
            if variant is not None:
                s["variant"] = variant
            platform = getattr(r.engine, "platform", None)
            if platform is not None:
                s["platform"] = platform
            boards += s.get("boards") or 0
            replica_stats.append(s)
        with self._lock:
            failover_lat = list(self._failover_lat)
        fleet = self._counters()
        if self.cache is not None:
            fleet["cache"] = self.cache.stats()
        fleet.update({
            "replicas_serving": self._serving_count(),
            "replicas_total": len(reps),
            "boards": boards,
            "tiers": self._tier_latency(),
            "failover_p50_ms": round(float(np.percentile(
                np.array(failover_lat), 50)) * 1000, 3)
            if failover_lat else None,
        })
        return {"fleet": fleet, "replicas": replica_stats,
                "boards": boards}
