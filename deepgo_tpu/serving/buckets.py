"""Shape-bucket compile cache: pad any request count onto a fixed ladder.

A jitted forward compiles once per distinct batch shape, and a workload
whose live batch shrinks through arbitrary sizes (self-play as games
finish, a serving queue under variable load) would trigger a fresh XLA
compile per size. The fix is the FireCaffe discipline (arXiv:1511.00175)
applied to inference: keep a small ladder of fixed batch sizes on the
accelerator and pad every request count up to the nearest rung, so after
one warmup pass over the ladder no request shape ever compiles again.

Padding is free of numerical consequence here: each board's forward is
row-independent (conv stack, no cross-batch reduction), so the first n
rows of a padded forward are BIT-IDENTICAL to the unpadded forward —
tests/test_serving_engine.py asserts equality with ``==``, not allclose.
"""

from __future__ import annotations

import numpy as np

# The default rung spacing (~4x) keeps warmup to five compiles while
# capping pad waste at 4x on the smallest requests; 512 saturates the
# flagship net on one chip (bench.py runs it at 8192 only by stacking).
DEFAULT_BUCKETS = (1, 8, 32, 128, 512)

# Padding rows: an empty board scored for player 1 at rank 1 — the same
# filler selfplay.batched_log_probs always used, kept so padded dispatch
# stays comparable across the engine and the legacy helpers.
PAD_PLAYER = 1
PAD_RANK = 1


class BucketLadder:
    """An ascending ladder of batch sizes plus the pad/plan arithmetic."""

    def __init__(self, buckets=DEFAULT_BUCKETS):
        rungs = tuple(sorted({int(b) for b in buckets}))
        if not rungs or rungs[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        self.buckets = rungs

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n. Raises for n over the top rung — callers
        split oversize batches with plan() instead of padding down."""
        if n < 1:
            raise ValueError(f"need at least one request, got {n}")
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"{n} exceeds the largest bucket {self.max_bucket}")

    def plan(self, n: int) -> list[tuple[int, int, int]]:
        """Cover n rows with ladder-shaped dispatches:
        ``[(start, count, bucket), ...]``. Full top-rung chunks first (no
        padding), then one padded dispatch for the remainder."""
        out, start = [], 0
        while n - start >= self.max_bucket:
            out.append((start, self.max_bucket, self.max_bucket))
            start += self.max_bucket
        rest = n - start
        if rest:
            out.append((start, rest, self.bucket_for(rest)))
        return out

    def pad(self, packed: np.ndarray, players: np.ndarray, ranks: np.ndarray,
            bucket: int):
        """(packed, players, ranks) padded with empty-board filler rows up
        to ``bucket``; no copy when the count already sits on a rung."""
        n = len(packed)
        if bucket == n:
            return packed, players, ranks
        pad = bucket - n
        return (
            np.concatenate(
                [packed, np.zeros((pad,) + packed.shape[1:], packed.dtype)]),
            np.concatenate(
                [players, np.full(pad, PAD_PLAYER, players.dtype)]),
            np.concatenate([ranks, np.full(pad, PAD_RANK, ranks.dtype)]),
        )


def bucketed_forward(fn, packed: np.ndarray, players: np.ndarray,
                     ranks: np.ndarray, ladder: BucketLadder) -> np.ndarray:
    """Run ``fn(packed, players, ranks) -> (B, ...)`` over the ladder.

    Any request count dispatches as top-rung chunks plus one padded
    remainder, so ``fn`` (a jitted forward) only ever sees ladder shapes.
    Returns the first-n rows as one host array.
    """
    parts = []
    for start, count, bucket in ladder.plan(len(packed)):
        sl = slice(start, start + count)
        p, pl, rk = ladder.pad(packed[sl], players[sl], ranks[sl], bucket)
        parts.append(np.asarray(fn(p, pl, rk))[:count])
    return parts[0] if len(parts) == 1 else np.concatenate(parts)
