"""Resilience primitives for the serving path: typed shed/poison errors,
a closed/open/half-open circuit breaker, and full-jitter restart backoff.

Large fleets treat component failure as the steady state (FireCaffe,
arXiv:1511.00175: failure frequency grows linearly with worker count), so
the serving layer needs the same discipline PR 1 gave training. The
pieces here are deliberately tiny, lock-protected state machines with
injectable clocks — the supervisor (serving/supervisor.py) composes them,
and the tests drive every transition deterministically without sleeping.

Failure-handling vocabulary (every one is an ``EngineError``, so callers
that already catch the engine's typed failures keep working):

  EngineOverloaded   shed at submit(): the estimated queue wait already
                     exceeds the request's deadline — queueing it would
                     only manufacture a timeout later.
  CircuitOpen        shed at submit(): the engine is failing persistently
                     and the breaker fails callers fast instead of letting
                     each one discover the outage by timeout.
  PoisonedRequest    this request deterministically fails the forward on
                     its own (its batch neighbors succeeded without it);
                     the offending inputs are quarantined for postmortem.
  RestartsExhausted  the supervisor gave up rebuilding the engine after
                     ``max_restarts`` consecutive failed restarts.
"""

from __future__ import annotations

import threading
import time

from ..analysis.lockcheck import make_lock
from .engine import EngineError


class EngineOverloaded(EngineError):
    """submit() rejected by deadline-aware admission control."""


class CircuitOpen(EngineError):
    """submit() shed by an open circuit breaker (engine failing hard)."""


class PoisonedRequest(EngineError):
    """The request itself fails the forward; inputs quarantined."""


class RestartsExhausted(EngineError):
    """The supervisor's bounded restart budget ran out."""


def full_jitter_delay(attempt: int, base: float, cap: float, rng) -> float:
    """AWS-style full-jitter backoff: U(0, min(cap, base * 2**attempt)).

    Drawing the whole delay uniformly (not just +/- a fraction)
    decorrelates a herd of restarters/retriers that all observed the same
    failure at the same instant — the exponential envelope bounds the
    worst case, the jitter spreads the load. ``attempt`` counts from 0.
    """
    return rng.uniform(0.0, min(cap, base * (2.0 ** attempt)))


class CircuitBreaker:
    """Closed / open / half-open breaker with single-probe recovery.

    closed     normal operation; ``failures`` CONSECUTIVE failures open it
               (any success resets the count).
    open       ``allow()`` returns False — callers shed instantly — until
               ``reset_timeout_s`` has passed, then exactly one caller is
               let through as the probe (state moves to half-open).
    half-open  the probe is in flight; everyone else still sheds. The
               probe's success closes the breaker, its failure re-opens
               it (and restarts the recovery timer).

    ``clock`` is injectable (tests drive recovery without sleeping);
    ``on_transition(old, new)`` observes every state change — the
    supervisor turns those into MetricsWriter events.
    """

    def __init__(self, failures: int = 5, reset_timeout_s: float = 30.0,
                 clock=time.monotonic, on_transition=None,
                 name: str = "breaker"):
        if failures < 1:
            raise ValueError(f"failures must be >= 1, got {failures}")
        self.failures = failures
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._on_transition = on_transition
        self._lock = make_lock(name)
        self._state = "closed"
        self._consecutive = 0
        self._opened_at: float | None = None
        self._probe_ready = False   # open -> probe available immediately
        self._transitions = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _move(self, new: str) -> None:
        # lock held by caller
        old, self._state = self._state, new
        self._transitions += 1
        if self._on_transition is not None:
            self._on_transition(old, new)

    def allow(self) -> bool:
        """May a request proceed right now?

        In the open state, the first call at/after the recovery deadline
        is granted as THE probe (state -> half-open); in half-open, the
        probe is already out, so everyone sheds until it resolves."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                due = self._probe_ready or (
                    self._clock() - self._opened_at >= self.reset_timeout_s)
                if due:
                    self._probe_ready = False
                    self._move("half_open")
                    return True
                return False
            return False  # half_open: probe outstanding

    def cancel_probe(self) -> None:
        """The probe slot was granted but no request was actually sent
        (e.g. the submit then failed admission or backpressure): return
        to open with the probe immediately available to the next caller,
        so a shed probe can never wedge the breaker half-open forever."""
        with self._lock:
            if self._state == "half_open":
                self._probe_ready = True
                self._move("open")

    def record_success(self) -> None:
        """Any served request closes the breaker, whatever the state: a
        success is ground truth that the engine serves again. The probe
        dance exists for the no-traffic case — but the supervisor also
        replays parked requests after a restart, and those replays are
        real traffic whose success should not wait out reset_timeout_s."""
        with self._lock:
            self._consecutive = 0
            if self._state != "closed":
                self._opened_at = None
                self._probe_ready = False
                self._move("closed")

    def record_failure(self) -> None:
        with self._lock:
            if self._state == "half_open":
                self._opened_at = self._clock()
                self._move("open")
                return
            self._consecutive += 1
            if self._state == "closed" and self._consecutive >= self.failures:
                self._opened_at = self._clock()
                self._move("open")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "transitions": self._transitions,
            }
