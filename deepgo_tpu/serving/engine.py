"""Micro-batching inference engine: futures in, one padded dispatch out.

Callers — selfplay games, arena agents, an eventual GTP/eval frontend —
submit single-board requests and get ``concurrent.futures.Future``s. A
dispatcher thread coalesces up to ``max_bucket`` requests or
``max_wait_ms``, pads the batch onto the bucket ladder (buckets.py), runs
ONE device dispatch, and scatters result rows back to the futures. The
queue is bounded (backpressure: a flooded engine pushes back on
submitters instead of growing without bound), requests carry optional
deadlines, and dispatcher death surfaces on the next ``submit()`` — the
same worker-death contract as data.loader.AsyncLoader, for the same
reason: a silently dead thread turns every waiter into a deadlock.

Batching changes nothing numerically: forwards are row-independent, so a
request's row is bit-identical whether it rode alone or in a full bucket
(tests assert ``==``). What batching buys is throughput — one dispatch
amortizes host->device transfer and XLA dispatch overhead across every
coalesced request, the serving-side twin of the training loader's
superbatches.
"""

from __future__ import annotations

import dataclasses
import queue
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..analysis import xlacheck
from ..analysis.lockcheck import make_lock
from ..obs import get_registry
from ..utils import faults
from .buckets import DEFAULT_BUCKETS, BucketLadder


class EngineError(RuntimeError):
    """Base class for serving-engine failures."""


class EngineClosed(EngineError):
    """submit() after close(), or a pending request cancelled by close()."""


class EngineBusy(EngineError):
    """Non-blocking submit() against a full request queue (backpressure)."""


class BatchDispatchError(EngineError):
    """One coalesced dispatch failed inside the forward.

    Fails only the batch that rode the broken dispatch — the dispatcher
    survives, so one bad request cannot permanently kill the engine for
    every later submitter. Carries ``batch_size`` (live requests in the
    failed dispatch) so the resilience layer (serving/supervisor.py) can
    tell group failure (retry members individually: a neighbor may be
    poison) from lone failure (this request fails on its own). The
    underlying forward exception rides as ``__cause__``.
    """

    def __init__(self, message: str, batch_size: int):
        super().__init__(message)
        self.batch_size = batch_size


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Knobs for one engine. ``max_wait_ms`` is the latency/throughput
    trade: 0 dispatches whatever is queued immediately (lowest latency,
    worst occupancy under trickle load); a few ms lets concurrent
    submitters coalesce into one saturated dispatch (docs/serving.md)."""

    buckets: tuple[int, ...] = DEFAULT_BUCKETS
    max_wait_ms: float = 2.0
    max_queue: int = 4096
    timeout_s: float | None = None      # default per-request deadline
    latency_window: int = 2048          # samples kept for p50/p99
    metrics_interval: int = 100         # dispatches between metrics records


class _Request:
    __slots__ = ("packed", "player", "rank", "future", "t_submit", "deadline",
                 "solo", "trace", "workload")

    def __init__(self, packed, player, rank, deadline, solo=False,
                 trace=None, workload=None):
        self.packed = packed
        self.player = player
        self.rank = rank
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.deadline = deadline
        self.solo = solo
        self.trace = trace  # obs.tracing.TraceContext, or None (off)
        self.workload = workload  # obs.workload.WorkloadToken, or None (off)


class InferenceEngine:
    """One model, one dispatcher thread, many concurrent submitters.

    ``forward(params, packed, player, rank) -> (B, ...)`` is any jitted
    row-independent forward (policy log-probs, value win-probs); the
    engine is agnostic to what the rows mean.
    """

    def __init__(self, forward, params, config: EngineConfig | None = None,
                 name: str = "policy", metrics=None):
        self.config = config or EngineConfig()
        self.ladder = BucketLadder(self.config.buckets)
        self.name = name
        # DEEPGO_XLACHECK=1 arms the recompile sentinel: the forward is
        # wrapped with a per-engine compile counter (zero budget after
        # warmup); off, the fn passes through untouched and the dispatch
        # loop pays one attribute check (docs/static_analysis.md)
        self._forward = xlacheck.watch_compiles(forward, name=name)
        self._xla_on = xlacheck.enabled()
        self._params = params
        self._metrics = metrics
        self._queue: queue.Queue[_Request] = queue.Queue(
            maxsize=self.config.max_queue)
        self._closing = threading.Event()   # no new submits
        self._cancel = threading.Event()    # fail pending instead of draining
        self._error: BaseException | None = None
        self._lock = make_lock(f"engine.{name}")
        self._latencies: deque[float] = deque(maxlen=self.config.latency_window)
        # forward-only durations of recent successful dispatches: the
        # supervisor's admission control estimates queue wait from their
        # p50 (a small window keeps the estimate current under load shifts)
        self._dispatch_secs: deque[float] = deque(maxlen=64)
        # full (max-bucket) windows tracked separately: queue drain under
        # backlog runs in max-bucket windows, and the all-sizes p50
        # underestimates their cost badly when small interactive
        # dispatches dominate the recent mix
        self._window_secs: deque[float] = deque(maxlen=32)
        # solo lane: isolation retries from the resilience layer dispatch
        # strictly alone (never coalesced), so a retried request's failure
        # is attributable to IT. Internal — bypasses the bounded queue;
        # membership is capped by the batch that failed.
        self._solo: deque[_Request] = deque()
        self._bucket_hits: dict[int, int] = {}
        self._dispatches = 0
        self._dispatch_failures = 0
        self._boards = 0
        self._padded_boards = 0
        self._timeouts = 0
        self._warm_shapes = 0
        self._join_timed_out = False
        self._t_start = time.monotonic()
        # process-registry aggregates (docs/observability.md), labeled by
        # engine name so shared fleets stay distinguishable on /metrics;
        # metric objects cached up front — the dispatch loop pays one
        # observe()/inc() per event, never a registry lookup
        reg = get_registry()
        self._obs_dispatch = reg.histogram(
            "deepgo_serving_dispatch_seconds",
            "forward duration of one coalesced dispatch")
        self._obs_request = reg.histogram(
            "deepgo_serving_request_seconds",
            "request latency submit-to-result")
        self._obs_boards = reg.counter(
            "deepgo_serving_boards_total", "boards served")
        self._obs_dispatches = reg.counter(
            "deepgo_serving_dispatches_total", "coalesced dispatches run")
        self._obs_failures = reg.counter(
            "deepgo_serving_dispatch_failures_total",
            "dispatches failed inside the forward")
        self._obs_timeouts = reg.counter(
            "deepgo_serving_timeouts_total",
            "requests expired before dispatch")
        self._obs_occupancy = reg.gauge(
            "deepgo_serving_occupancy",
            "real boards / padded boards since engine start")
        self._obs_depth = reg.gauge(
            "deepgo_serving_queue_depth",
            "requests waiting in the bounded queue")
        self._thread = threading.Thread(
            target=self._dispatch_loop, name=f"serving-{name}", daemon=True)
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------

    def warmup(self) -> int:
        """Compile every ladder rung up front (empty-board batches), so the
        steady state performs zero compilations. Returns rung count.

        Each rung's second (post-compile) forward is timed and seeded
        into the rolling dispatch-latency window, so admission control
        has a latency prior before the first live dispatch. Without the
        seed the estimate stays None under a tight-deadline flood —
        queued requests expire before any dispatch succeeds, so the
        congestion signal depends on exactly the work congestion
        prevents, and the door never sheds."""
        for b in self.ladder.buckets:
            packed = np.zeros((b, 9, 19, 19), dtype=np.uint8)
            player = np.ones(b, dtype=np.int32)
            rank = np.ones(b, dtype=np.int32)
            args = (self._params, packed, player, rank)
            if self._xla_on:
                # stage exactly like the armed dispatch: a weak-typed
                # Python scalar traced here and a device_put-concrete
                # one there would be DIFFERENT programs — the sentinel
                # would (correctly) call the first dispatch a storm
                args = xlacheck.stage_h2d(*args)
            np.asarray(self._forward(*args))
            t_fwd = time.monotonic()
            np.asarray(self._forward(*args))
            dt = time.monotonic() - t_fwd
            with self._lock:
                self._dispatch_secs.append(dt)
                if b == self.ladder.max_bucket:
                    self._window_secs.append(dt)
        self._warm_shapes = len(self.ladder.buckets)
        # warmup over: from here any compile is a steady-state compile —
        # a typed RecompileStorm finding when the sentinel is armed
        xlacheck.mark_warm(self._forward)
        return self._warm_shapes

    def compile_cache_size(self) -> int | None:
        """Distinct shapes the jitted forward has compiled (None when the
        callable doesn't expose its jit cache) — what the zero-recompile
        tests assert stays flat after warmup."""
        cache_size = getattr(self._forward, "_cache_size", None)
        return cache_size() if callable(cache_size) else None

    @property
    def params(self):
        """The weights the next dispatch will use (see set_params)."""
        return self._params

    def set_params(self, params) -> None:
        """In-place weight hot-swap: a pure pointer swap, no pause.

        The dispatcher reads ``self._params`` once per dispatch, so an
        atomic attribute assignment is the entire protocol — in-flight
        dispatches finish on the weights they started with, the next
        dispatch picks up the new ones, and nothing recompiles as long as
        the new pytree matches the old one's structure/shapes/dtypes (the
        jit cache is keyed on those, and the bucket ladder shapes never
        change). The fleet reload path (serving/fleet.py) drains a
        replica first so a request's weights are never ambiguous."""
        self._params = params

    def _check_alive(self) -> None:
        if self._error is not None:
            raise EngineError(
                f"InferenceEngine[{self.name}] dispatcher thread died"
            ) from self._error
        if self._closing.is_set():
            raise EngineClosed(f"InferenceEngine[{self.name}] is closed")

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting work and shut the dispatcher down.

        ``drain=True`` processes everything already queued before the
        thread exits (every pending future resolves); ``drain=False``
        fails pending futures with EngineClosed instead. Either way
        close() returns once the thread is joined — it never leaves
        waiters hanging on futures nobody will resolve."""
        if not drain:
            self._cancel.set()
        self._closing.set()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # a wedged dispatcher (blocked inside a device claim holding
            # the GIL) must be VISIBLE: record it in stats() and say so on
            # stderr instead of returning as if the shutdown were clean
            self._join_timed_out = True
            print(
                f"InferenceEngine[{self.name}] dispatcher did not exit "
                f"within {timeout}s at close; thread leaked (likely wedged "
                "inside the forward / device claim)",
                file=sys.stderr, flush=True)
        # belt and braces: anything still queued after the join (thread
        # died, join timed out) must not strand its waiters
        self._fail_pending(EngineClosed(
            f"InferenceEngine[{self.name}] closed with request pending"))
        if self._metrics is not None:
            self._metrics.write("serving_close", engine=self.name,
                                **self.stats())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- submission --------------------------------------------------------

    def submit(self, packed: np.ndarray, player: int, rank: int,
               timeout_s: float | None = None, block: bool = True,
               solo: bool = False, trace=None, workload=None) -> Future:
        """Queue one board; returns a Future resolving to its result row.

        ``timeout_s`` (default: config.timeout_s) bounds queue-to-result
        time — an expired request fails with TimeoutError instead of
        occupying a dispatch. With ``block=False`` a full queue raises
        EngineBusy immediately; blocking submits wait for space but keep
        re-checking engine liveness so a dead dispatcher can't strand
        them. ``solo=True`` routes the request through the isolation lane:
        it dispatches strictly alone (the supervisor's batch-poison
        bisection), skipping the bounded queue. ``trace`` is the caller's
        TraceContext (obs/tracing.py) — the timeline gains queued/
        coalesced/dispatched/resolved stamps; when tracing is armed and
        no outer layer owns the request, the engine starts (and
        finishes) a trace of its own. ``workload`` is the caller's
        WorkloadToken (obs/workload.py) under the same ownership rule —
        the outermost layer records arrival/outcome, the engine stamps
        the bucket the request coalesced into."""
        self._check_alive()
        owned = None
        if trace is None:
            from ..obs import tracing

            trace = owned = tracing.start_request(engine=self.name)
        if trace is not None:
            trace.mark("queued", engine=self.name)
        wl_owned = None
        if workload is None:
            from ..obs import workload as workload_mod

            workload = wl_owned = workload_mod.note_request(
                packed, player, rank, engine=self.name)
        timeout_s = self.config.timeout_s if timeout_s is None else timeout_s
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        req = _Request(np.asarray(packed), int(player), int(rank), deadline,
                       solo=solo, trace=trace, workload=workload)
        if owned is not None:
            req.future.add_done_callback(owned.finish_future)
        if wl_owned is not None:
            req.future.add_done_callback(wl_owned.finish_future)
        if solo:
            self._solo.append(req)
            return req.future
        while True:
            try:
                self._queue.put(req, block=block, timeout=0.1)
                return req.future
            except queue.Full:
                if not block:
                    raise EngineBusy(
                        f"InferenceEngine[{self.name}] queue full "
                        f"({self.config.max_queue} pending)") from None
                self._check_alive()

    def evaluate(self, packed: np.ndarray, players: np.ndarray,
                 ranks: np.ndarray, timeout_s: float | None = None
                 ) -> np.ndarray:
        """Blocking convenience: submit every row, gather in order.

        This is how the lockstep drivers (match harness, corpus tools)
        ride the engine — their batch dissolves into independent requests
        that coalesce with whatever else is in flight."""
        futures = [self.submit(packed[i], int(players[i]), int(ranks[i]),
                               timeout_s=timeout_s)
                   for i in range(len(packed))]
        return np.stack([f.result() for f in futures])

    # -- dispatcher --------------------------------------------------------

    def _collect(self) -> list[_Request] | None:
        """One coalescing window: block for the first request, then gather
        until the ladder's top rung fills or ``max_wait_ms`` elapses.
        Solo requests (the isolation lane) preempt the window and dispatch
        strictly alone. Returns None when closing and everything is
        empty."""
        while True:
            if self._solo:
                return [self._solo.popleft()]
            try:
                first = self._queue.get(timeout=0.05)
                break
            except queue.Empty:
                if self._closing.is_set() and not self._solo:
                    return None
        batch = [first]
        t_end = time.monotonic() + self.config.max_wait_ms / 1000.0
        while len(batch) < self.ladder.max_bucket:
            # a closing engine stops waiting for stragglers: drain eagerly
            remaining = 0.0 if self._closing.is_set() \
                else t_end - time.monotonic()
            try:
                batch.append(self._queue.get(
                    block=remaining > 0, timeout=max(remaining, 0.0) or None))
            except queue.Empty:
                break
        return batch

    def _dispatch(self, batch: list[_Request]) -> None:
        now = time.monotonic()
        live = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                if r.trace is not None:
                    r.trace.mark("expired", engine=self.name)
                r.future.set_exception(TimeoutError(
                    f"request expired after {now - r.t_submit:.3f}s in "
                    f"InferenceEngine[{self.name}] queue"))
                with self._lock:
                    self._timeouts += 1
                self._obs_timeouts.inc(engine=self.name)
            elif r.future.set_running_or_notify_cancel():
                live.append(r)
        if not live:
            return
        n = len(live)
        bucket = self.ladder.bucket_for(n)
        for r in live:
            # workload tap: one attr set per armed request — the record
            # gains the ladder rung the request actually dispatched on
            if r.workload is not None:
                r.workload.bucket = bucket
        traced = [r for r in live if r.trace is not None]
        for r in traced:
            r.trace.mark("coalesced", engine=self.name, batch=n,
                         bucket=bucket)
            r.trace.set(bucket=bucket, engine=self.name)
        packed, players, ranks = self.ladder.pad(
            np.stack([r.packed for r in live]),
            np.array([r.player for r in live], dtype=np.int32),
            np.array([r.rank for r in live], dtype=np.int32), bucket)
        for r in traced:
            r.trace.mark("dispatched", engine=self.name)
        t_fwd = time.monotonic()
        try:
            faults.check("serving_forward")
            # gray-failure hooks (deepgo_tpu/chaos): an injected brownout
            # sleeps INSIDE the timed dispatch window so the slowdown is
            # visible to every latency surface (dispatch histogram,
            # estimated_wait_s, the fleet's outlier ejection) exactly
            # like a real slow replica; the sleep itself lives in the
            # faults harness, not here
            faults.maybe_slow("serving_slow", self.name)
            if self._xla_on:
                # the DECLARED h2d point: stage explicitly so the armed
                # transfer guard proves the guarded forward performs no
                # implicit transfer (an implicit one raises at its line)
                params, packed, players, ranks = xlacheck.stage_h2d(
                    self._params, packed, players, ranks)
                with xlacheck.transfer_guard(f"engine.{self.name}"):
                    out = self._forward(params, packed, players, ranks)
            else:
                out = self._forward(self._params, packed, players, ranks)
            # lint: allow[hot-sync] dispatch-time d2h is the DECLARED materialization point: one fetch per coalesced batch (docs/static_analysis.md)
            out = np.asarray(out)
            if faults.corrupt_due("serving_corrupt", self.name):
                # silently WRONG output: sign-flipped and shifted, so a
                # log-prob row comes back denormalized with its argmax
                # at the original argmin — the gray failure the canary
                # probes and the fleet integrity guard exist to catch
                out = 1.0 - out
        except BaseException as e:  # noqa: BLE001 — typed onto the futures
            # contain the blast radius to THIS batch: its futures fail with
            # a typed wrapper (cause attached), the dispatcher keeps
            # serving everyone else. The supervisor bisects the batch by
            # retrying members through the solo lane.
            err = BatchDispatchError(
                f"dispatch of {n} request(s) failed in "
                f"InferenceEngine[{self.name}]: {e!r}", n)
            err.__cause__ = e
            with self._lock:
                self._dispatch_failures += 1
            self._obs_failures.inc(engine=self.name)
            for r in live:
                if r.trace is not None:
                    r.trace.mark("failed", engine=self.name,
                                 error=type(e).__name__, batch=n)
                if not r.future.done():
                    r.future.set_exception(err)
            return
        t_done = time.monotonic()
        for r in traced:
            r.trace.mark("resolved", engine=self.name)
        for i, r in enumerate(live):
            r.future.set_result(out[i])
        with self._lock:
            self._dispatches += 1
            self._boards += n
            self._padded_boards += bucket
            self._bucket_hits[bucket] = self._bucket_hits.get(bucket, 0) + 1
            self._latencies.extend(t_done - r.t_submit for r in live)
            self._dispatch_secs.append(t_done - t_fwd)
            if bucket == self.ladder.max_bucket:
                self._window_secs.append(t_done - t_fwd)
            occupancy = self._boards / self._padded_boards
            write_metrics = (
                self._metrics is not None
                and self._dispatches % self.config.metrics_interval == 0)
        # bucket label: the roofline join (obs/costmodel.py) divides the
        # rung's AOT FLOPs by this series' mean to get achieved FLOP/s —
        # one extra label on an existing observe, no new hot-path work
        self._obs_dispatch.observe(t_done - t_fwd, engine=self.name,
                                   bucket=bucket)
        for r in live:
            self._obs_request.observe(t_done - r.t_submit, engine=self.name)
        self._obs_dispatches.inc(engine=self.name)
        self._obs_boards.inc(n, engine=self.name)
        self._obs_occupancy.set(occupancy, engine=self.name)
        self._obs_depth.set(self._queue.qsize(), engine=self.name)
        if write_metrics:
            self._metrics.write("serving", engine=self.name, **self.stats())

    def _fail_pending(self, exc: BaseException) -> None:
        while self._solo:
            try:
                req = self._solo.popleft()
            except IndexError:  # pragma: no cover — concurrent drain
                break
            if not req.future.done():
                req.future.set_exception(exc)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if not req.future.done():
                req.future.set_exception(exc)

    def _dispatch_loop(self) -> None:
        try:
            while True:
                if self._cancel.is_set():
                    self._fail_pending(EngineClosed(
                        f"InferenceEngine[{self.name}] closed before "
                        "this request dispatched"))
                    return
                batch = self._collect()
                if batch is None:
                    return
                # dispatcher-death fault point: fires OUTSIDE the per-batch
                # containment, so an injected fault here exercises the real
                # thread-death path (stashed error, failed futures, next
                # submit() raises) that the supervisor's restart absorbs
                faults.check("serving_dispatch")
                # replica-scoped variant of the same death: a chaos
                # scenario kills engine "bench-1" of a fleet by name
                # while its peers keep serving (deepgo_tpu/chaos)
                faults.check(f"serving_dispatch.{self.name}")
                self._dispatch(batch)
        except BaseException as e:  # noqa: BLE001 — surfaced via submit()
            # AsyncLoader._worker's contract: stash the error, fail every
            # in-flight future, and let the next submit() re-raise it —
            # never leave waiters blocked on futures a dead thread owns.
            self._error = e
            self._closing.set()
            if "batch" in locals() and batch:
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
            self._fail_pending(e)

    # -- observability -----------------------------------------------------

    def queue_depth(self) -> int:
        """Requests waiting in the bounded queue right now (approximate —
        the dispatcher drains concurrently)."""
        return self._queue.qsize()

    def dispatch_p50_s(self) -> float | None:
        """Rolling median forward duration of recent successful dispatches
        (seconds), or None before the first one. The admission-control
        input: estimated queue wait = p50 x pending dispatch windows."""
        with self._lock:
            if not self._dispatch_secs:
                return None
            return float(np.median(self._dispatch_secs))

    def window_p50_s(self) -> float | None:
        """Rolling median duration of FULL (max-bucket) dispatch windows,
        falling back to the all-sizes median before the first full window.
        The admission cost-per-window input: a backlog drains in
        max-bucket windows, and under a mixed workload the all-sizes p50
        collapses toward the small interactive dispatches — estimating a
        large queue's drain time from 1-board forwards blinds the door
        exactly when coexistence needs it."""
        with self._lock:
            if self._window_secs:
                return float(np.median(self._window_secs))
            if not self._dispatch_secs:
                return None
            return float(np.median(self._dispatch_secs))

    def stats(self) -> dict:
        """Snapshot of the engine counters: request p50/p99 latency (ms,
        submit-to-result over the sliding window), mean batch occupancy
        (real boards / padded boards — the pad-waste measure), per-bucket
        dispatch histogram, and boards/sec since construction."""
        with self._lock:
            lat = np.array(self._latencies, dtype=np.float64)
            dt = max(time.monotonic() - self._t_start, 1e-9)
            return {
                "dispatches": self._dispatches,
                "boards": self._boards,
                "boards_per_sec": round(self._boards / dt, 1),
                "occupancy": round(
                    self._boards / self._padded_boards, 4)
                if self._padded_boards else None,
                "bucket_hits": {str(k): v for k, v in
                                sorted(self._bucket_hits.items())},
                "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 3)
                if lat.size else None,
                "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 3)
                if lat.size else None,
                "timeouts": self._timeouts,
                "dispatch_failures": self._dispatch_failures,
                "dispatcher_wedged": self._join_timed_out,
                "warm_shapes": self._warm_shapes,
            }
