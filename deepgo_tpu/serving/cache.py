"""Content-addressed position cache with request coalescing.

The policy network is a pure function of (packed planes, player, rank),
and the workload observatory (PR 15) measured a 68.2% projected hit rate
on an opening-heavy capture — the single largest untapped throughput
multiplier in the serving stack. This module is that multiplier:
a bounded LRU in front of ``FleetRouter.submit`` keyed on the PR 15
content digests (``utils/digest.py``), with three protocol layers on
top of plain lookup:

  * **keying** — ``exact`` keys on the sha256-64 of the dispatch row;
    ``canonical`` keys on the 8-fold-symmetry orbit minimum, so all
    dihedral views of one position share a single entry. A canonical
    entry stores the forward output of the CANONICAL view; a hit from
    any view is mapped back through the inverse dihedral permutation
    (``digest.INV_PERMS``, the same frozen table ``ops/augment`` bakes
    into training) — for an equivariant forward the remap is a pure
    gather, so parity with an uncached forward is bitwise. The plain
    f32 CNN is NOT architecturally equivariant (only the fused ``sym``
    variant is), so ``canonical`` is a config choice, not the default.
  * **coalescing** — N in-flight submits for one key attach as
    followers to one leader; the fleet runs exactly one forward. A
    failed/timed-out leader never poisons its followers: the leader's
    own caller sees its error, the next follower is PROMOTED and
    re-dispatched, and the chain terminates because every promotion
    consumes a waiter.
  * **invalidation** — stale-weights answers are wrong answers. The
    router bumps the cache generation and clears entries at BOTH ends
    of ``fleet.reload()``; every leader captures the generation when it
    starts, and ``complete_ok`` refuses to publish a fill from an older
    generation — so a forward that raced a weight roll can never leave
    a mixed-weights row behind for later traffic.
      ``deepgo_cache_stale_hits_total`` counts entries SERVED from a
    dead generation; the clear-on-invalidate discipline makes it
    structurally zero and the chaos campaign's integrity re-check
    asserts it stays there.

Per-tier bypass (``CacheConfig.bypass_tiers``) lets batch-tier bulk
scans opt out of polluting the LRU entirely — no lookup, no fill.

``simulate`` replays a captured key stream through the same eviction
policy offline: the achieved (not just projected) hit rate per cache
size that ``cli workload analyze --simulate-cache`` reports for
capacity planning.

The cache owns keys, storage, and waiter bookkeeping; the ROUTER owns
dispatch and calls ``join`` / ``complete_ok`` / ``complete_err`` /
``invalidate`` (see fleet.py "the cached door"). Everything here is
thread-safe under one lock; resolution of waiter futures happens
outside it.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Iterable

import numpy as np

from ..analysis.lockcheck import make_lock
from ..obs import get_registry
from ..utils import digest as digest_mod

KEYINGS = ("exact", "canonical")


class CacheKeyingError(RuntimeError):
    """A canonical-key remap was asked of an output shape that has no
    per-point axis to permute (not a scalar, last dim != 361)."""


@dataclass(frozen=True)
class CacheConfig:
    """Position-cache policy knobs.

    capacity      — max entries; 0 disables storage (coalescing still
                    works: in-flight dedup needs no LRU).
    keying        — "exact" (sha256-64 of the dispatch row) or
                    "canonical" (8-fold-symmetry orbit minimum; requires
                    an equivariant forward for bitwise parity).
    bypass_tiers  — tiers that skip the cache entirely (no lookup, no
                    fill, no coalescing): batch-tier bulk scans must not
                    evict the interactive working set.
    coalesce      — attach concurrent same-key submits to one leader.
    """

    capacity: int = 4096
    keying: str = "exact"
    bypass_tiers: tuple = ("batch",)
    coalesce: bool = True

    def __post_init__(self):
        if self.capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {self.capacity}")
        if self.keying not in KEYINGS:
            raise ValueError(f"keying {self.keying!r} not in {KEYINGS}")


class Waiter:
    """One caller future riding an in-flight forward, plus everything a
    promotion needs to re-dispatch it (deadline/trace belong to the
    waiter, not the key)."""

    __slots__ = ("future", "k", "tier", "deadline", "trace")

    def __init__(self, future, k, tier, deadline, trace):
        self.future = future
        self.k = k
        self.tier = tier
        self.deadline = deadline
        self.trace = trace


class _InFlight:
    """One leader forward and its followers. ``generation`` is captured
    at creation: a fill whose generation is no longer current is
    discarded (the answer still serves its waiters — it was computed
    under SOME consistent weights — it just never enters storage)."""

    __slots__ = ("packed", "player", "rank", "generation", "waiters")

    def __init__(self, packed, player, rank, generation, waiter):
        self.packed = packed
        self.player = player
        self.rank = rank
        self.generation = generation
        self.waiters = [waiter]


class _Entry:
    __slots__ = ("row", "generation", "nbytes")

    def __init__(self, row: np.ndarray, generation: int):
        self.row = row
        self.generation = generation
        self.nbytes = int(row.nbytes)


class PositionCache:
    """Bounded content-addressed result cache + coalescing table.

    Driven by the router; usable standalone in tests. All counters are
    mirrored to the shared obs registry under ``deepgo_cache_*`` with a
    ``kind`` label carrying the keying mode.
    """

    def __init__(self, config: CacheConfig | None = None,
                 name: str = "cache", metrics=None,
                 clock=time.monotonic):
        self.config = config or CacheConfig()
        self.name = name
        self._metrics = metrics
        self._clock = clock
        self._lock = make_lock(f"cache.{name}")
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self._inflight: dict[str, _InFlight] = {}
        self._generation = 0
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._coalesced = 0
        self._evictions = 0
        self._invalidations = 0
        self._bypassed = 0
        self._stale_hits = 0     # gen-mismatched entries SERVED: never
        self._stale_blocked = 0  # gen-mismatched entries dropped unserved
        reg = get_registry()
        self._obs_hits = reg.counter(
            "deepgo_cache_hits_total",
            "requests served from the position cache")
        self._obs_misses = reg.counter(
            "deepgo_cache_misses_total",
            "cache lookups that went to a forward (leader dispatches)")
        self._obs_coalesced = reg.counter(
            "deepgo_cache_coalesced_total",
            "requests attached as followers to an in-flight leader")
        self._obs_evictions = reg.counter(
            "deepgo_cache_evictions_total",
            "entries dropped by the LRU bound")
        self._obs_invalidations = reg.counter(
            "deepgo_cache_invalidations_total",
            "generation bumps (reload starts/ends) clearing the cache")
        self._obs_stale = reg.counter(
            "deepgo_cache_stale_hits_total",
            "entries SERVED from a dead generation — structurally zero; "
            "the chaos integrity re-check asserts it stays there")
        self._obs_entries = reg.gauge(
            "deepgo_cache_entries", "positions currently cached")
        self._obs_bytes = reg.gauge(
            "deepgo_cache_bytes", "bytes held by cached result rows")

    # -- keying ------------------------------------------------------------

    def prepare(self, packed: np.ndarray, player: int, rank: int
                ) -> tuple[str, np.ndarray, int]:
        """(key, dispatch_packed, k): the cache key for this request,
        the packed view a leader should actually dispatch, and the
        symmetry index mapping the dispatched view back to the request
        (0 under exact keying — dispatch is the request itself)."""
        if self.config.keying == "canonical":
            return digest_mod.canonicalize(packed, player, rank)
        return (digest_mod.exact_digest(packed, player, rank),
                np.asarray(packed), 0)

    def bypass(self, tier: str | None) -> bool:
        if tier in self.config.bypass_tiers:
            with self._lock:
                self._bypassed += 1
            return True
        return False

    def _remap(self, row: np.ndarray, k: int) -> np.ndarray:
        """Map a stored canonical-view output to the waiter's view. A
        scalar output is symmetry-invariant (remap is the identity); a
        (..., 361) row gathers through the pinned inverse table; any
        other shape cannot be served across views."""
        arr = np.asarray(row)
        if k == 0 or arr.ndim == 0:
            return arr
        if arr.shape[-1] != digest_mod.NUM_POINTS:
            raise CacheKeyingError(
                f"canonical keying cannot remap output shape {arr.shape} "
                f"(expected scalar or last dim {digest_mod.NUM_POINTS})")
        return digest_mod.remap_from_canonical(arr, k)

    # -- the coalescing protocol ------------------------------------------

    def join(self, key: str, waiter: Waiter) -> tuple[str, np.ndarray | None]:
        """Atomically classify one request against storage + in-flight:

        ("hit", row)      — stored entry, already remapped to the
                            waiter's view; resolve the caller now.
        ("follower", None) — a leader is in flight; the waiter is queued
                            and will be resolved by ``complete_*``.
        ("leader", None)  — nobody is computing this key; the caller
                            must dispatch it and report back.
        """
        tier = waiter.tier or "none"
        kind = self.config.keying
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                if entry.generation != self._generation:
                    # invalidate() clears storage, so a dead-generation
                    # entry should not exist; drop it UNSERVED if one
                    # ever does — the miss path recomputes
                    self._drop_locked(key, entry)
                    self._stale_blocked += 1
                else:
                    self._entries.move_to_end(key)
                    self._hits += 1
                    row = entry.row
                    self._obs_hits.inc(cache=self.name, kind=kind, tier=tier)
                    return "hit", self._remap(row, waiter.k)
            flight = self._inflight.get(key)
            if flight is not None and self.config.coalesce:
                flight.waiters.append(waiter)
                self._coalesced += 1
                self._obs_coalesced.inc(cache=self.name, kind=kind,
                                        tier=tier)
                return "follower", None
            self._misses += 1
            self._obs_misses.inc(cache=self.name, kind=kind, tier=tier)
            return "leader", None

    def lead(self, key: str, packed: np.ndarray, player: int, rank: int,
             waiter: Waiter) -> None:
        """Register the leader's in-flight record (after ``join``
        returned "leader"). Kept separate so the router can refuse to
        lead — e.g. coalescing disabled — without poisoning the table."""
        with self._lock:
            self._inflight[key] = _InFlight(
                packed, int(player), int(rank), self._generation, waiter)

    def complete_ok(self, key: str, row) -> list[tuple[Waiter, object]]:
        """The leader's forward succeeded: publish (same-generation
        fills only) and hand back ``(waiter, value)`` pairs — values
        already remapped per waiter — for the router to resolve outside
        the cache lock."""
        arr = np.asarray(row)
        out = []
        with self._lock:
            flight = self._inflight.pop(key, None)
            if flight is None:
                return out
            if (flight.generation == self._generation
                    and self.config.capacity > 0):
                stored = np.array(arr)  # private copy; callers may mutate
                stored.setflags(write=False)
                prev = self._entries.pop(key, None)
                if prev is not None:
                    self._bytes -= prev.nbytes
                entry = _Entry(stored, flight.generation)
                self._entries[key] = entry
                self._bytes += entry.nbytes
                while len(self._entries) > self.config.capacity:
                    _, old = self._entries.popitem(last=False)
                    self._bytes -= old.nbytes
                    self._evictions += 1
                    self._obs_evictions.inc(cache=self.name,
                                            kind=self.config.keying)
                self._update_gauges_locked()
            for w in flight.waiters:
                try:
                    out.append((w, self._remap(arr, w.k)))
                except CacheKeyingError as e:
                    out.append((w, e))
        return out

    def complete_err(self, key: str
                     ) -> tuple[Waiter | None, Waiter | None, object | None]:
        """The leader's forward failed. Returns ``(leader, promoted,
        dispatch)``: the leader waiter (its caller gets the error — a
        failure is the leader's own), the next follower promoted to
        leader (re-dispatch it; None when no followers remain), and the
        ``(packed, player, rank)`` triple the promotion must submit."""
        with self._lock:
            flight = self._inflight.get(key)
            if flight is None:
                return None, None, None
            leader = flight.waiters.pop(0) if flight.waiters else None
            if not flight.waiters:
                del self._inflight[key]
                return leader, None, None
            promoted = flight.waiters[0]
            return leader, promoted, (flight.packed, flight.player,
                                      flight.rank)

    def drop_flight(self, key: str) -> list[Waiter]:
        """Remove one in-flight record wholesale (shutdown sweep) and
        return every waiter still riding it."""
        with self._lock:
            flight = self._inflight.pop(key, None)
            return list(flight.waiters) if flight is not None else []

    def inflight_keys(self) -> list[str]:
        with self._lock:
            return list(self._inflight)

    # -- invalidation ------------------------------------------------------

    def invalidate(self, reason: str = "reload") -> int:
        """Bump the generation and clear storage. In-flight leaders keep
        computing — their answers still serve their waiters — but their
        fills are now refused (generation mismatch). Returns the number
        of entries dropped."""
        with self._lock:
            dropped = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._generation += 1
            self._invalidations += 1
            self._update_gauges_locked()
        self._obs_invalidations.inc(cache=self.name,
                                    kind=self.config.keying, reason=reason)
        if self._metrics is not None:
            self._metrics.write("cache_invalidate", cache=self.name,
                                reason=reason, dropped=dropped)
        return dropped

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    # -- accounting --------------------------------------------------------

    def _drop_locked(self, key: str, entry: _Entry) -> None:
        self._entries.pop(key, None)
        self._bytes -= entry.nbytes
        self._update_gauges_locked()

    def _update_gauges_locked(self) -> None:
        self._obs_entries.set(len(self._entries), cache=self.name)
        self._obs_bytes.set(self._bytes, cache=self.name)

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses
            return {
                "keying": self.config.keying,
                "capacity": self.config.capacity,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self._hits,
                "misses": self._misses,
                "coalesced": self._coalesced,
                "evictions": self._evictions,
                "invalidations": self._invalidations,
                "bypassed": self._bypassed,
                "stale_hits": self._stale_hits,
                "stale_blocked": self._stale_blocked,
                "hit_rate": (self._hits / total) if total else None,
                "inflight": len(self._inflight),
                "generation": self._generation,
            }


# -- offline simulation ----------------------------------------------------

def simulate(keys: Iterable[str], capacity: int) -> dict:
    """Replay a key stream through the production eviction policy (LRU,
    same order of operations) and report the ACHIEVED hit rate — what
    ``cli workload analyze --simulate-cache`` uses for capacity
    planning. Coalescing is not modeled: a capture is sequential, so
    in-flight overlap is a live-only effect."""
    if capacity < 0:
        raise ValueError(f"capacity must be >= 0, got {capacity}")
    lru: OrderedDict[str, None] = OrderedDict()
    hits = misses = evictions = 0
    for key in keys:
        if key in lru:
            hits += 1
            lru.move_to_end(key)
            continue
        misses += 1
        if capacity > 0:
            lru[key] = None
            if len(lru) > capacity:
                lru.popitem(last=False)
                evictions += 1
    total = hits + misses
    return {
        "capacity": capacity,
        "requests": total,
        "hits": hits,
        "misses": misses,
        "evictions": evictions,
        "hit_rate": round(hits / total, 4) if total else None,
    }
