"""Production-shaped inference serving: bucket ladder + micro-batch engine.

The layer every inference workload calls into (ROADMAP north star:
"serves heavy traffic ... as fast as the hardware allows"):

  * buckets.py — shape-bucket compile cache: any request count pads onto
    a fixed ladder of precompiled batch sizes, so after warmup no shape
    ever recompiles and padded rows stay bit-identical to unpadded ones.
  * engine.py — micro-batching queue: callers submit single boards and
    get futures; a dispatcher coalesces, pads, runs one device dispatch,
    scatters rows back. Bounded queue, per-request timeouts, engine
    metrics (p50/p99, occupancy, bucket histogram, boards/sec).
  * resilience.py / supervisor.py — the failure-as-steady-state layer:
    ``SupervisedEngine`` wraps an engine factory with dispatcher-death
    auto-restart (bounded exponential backoff + full jitter, in-flight
    requests replayed), batch-poison isolation (solo-lane bisection +
    atomic quarantine dump), a closed/open/half-open circuit breaker,
    and deadline-aware admission control (docs/robustness.md).
  * fleet.py — N supervised replicas behind one ``FleetRouter``:
    least-estimated-wait placement, failover with exclusion, background
    replica respawn, rolling in-place weight hot-swap (``reload``), and
    priority tiers (interactive > selfplay > batch) whose overload
    shedding drains the cheap tier first (docs/serving.md).

Factories below wire the engine to the models; ``shared_policy_engine`` /
``shared_value_engine`` memoize per (params, config) so mixed workloads —
selfplay, policy agents, 2-ply value search, arena matches — share one
saturated evaluator instead of each trickling its own device calls.
"""

from __future__ import annotations

from .buckets import (DEFAULT_BUCKETS, BucketLadder,  # noqa: F401
                      bucketed_forward)
from .engine import (BatchDispatchError, EngineBusy,  # noqa: F401
                     EngineClosed, EngineConfig, EngineError,
                     InferenceEngine)
from .resilience import (CircuitBreaker, CircuitOpen,  # noqa: F401
                         EngineOverloaded, PoisonedRequest,
                         RestartsExhausted, full_jitter_delay)
from .cache import (CacheConfig, CacheKeyingError,  # noqa: F401
                    PositionCache)
from .cache import simulate as simulate_cache  # noqa: F401
from .replay import (WorkloadReplayer, build_synthetic_requests,  # noqa: F401
                     load_trace, write_synthetic_capture)
from .supervisor import SupervisedEngine, SupervisorConfig  # noqa: F401
from .fleet import (TIERS, FailoverExhausted, FleetConfig,  # noqa: F401
                    FleetReloadError, FleetRouter, FleetUnavailable,
                    IntegrityViolation)
from .variants import VARIANTS, variant_spec, verify_variant  # noqa: F401


def __getattr__(name: str):
    # lazy: these live in models/quant, whose jax import must not ride
    # along with `import deepgo_tpu.serving` (see variants.py)
    if name in ("ToleranceConfig", "VariantToleranceError"):
        from . import variants

        return getattr(variants, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def ladder_for(n_games: int, buckets=DEFAULT_BUCKETS) -> BucketLadder:
    """The default ladder trimmed to a known fleet size: rungs above the
    smallest one covering ``n_games`` are dead weight (warmup compiles
    nobody dispatches), so a 32-game selfplay run warms (1, 8, 32). A
    fleet larger than the top rung keeps the full ladder — oversize
    batches dispatch as top-rung chunks (BucketLadder.plan)."""
    keep = [b for b in sorted(buckets) if b < n_games]
    ceil = [b for b in sorted(buckets) if b >= n_games]
    return BucketLadder(tuple(keep + ceil[:1]))


def _resolve_variant(params, cfg, variant: str, expand_backend: str,
                     verify: bool, tolerance=None, sample=None):
    """(spec, prepared_params) for one serving variant, gated: a lossy
    variant must pass the tolerance harness against its exact reference
    before any engine is built over it — failure raises the typed
    ``VariantToleranceError`` and the variant never serves
    (docs/serving.md "Serving variants")."""
    from . import variants as variants_mod

    spec = variants_mod.variant_spec(cfg, variant, expand_backend)
    if verify and spec.lossy:
        variants_mod.verify_variant(cfg, params, variant,
                                    tolerance=tolerance,
                                    expand_backend=expand_backend,
                                    sample=sample)
    return spec, spec.prepare(params)


def _stamp_variant(engine, spec):
    """Mark an engine (or supervised engine) with its variant identity:
    ``variant`` surfaces in fleet stats/health, ``prepare_params`` is
    the hook FleetRouter.reload/_respawn use to re-prepare BASE params
    for this replica's program."""
    engine.variant = spec.name
    engine.prepare_params = spec.prepare
    return engine


def policy_engine(params, cfg, config: EngineConfig | None = None,
                  expand_backend: str = "xla", metrics=None,
                  name: str = "policy", variant: str = "f32",
                  verify: bool = True, tolerance=None,
                  sample=None) -> InferenceEngine:
    """Engine over the policy forward: rows are (361,) log-probs.
    ``variant`` selects the serving program (serving/variants.py:
    f32 | int8 | sym | int8+sym); lossy variants are tolerance-gated
    before the engine exists. The f32 path keeps its historical
    contract — a FRESH jitted forward per engine, so the per-engine
    compile counter (zero-recompile tests, xlacheck sentinel) counts
    this engine's shapes alone; variant forwards are process-memoized
    per (cfg, variant) so replicas and A/B arms share warm caches."""
    if variant == "f32":
        from ..models.serving import make_log_prob_fn

        return InferenceEngine(make_log_prob_fn(cfg, expand_backend),
                               params, config=config, name=name,
                               metrics=metrics)
    spec, prepared = _resolve_variant(params, cfg, variant, expand_backend,
                                      verify, tolerance, sample)
    return _stamp_variant(
        InferenceEngine(spec.forward, prepared, config=config, name=name,
                        metrics=metrics), spec)


def value_engine(params, cfg, config: EngineConfig | None = None,
                 metrics=None, name: str = "value") -> InferenceEngine:
    """Engine over the value forward: rows are scalar win-probs."""
    from ..models.serving import make_value_fn

    return InferenceEngine(make_value_fn(cfg), params, config=config,
                           name=name, metrics=metrics)


def supervised_policy_engine(params, cfg,
                             config: EngineConfig | None = None,
                             supervisor: SupervisorConfig | None = None,
                             expand_backend: str = "xla", metrics=None,
                             name: str = "policy", variant: str = "f32",
                             verify: bool = True, tolerance=None,
                             sample=None) -> SupervisedEngine:
    """Resilient engine over the policy forward: an InferenceEngine
    factory under a SupervisedEngine (auto-restart, poison isolation,
    breaker, deadline shedding). The jitted forward is built ONCE and
    closed over, so a restart reuses the warm jit cache — replayed
    requests never recompile. ``variant`` as in ``policy_engine`` (f32
    keeps a per-call forward; variant forwards are process-memoized)."""
    if variant == "f32":
        from ..models.serving import make_log_prob_fn

        forward = make_log_prob_fn(cfg, expand_backend)
        return SupervisedEngine(
            lambda: InferenceEngine(forward, params, config=config,
                                    name=name, metrics=metrics),
            config=supervisor, name=name, metrics=metrics)
    spec, prepared = _resolve_variant(params, cfg, variant, expand_backend,
                                      verify, tolerance, sample)
    return _stamp_variant(SupervisedEngine(
        lambda: InferenceEngine(spec.forward, prepared, config=config,
                                name=name, metrics=metrics),
        config=supervisor, name=name, metrics=metrics), spec)


def supervised_value_engine(params, cfg,
                            config: EngineConfig | None = None,
                            supervisor: SupervisorConfig | None = None,
                            metrics=None,
                            name: str = "value") -> SupervisedEngine:
    """Resilient engine over the value forward (see
    supervised_policy_engine)."""
    from ..models.serving import make_value_fn

    forward = make_value_fn(cfg)
    return SupervisedEngine(
        lambda: InferenceEngine(forward, params, config=config, name=name,
                                metrics=metrics),
        config=supervisor, name=name, metrics=metrics)


def fleet_policy_engine(params, cfg, replicas: int = 2,
                        config: EngineConfig | None = None,
                        fleet: FleetConfig | None = None,
                        supervisor: SupervisorConfig | None = None,
                        expand_backend: str = "xla", metrics=None,
                        name: str = "policy-fleet",
                        variants=None, verify: bool = True,
                        tolerance=None, sample=None,
                        platforms=None, cache=None) -> FleetRouter:
    """A FleetRouter of N supervised policy replicas sharing ONE jitted
    forward per variant — so warmup compiles each ladder rung once for
    the whole fleet, and restarts, respawns, and ``reload`` weight swaps
    all reuse the warm jit cache (zero recompiles, the hot-reload
    contract).

    ``variants`` (a name or a list — serving/variants.py) assigns a
    serving variant to each replica round-robin: ``("f32", "int8")``
    over 4 replicas serves 2 full-precision and 2 quantized replicas
    behind one router, hot-swappable via ``reload`` (each replica's
    ``prepare_params`` hook re-prepares the new BASE checkpoint for its
    own program). Lossy variants are tolerance-gated ONCE here, before
    any replica exists — a failing variant refuses to serve.

    ``platforms`` (a tuple of jax platform names, round-robin like
    variants) builds a HETEROGENEOUS fleet — ``("tpu", "cpu")`` serves
    an accelerator replica and a CPU surge replica behind one router,
    with batch-tier traffic preferring the surge platform
    (``FleetConfig.surge_platforms``) and cross-platform failover for
    free. A requested platform with no live devices falls back to the
    default device (``platform_realized: false`` in health) so chaos
    benches stay honest on single-platform containers. Mutually
    exclusive with lossy ``variants``: each feature owns the replica's
    ``prepare_params`` hook.

    ``cache`` (a CacheConfig or PositionCache) arms the router's
    content-addressed position cache (serving/cache.py)."""
    from . import variants as variants_mod

    if variants is None:
        variants = ("f32",)
    elif isinstance(variants, str):
        variants = (variants,)
    if platforms is not None and set(variants) != {"f32"}:
        raise ValueError(
            "platforms= cannot combine with non-f32 variants: platform "
            "placement and variant preparation both own the replica's "
            "prepare_params hook")
    if platforms is not None:
        return _platform_fleet(params, cfg, replicas, config, fleet,
                               supervisor, expand_backend, metrics, name,
                               tuple(platforms), cache)
    if set(variants) == {"f32"}:
        # the historical pure-f32 fleet: ONE fresh jitted forward per
        # fleet call, shared by its replicas — per-fleet compile
        # counters stay scoped to this fleet's own shapes
        from ..models.serving import make_log_prob_fn

        forward = make_log_prob_fn(cfg, expand_backend)

        def make_f32_replica(i: int) -> SupervisedEngine:
            return SupervisedEngine(
                lambda: InferenceEngine(forward, params, config=config,
                                        name=f"{name}-{i}",
                                        metrics=metrics),
                config=supervisor, name=f"{name}-{i}", metrics=metrics)

        return FleetRouter(make_f32_replica, replicas, config=fleet,
                           name=name, metrics=metrics, params=params,
                           cache=cache)
    specs = {}
    for v in dict.fromkeys(variants):  # verify each distinct variant once
        spec, prepared = _resolve_variant(params, cfg, v, expand_backend,
                                          verify, tolerance, sample)
        specs[v] = (spec, prepared)
    assignment = [variants[i % len(variants)] for i in range(replicas)]
    for v in specs:
        variants_mod._note_serving(v, assignment.count(v))

    def make_replica(i: int) -> SupervisedEngine:
        spec, prepared = specs[assignment[i]]
        return _stamp_variant(SupervisedEngine(
            lambda: InferenceEngine(spec.forward, prepared, config=config,
                                    name=f"{name}-{i}", metrics=metrics),
            config=supervisor, name=f"{name}-{i}", metrics=metrics), spec)

    return FleetRouter(make_replica, replicas, config=fleet, name=name,
                       metrics=metrics, params=params, cache=cache)


def _platform_fleet(params, cfg, replicas, config, fleet, supervisor,
                    expand_backend, metrics, name, platforms,
                    cache) -> FleetRouter:
    """The heterogeneous-platform fleet body: platform assignment is
    round-robin (mirroring variants), each DISTINCT platform gets its
    own fresh jitted forward (per-platform compile counters stay
    scoped), and each replica's params are device_put onto its
    platform's first device — the multi-platform ``jax_platforms``
    pattern. The placement hook doubles as ``prepare_params`` so reloads
    and respawns re-place every new checkpoint on the replica's own
    device."""
    import jax

    from ..models.serving import make_log_prob_fn

    if not platforms:
        raise ValueError("platforms must name at least one jax platform")
    forwards, devices = {}, {}
    for p in dict.fromkeys(platforms):
        forwards[p] = make_log_prob_fn(cfg, expand_backend)
        try:
            devices[p] = jax.devices(p)[0]
        except Exception:  # noqa: BLE001 — platform absent on this host
            # fall back to the default device so a ("tpu", "cpu") config
            # stays runnable on a CPU-only container; health reports
            # platform_realized: false for the unrealized replicas
            devices[p] = None

    def place(p, tree):
        dev = devices[p]
        return tree if dev is None else jax.device_put(tree, dev)

    assignment = [platforms[i % len(platforms)] for i in range(replicas)]

    def make_replica(i: int) -> SupervisedEngine:
        p = assignment[i]
        forward = forwards[p]
        placed = place(p, params)
        eng = SupervisedEngine(
            lambda: InferenceEngine(forward, placed, config=config,
                                    name=f"{name}-{i}", metrics=metrics),
            config=supervisor, name=f"{name}-{i}", metrics=metrics)
        eng.platform = p
        eng.platform_realized = devices[p] is not None
        eng.prepare_params = lambda base, p=p: place(p, base)
        return eng

    return FleetRouter(make_replica, replicas, config=fleet, name=name,
                       metrics=metrics, params=params, cache=cache)


def fleet_value_engine(params, cfg, replicas: int = 2,
                       config: EngineConfig | None = None,
                       fleet: FleetConfig | None = None,
                       supervisor: SupervisorConfig | None = None,
                       metrics=None,
                       name: str = "value-fleet") -> FleetRouter:
    """FleetRouter over the value forward (see fleet_policy_engine)."""
    from ..models.serving import make_value_fn

    forward = make_value_fn(cfg)

    def make_replica(i: int) -> SupervisedEngine:
        return SupervisedEngine(
            lambda: InferenceEngine(forward, params, config=config,
                                    name=f"{name}-{i}", metrics=metrics),
            config=supervisor, name=f"{name}-{i}", metrics=metrics)

    return FleetRouter(make_replica, replicas, config=fleet, name=name,
                       metrics=metrics)


# One engine per live (params, model config, engine config): agents built
# from the same checkpoint — a policy player and the value searcher's
# prior, both sides of a self-match — coalesce into the same dispatches.
_SHARED: dict[tuple, InferenceEngine] = {}


def _shared(kind: str, factory, params, cfg, config: EngineConfig | None,
            supervised: bool, fleet: int = 1, variant: str = "f32"):
    key = (kind, supervised, fleet, id(params), cfg, config, variant)
    engine = _SHARED.get(key)
    if (engine is None or engine._closing.is_set()
            or getattr(engine, "_failed", None) is not None):
        kw = {} if kind == "value" else {"variant": variant}
        # variant engines get distinct names so their metrics series
        # (and the roofline's per-engine join) never merge with f32's
        suffix = "" if variant == "f32" else f"-{variant}"
        if fleet > 1:
            fleet_factory = (fleet_policy_engine if kind == "policy"
                             else fleet_value_engine)
            if kind == "policy":
                kw = {"variants": variant}
            engine = _SHARED[key] = fleet_factory(
                params, cfg, replicas=fleet, config=config,
                name=f"shared-{kind}-fleet{suffix}", **kw)
        else:
            engine = _SHARED[key] = factory(params, cfg, config=config,
                                            name=f"shared-{kind}{suffix}",
                                            **kw)
    return engine


def shared_policy_engine(params, cfg, config: EngineConfig | None = None,
                         supervised: bool = False, fleet: int = 1,
                         variant: str = "f32"):
    """``fleet > 1`` returns a FleetRouter of that many supervised
    replicas (replica supervision is implied — every replica is a
    SupervisedEngine); otherwise the single shared engine as before.
    ``variant`` selects the serving program (serving/variants.py) —
    memoized per (checkpoint, variant), so an int8 champion and the f32
    one coexist as distinct shared engines for a live A/B."""
    return _shared("policy",
                   supervised_policy_engine if supervised else policy_engine,
                   params, cfg, config, supervised, fleet, variant)


def shared_value_engine(params, cfg, config: EngineConfig | None = None,
                        supervised: bool = False, fleet: int = 1):
    return _shared("value",
                   supervised_value_engine if supervised else value_engine,
                   params, cfg, config, supervised, fleet)


def close_shared_engines() -> None:
    """Drain and drop every registry engine (match CLI teardown)."""
    while _SHARED:
        _, engine = _SHARED.popitem()
        engine.close()
