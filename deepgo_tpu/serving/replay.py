"""High-fidelity workload replay: a captured trace, re-served live.

The workload observatory (obs/workload.py) records what the fleet was
asked; this module plays it back — the other half of "measure before
you optimize": a cache or surge-tier PR proves its ">2x under a
realistic opening-heavy trace" claim by replaying the SAME trace
against both arms, and a chaos bench stresses the fleet with the shape
of real traffic instead of uniform-random boards.

  * ``load_trace`` — a capture directory back as submittable items:
    each ``workload_request`` joined with its packed payload from the
    content-addressed position store (missing payloads are a typed
    error — a digest-only capture characterizes but cannot replay).
  * ``WorkloadReplayer`` — OPEN-LOOP arrival fidelity: requests are
    submitted at the recorded inter-arrival offsets (scaled by
    ``speed``), never gated on earlier responses — a slow fleet makes
    queues grow, exactly like production, instead of silently slowing
    the generator. The report quantifies fidelity (span error, mean/p99
    scheduling lag vs the recorded timeline) next to the served
    outcomes; the acceptance bar is the replayed timeline within 10%.
  * ``build_synthetic_requests`` / ``write_synthetic_capture`` — the
    opening-heavy generator for when no capture exists: real game
    openings replayed through the ``go/`` rules engine into packed
    positions, sampled with a Zipf-style popularity skew (early moves
    dominate — every game starts from the same opening tree) over
    Poisson arrivals, all derived from one seed, so two runs of the
    same spec replay the identical trace.

``cli workload record|analyze|replay`` and ``bench.py --mode serving
--trace DIR`` are the operator surfaces (docs/serving.md,
docs/observability.md "Workload observatory").
"""

from __future__ import annotations

import inspect
import os
import time

import numpy as np

from ..obs import workload as workload_mod
from ..obs.workload import WorkloadCaptureError
from .engine import EngineBusy
from .fleet import FleetUnavailable
from .resilience import CircuitOpen, EngineOverloaded, PoisonedRequest

DEFAULT_TIERS = ("interactive", "selfplay", "batch")


def load_trace(path: str, strict: bool = True) -> list[dict]:
    """A capture directory as replayable items, oldest first: ``{t,
    packed, player, rank, tier, session}`` per recorded request. ``strict``
    raises when any request's payload is missing from the position
    store; otherwise those requests are dropped (reported by len)."""
    cap = workload_mod.load_capture(path)
    items: list[dict] = []
    missing = 0
    for r in cap["requests"]:
        pos = cap["positions"].get(r.get("digest"))
        if pos is None or not pos.get("packed"):
            missing += 1
            continue
        items.append({
            "t": float(r.get("t", 0.0)),
            "packed": workload_mod.decode_packed(pos["packed"]),
            "player": int(r.get("player", pos.get("player", 1))),
            "rank": int(r.get("rank", pos.get("rank", 1))),
            "tier": r.get("tier"),
            "session": r.get("session"),
        })
    if missing and strict:
        raise WorkloadCaptureError(
            f"{missing}/{len(cap['requests'])} recorded request(s) have "
            f"no stored payload in {path!r} — capture is not replayable "
            "(recorded with store_positions=False?)")
    return items


class WorkloadReplayer:
    """Replay one trace against a live engine/fleet at ``speed``x.

    ``engine`` is anything with the serving ``submit`` surface — a bare
    ``InferenceEngine``, a ``SupervisedEngine``, or a ``FleetRouter``
    (tier-aware submit detected by signature, so recorded tiers travel
    when the target understands them). The scheduler is one thread (the
    caller's): it sleeps to each request's target offset, submits, and
    moves on — responses resolve concurrently on the serving side and
    are collected after the send loop (open loop). Clock and sleep are
    injectable; the fidelity tests drive a fake clock."""

    def __init__(self, engine, trace: list[dict], speed: float = 1.0,
                 timeout_s: float | None = None,
                 collect_timeout_s: float = 60.0, on_result=None,
                 clock=time.monotonic, sleep=time.sleep):
        if speed <= 0:
            raise ValueError(f"speed must be > 0, got {speed}")
        if not trace:
            raise ValueError("empty trace: nothing to replay")
        self.engine = engine
        self.trace = sorted(trace, key=lambda r: float(r.get("t", 0.0)))
        self.speed = float(speed)
        self.timeout_s = timeout_s
        self.collect_timeout_s = float(collect_timeout_s)
        # per-request observer: on_result(item, outcome, value, exc) —
        # the chaos campaign's wrong-answer and lost-future accounting
        # rides here instead of a second pass over private state
        self.on_result = on_result
        self._clock = clock
        self._sleep = sleep
        params = inspect.signature(engine.submit).parameters
        self._accepts_tier = "tier" in params
        self._accepts_session = "session" in params

    def run(self) -> dict:
        t_base = float(self.trace[0].get("t", 0.0))
        targets = [(float(r.get("t", 0.0)) - t_base) / self.speed
                   for r in self.trace]
        actuals: list[float] = []
        futures: list = []
        # "lost" extends the capture-outcome vocabulary for replay only:
        # a future nobody resolved within collect_timeout_s — the
        # integrity invariant chaos campaigns exist to check
        outcomes = {o: 0 for o in (*workload_mod.OUTCOMES, "lost")}
        tiers: dict[str, int] = {}
        t0 = self._clock()
        for item, target in zip(self.trace, targets):
            now = self._clock() - t0
            if now < target:
                self._sleep(target - now)
            kw = {}
            if self._accepts_tier and item.get("tier") is not None:
                kw["tier"] = item["tier"]
            if self._accepts_session and item.get("session") is not None:
                kw["session"] = item["session"]
            tier = str(item.get("tier") or "untiered")
            tiers[tier] = tiers.get(tier, 0) + 1
            try:
                futures.append(self.engine.submit(
                    item["packed"], item["player"], item["rank"],
                    timeout_s=self.timeout_s, **kw))
            except (EngineOverloaded, CircuitOpen, EngineBusy,
                    FleetUnavailable):
                futures.append(None)  # counted as shed at collection
            actuals.append(self._clock() - t0)
        for item, f in zip(self.trace, futures):
            value = exc = None
            if f is None:
                outcome = "shed"
            else:
                try:
                    value = f.result(timeout=self.collect_timeout_s)
                    outcome = "ok"
                except TimeoutError as e:
                    # a future STILL unresolved after the collection
                    # grace is lost — dropped by a failover hole, not
                    # merely late; a resolved TimeoutError is a
                    # deadline verdict the serving side delivered
                    outcome = "lost" if not f.done() else "timeout"
                    exc = e
                except (EngineOverloaded, CircuitOpen, EngineBusy,
                        FleetUnavailable) as e:
                    outcome, exc = "shed", e
                except PoisonedRequest as e:
                    outcome, exc = "poisoned", e
                except BaseException as e:  # noqa: BLE001 — an outcome
                    outcome, exc = "failed", e
            outcomes[outcome] += 1
            if self.on_result is not None:
                self.on_result(item, outcome, value, exc)
        wall = self._clock() - t0
        target_span = targets[-1]
        actual_span = actuals[-1] - actuals[0] if len(actuals) > 1 else 0.0
        lags = np.abs(np.array(actuals) - np.array(targets))
        report = {
            "requests": len(self.trace),
            "speed": self.speed,
            "target_span_s": round(target_span, 6),
            "actual_span_s": round(actual_span, 6),
            "span_error_frac": round(
                abs(actual_span - target_span) / target_span, 6)
            if target_span > 0 else 0.0,
            "mean_lag_ms": round(float(lags.mean()) * 1000, 3),
            "p99_lag_ms": round(float(np.percentile(lags, 99)) * 1000, 3),
            "lag_frac": round(float(lags.mean()) / target_span, 6)
            if target_span > 0 else 0.0,
            "wall_s": round(wall, 4),
            "boards_per_sec": round(len(self.trace) / wall, 1)
            if wall > 0 else None,
            "tiers": {t: tiers[t] for t in sorted(tiers)},
            "outcomes": {o: n for o, n in outcomes.items() if n},
        }
        # the acceptance bar: the replayed timeline within 10% of the
        # recorded one, both in total span and in mean per-request lag
        report["fidelity_ok"] = (report["span_error_frac"] <= 0.10
                                 and report["lag_frac"] <= 0.10)
        return report


# ---------------------------------------------------------------------------
# the synthetic opening-heavy generator

def _opening_pool(sgf_dir: str, games: int, opening_moves: int
                  ) -> list[dict]:
    """Packed positions from the first ``opening_moves`` plies of up to
    ``games`` real games: the shared-opening-tree duplication is REAL —
    every game's move-0 position is the same empty board, and early
    joseki repeat across games."""
    from ..go.replay import replay_positions
    from ..sgf import parse_file

    paths: list[str] = []
    for dirpath, dirnames, filenames in os.walk(sgf_dir):
        dirnames.sort()
        paths.extend(os.path.join(dirpath, n) for n in sorted(filenames)
                     if n.endswith(".sgf"))
    pool: list[dict] = []
    used = 0
    for path in paths:
        if used >= games:
            break
        try:
            game = parse_file(path)
        except (OSError, ValueError):
            continue
        if not game.moves:
            continue
        used += 1
        ranks = game.ranks or (5, 5)
        for i, (packed, move) in enumerate(replay_positions(game)):
            if i >= opening_moves:
                break
            pool.append({
                "packed": packed,
                "player": int(move.player),
                "rank": int(ranks[move.player - 1]),
                "move": i,
            })
    if not pool:
        raise WorkloadCaptureError(
            f"no usable SGF games under {sgf_dir!r} — cannot build a "
            "synthetic opening pool")
    return pool


def build_synthetic_requests(sgf_dir: str, requests: int = 512,
                             games: int = 32, opening_moves: int = 12,
                             rate_per_s: float = 200.0,
                             zipf_s: float = 1.1, seed: int = 0,
                             tiers: tuple = DEFAULT_TIERS,
                             tier_weights: tuple = (0.6, 0.3, 0.1)
                             ) -> list[dict]:
    """A deterministic (seed-derived) opening-heavy trace, in memory.

    Popularity is doubly skewed: the pool already duplicates early
    positions across games (the real opening tree), and sampling
    weights decay with move number as ``1/(1+move)^zipf_s`` — so
    move-0/1 positions dominate the way a production opening-explorer
    workload does. Arrivals are Poisson at ``rate_per_s`` (burstiness
    ~0 by construction; the analyzer measures, not assumes)."""
    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    pool = _opening_pool(sgf_dir, games, opening_moves)
    rng = np.random.default_rng(seed)
    weights = np.array([1.0 / (1.0 + p["move"]) ** zipf_s for p in pool])
    weights /= weights.sum()
    picks = rng.choice(len(pool), size=requests, p=weights)
    tw = np.array(tier_weights, dtype=np.float64)
    tw /= tw.sum()
    tier_picks = rng.choice(len(tiers), size=requests, p=tw)
    offsets = np.cumsum(rng.exponential(1.0 / rate_per_s, size=requests))
    items = []
    for i in range(requests):
        p = pool[int(picks[i])]
        items.append({
            "t": float(offsets[i]),
            "packed": p["packed"],
            "player": p["player"],
            "rank": p["rank"],
            "tier": str(tiers[int(tier_picks[i])]),
        })
    return items


def write_synthetic_capture(out_dir: str, items: list[dict]) -> dict:
    """Persist an in-memory synthetic trace in the standard capture
    layout (workload.jsonl + positions.jsonl), digests included, so
    ``cli workload analyze|replay`` and ``bench --trace`` consume
    synthetic and recorded captures identically."""
    from ..obs.exporter import JsonlSink

    os.makedirs(out_dir, exist_ok=True)
    seen: set[str] = set()
    canon: set[str] = set()
    with JsonlSink(os.path.join(out_dir, "workload.jsonl")) as sink, \
            JsonlSink(os.path.join(out_dir, "positions.jsonl")) as pos_sink:
        for item in items:
            digest = workload_mod.exact_digest(
                item["packed"], item["player"], item["rank"])
            canonical = workload_mod.canonical_digest(
                item["packed"], item["player"], item["rank"])
            canon.add(canonical)
            if digest not in seen:
                seen.add(digest)
                pos_sink.write(
                    "workload_position", digest=digest,
                    canonical=canonical, player=item["player"],
                    rank=item["rank"],
                    packed=workload_mod.encode_packed(item["packed"]))
            sink.write("workload_request", t=item["t"], digest=digest,
                       canonical=canonical, player=item["player"],
                       rank=item["rank"], tier=item.get("tier"),
                       outcome="synthetic", synthetic=True)
        sink.write("workload_capture", started=len(items),
                   finished=len(items), dropped=0, unique=len(seen),
                   canonical_unique=len(canon), synthetic=True)
    return {"requests": len(items), "unique": len(seen),
            "canonical_unique": len(canon), "dir": out_dir}
