"""Batched self-play: the policy network playing itself.

BASELINE.md config 5 ("batched self-play policy inference") realized as an
actual driver, not just a forward-pass benchmark: N games advance in
lockstep, the host summarizes each live board into a packed record (native
C++ engine when available), one batched TPU forward scores all of them, and
each game plays its best *legal* move (legality = empty and not suicide,
straight from the packed liberties-after channel — no second rules query).

Games end on double pass — a player passes when no legal move is left or
when its best move's probability falls below ``pass_threshold`` — or at
``max_moves``. Finished games can be exported as SGF, which feeds back into
this framework's own transcription pipeline (full circle).

Usage:
  python -m deepgo_tpu.selfplay --games 32 [--checkpoint runs/<id>/checkpoint.npz]
      [--max-moves 200] [--sgf-out selfplay_games/] [--temperature 0.5]
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp

from . import BOARD_SIZE
from .features import P_LIB_AFTER, P_STONES
from .go import native, new_board, play, summarize
from .models import policy_cnn
from .models.serving import make_policy_fn
from .sgf import Move, coord_to_sgf


class GameState:
    def __init__(self):
        self.stones, self.age = new_board()
        self.player = 1
        self.moves: list[Move] = []
        self.passes = 0
        self.done = False


def _summarize(state: GameState) -> np.ndarray:
    if native.available():
        return native.summarize_native(state.stones, state.age)
    return summarize(state.stones, state.age)


def self_play(params, cfg: policy_cnn.ModelConfig, n_games: int = 32,
              max_moves: int = 361, temperature: float = 0.0,
              pass_threshold: float = 1e-4, rank: int = 9, seed: int = 0):
    """Play n_games to completion; returns (games, stats)."""
    predict = make_policy_fn(cfg, top_k=1)
    rng = np.random.default_rng(seed)
    games = [GameState() for _ in range(n_games)]
    positions = 0
    t0 = time.time()

    while True:
        active = [g for g in games if not g.done]
        if not active:
            break
        packed = np.stack([_summarize(g) for g in active])
        players = np.array([g.player for g in active], dtype=np.int32)
        ranks = np.full(len(active), rank, dtype=np.int32)
        logp = np.asarray(
            predict(params, jnp.asarray(packed), jnp.asarray(players),
                    jnp.asarray(ranks))["log_probs"]
        )
        positions += len(active)

        # legality: empty and not suicide (liberties-after > 0)
        empty = packed[:, P_STONES].reshape(len(active), -1) == 0
        lib_after = np.stack(
            [packed[i, P_LIB_AFTER + g.player - 1].reshape(-1)
             for i, g in enumerate(active)]
        )
        legal = empty & (lib_after > 0)
        logp = np.where(legal, logp, -np.inf)

        for i, g in enumerate(active):
            row = logp[i]
            if temperature > 0:
                z = row / temperature
                z -= z.max() if np.isfinite(z.max()) else 0
                p = np.exp(z)
                total = p.sum()
                move_idx = int(rng.choice(361, p=p / total)) if total > 0 else -1
            else:
                move_idx = int(row.argmax()) if np.isfinite(row.max()) else -1
            best_prob = float(np.exp(row[move_idx])) if move_idx >= 0 else 0.0

            if move_idx < 0 or best_prob < pass_threshold:
                g.passes += 1  # pass (not recorded on the board, like the reference)
                if g.passes >= 2:
                    g.done = True
            else:
                g.passes = 0
                x, y = divmod(move_idx, BOARD_SIZE)
                play(g.stones, g.age, x, y, g.player)
                g.moves.append(Move(g.player, x, y))
                if len(g.moves) >= max_moves:
                    g.done = True
            g.player = 3 - g.player

    dt = time.time() - t0
    stats = {
        "games": n_games,
        "positions": positions,
        "seconds": dt,
        "positions_per_sec": positions / dt,
        "mean_moves": float(np.mean([len(g.moves) for g in games])),
    }
    return games, stats


def to_sgf(game: GameState, black_rank: int = 9, white_rank: int = 9) -> str:
    lines = ["(;GM[1]", "FF[4]", "CA[UTF-8]", "SZ[19]",
             f"BR[{black_rank}d]", f"WR[{white_rank}d]"]
    for m in game.moves:
        tag = "B" if m.player == 1 else "W"
        lines.append(f";{tag}[{coord_to_sgf(m.x, m.y)}]")
    return "\r\n".join(lines) + ")\r\n"


def main(argv=None) -> None:
    import os

    ap = argparse.ArgumentParser()
    ap.add_argument("--games", type=int, default=32)
    ap.add_argument("--checkpoint", help="policy checkpoint (default: random init)")
    ap.add_argument("--model", default="small", choices=list(policy_cnn.CONFIGS))
    ap.add_argument("--max-moves", type=int, default=361)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--sgf-out", help="directory to write finished games")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    if args.checkpoint:
        from .models.serving import load_policy

        _, params, cfg = load_policy(args.checkpoint)
    else:
        cfg = policy_cnn.CONFIGS[args.model]
        params = policy_cnn.init(jax.random.key(args.seed), cfg)

    games, stats = self_play(params, cfg, n_games=args.games,
                             max_moves=args.max_moves,
                             temperature=args.temperature, seed=args.seed)
    print({k: round(v, 2) if isinstance(v, float) else v
           for k, v in stats.items()})

    if args.sgf_out:
        os.makedirs(args.sgf_out, exist_ok=True)
        for i, g in enumerate(games):
            with open(os.path.join(args.sgf_out, f"game_{i:04d}.sgf"), "w") as f:
                f.write(to_sgf(g))
        print(f"wrote {len(games)} SGFs to {args.sgf_out}")


if __name__ == "__main__":
    main()
