"""Batched self-play: the policy network playing itself.

BASELINE.md config 5 ("batched self-play policy inference") realized as an
actual driver, not just a forward-pass benchmark: the host summarizes each
live board into a packed record (native C++ engine when available), every
game submits its board to the micro-batching inference engine
(deepgo_tpu.serving) as an independent request, the dispatcher coalesces
the fleet into one padded TPU forward per ply, and each game plays its
best *legal* move (legality = empty and not suicide, straight from the
packed liberties-after channel — no second rules query). Because batches
pad onto the engine's precompiled bucket ladder, games finishing at mixed
lengths never trigger a recompile or distort the dispatch shape.

Games end on double pass — a player passes when no legal move is left or
when its best move's probability falls below ``pass_threshold`` — or at
``max_moves``. Finished games can be exported as SGF, which feeds back into
this framework's own transcription pipeline (full circle).

Usage:
  python -m deepgo_tpu.selfplay --games 32 [--checkpoint runs/<id>/checkpoint.npz]
      [--max-moves 200] [--sgf-out selfplay_games/] [--temperature 0.5]
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp

from . import BOARD_SIZE
from .features import P_LIB_AFTER, P_STONES
from .go import (group_and_liberties, native, neighbors, new_board, play,
                 summarize)
from .models import policy_cnn
from .serving import (BucketLadder, EngineConfig, bucketed_forward,
                      ladder_for, policy_engine)
from .sgf import Move, coord_to_sgf


class GameState:
    def __init__(self):
        self.stones, self.age = new_board()
        self.player = 1
        self.moves: list[Move] = []
        self.passes = 0
        self.done = False
        self.ko_point: tuple[int, int] | None = None


def apply_move(g: GameState, x: int, y: int) -> None:
    """Play the side-to-move's stone in game ``g`` with simple-ko tracking.

    The rules engine deliberately has no ko (it replays *recorded* games,
    board.py:15-18), but generated games need it: without a ko ban two
    deterministic agents recapture the same stone forever. After a play that
    captures exactly one stone and leaves the new stone as a lone chain with
    exactly one liberty, that captured point is banned for the opponent's
    immediate reply (simple ko; superko is not needed for policy-net play).
    """
    would_die: set[tuple[int, int]] = set()
    for n in neighbors(x, y):
        if g.stones[n] == 3 - g.player and n not in would_die:
            grp, libs = group_and_liberties(g.stones, *n)
            if libs == {(x, y)}:
                would_die |= grp
    play(g.stones, g.age, x, y, g.player)
    g.ko_point = None
    if len(would_die) == 1:
        grp, libs = group_and_liberties(g.stones, x, y)
        if len(grp) == 1 and len(libs) == 1:
            g.ko_point = next(iter(would_die))
    g.moves.append(Move(g.player, x, y))


def step_game(g: GameState, move_idx: int, max_moves: int) -> None:
    """Advance one ply: play ``move_idx`` or record a pass (-1); end the
    game on double pass or the move cap; flip the side to move."""
    if move_idx < 0:
        g.passes += 1
        g.ko_point = None  # a pass lifts the ko ban for the next player
        if g.passes >= 2:
            g.done = True
    else:
        g.passes = 0
        x, y = divmod(move_idx, BOARD_SIZE)
        apply_move(g, x, y)
        if len(g.moves) >= max_moves:
            g.done = True
    g.player = 3 - g.player


def step_games(games: list[GameState], moves, max_moves: int) -> None:
    """Advance every game one ply: game i plays ``moves[i]`` (-1 = pass).

    The move application — capture resolution, aging, simple-ko detection —
    runs as ONE threaded native call over all boards
    (native.play_batch_native) instead of a Python flood-fill per game,
    which profiling showed was >80% of the arena/self-play host time.
    Python keeps only the bookkeeping (move lists, pass/done flags, side to
    move). Falls back to per-game step_game without the native library.
    """
    played = [i for i, m in enumerate(moves) if m >= 0 and not games[i].done]
    if not native.batch_available() or not played:
        for i, m in enumerate(moves):
            if not games[i].done:  # same done-game skip as the native path
                step_game(games[i], int(m), max_moves)
        return
    stones = np.stack([games[i].stones for i in played])
    age = np.stack([games[i].age for i in played])
    mv = np.array([int(moves[i]) for i in played], dtype=np.int32)
    pl = np.array([games[i].player for i in played], dtype=np.int32)
    ko = native.play_batch_native(stones, age, mv, pl)
    for j, i in enumerate(played):
        g = games[i]
        g.stones[:] = stones[j]
        g.age[:] = age[j]
        g.ko_point = None if ko[j] < 0 else divmod(int(ko[j]), BOARD_SIZE)
        x, y = divmod(int(mv[j]), BOARD_SIZE)
        g.moves.append(Move(g.player, x, y))
        g.passes = 0
        if len(g.moves) >= max_moves:
            g.done = True
        g.player = 3 - g.player
    for i, m in enumerate(moves):
        if m < 0 and not games[i].done:
            step_game(games[i], int(m), max_moves)


def summarize_state(state: GameState) -> np.ndarray:
    if native.available():
        return native.summarize_native(state.stones, state.age)
    return summarize(state.stones, state.age)


def summarize_states(states: list[GameState]) -> np.ndarray:
    """Packed records for a fleet of live games, (N, 9, 19, 19) uint8.

    One native call (threaded in C++) summarizes every board — the per-ply
    host cost of self-play/arena drops from N FFI crossings plus a Python
    loop to a single crossing. Falls back to the per-board path without the
    native library (or with a stale .so lacking the batch symbol)."""
    if native.batch_available():
        stones = np.stack([g.stones for g in states])
        age = np.stack([g.age for g in states])
        return native.summarize_batch_native(stones, age)
    return np.stack([summarize_state(g) for g in states])


def legal_mask(packed: np.ndarray, players: np.ndarray,
               games: list[GameState] | None = None) -> np.ndarray:
    """(N, 361) bool: empty, not suicide, and not a banned ko recapture.

    Emptiness and suicide come from the packed planes alone; the ko ban
    comes from each game's ``ko_point`` when ``games`` is given.
    """
    n = packed.shape[0]
    empty = packed[:, P_STONES].reshape(n, -1) == 0
    lib_after = packed[np.arange(n), P_LIB_AFTER + players - 1].reshape(n, -1)
    legal = empty & (lib_after > 0)
    if games is not None:
        for i, g in enumerate(games):
            if g.ko_point is not None:
                legal[i, g.ko_point[0] * BOARD_SIZE + g.ko_point[1]] = False
    return legal


def batched_log_probs(predict, params, packed: np.ndarray,
                      players: np.ndarray, ranks: np.ndarray,
                      ladder: BucketLadder | None = None) -> np.ndarray:
    """Policy log-probs with the batch padded onto the serving bucket
    ladder (deepgo_tpu.serving.buckets).

    Game batches shrink irregularly as games finish; the ladder keeps the
    set of shapes ``jit`` ever sees to a handful of precompiled rungs
    instead of recompiling per batch size, and the padded rows are
    bit-identical to an unpadded forward (row-independent model). This is
    the direct, threadless path for a single lockstep caller; concurrent
    submitters should share an ``serving.InferenceEngine`` instead.
    """
    return bucketed_forward(
        lambda pk, pl, rk: predict(params, jnp.asarray(pk), jnp.asarray(pl),
                                   jnp.asarray(rk))["log_probs"],
        packed, players, ranks, ladder or ladder_for(len(packed)))


def select_from_log_probs(row: np.ndarray, temperature: float,
                          pass_threshold: float,
                          rng: np.random.Generator) -> int:
    """Pick a move from one masked (-inf = illegal) log-prob row.

    Returns a flat move index, or -1 to pass (no legal move, or the chosen
    move's probability falls below ``pass_threshold``).
    """
    if not np.isfinite(row.max()):
        return -1
    if temperature > 0:
        z = (row - row.max()) / temperature
        p = np.exp(z)
        move = int(rng.choice(361, p=p / p.sum()))
    else:
        move = int(row.argmax())
    if float(np.exp(row[move])) < pass_threshold:
        return -1
    return move


def self_play(params, cfg: policy_cnn.ModelConfig, n_games: int = 32,
              max_moves: int = 361, temperature: float = 0.0,
              pass_threshold: float = 1e-4, rank: int = 9, seed: int = 0,
              engine=None, max_wait_ms: float = 2.0,
              supervised: bool = False, fleet: int = 0,
              move_selector=None):
    """Play n_games to completion; returns (games, stats).

    Inference rides the micro-batching engine (deepgo_tpu.serving): each
    live game submits its own board and gets a future, instead of the
    fleet advancing as one lockstep batch. The dispatcher coalesces the
    submissions, pads to a precompiled bucket, and answers them in one
    device dispatch — so as games finish at mixed lengths the shrinking
    fleet never shows the compiler a new shape, and other workloads
    sharing the engine (arena agents, an eval frontend) ride the same
    saturated dispatches. Pass ``engine`` to share one; by default the
    run builds a private engine over a ladder trimmed to ``n_games``,
    warms every rung, and closes it on exit. ``supervised=True`` puts the
    private engine under the resilience supervisor (auto-restart, poison
    isolation, breaker, deadline shedding — docs/robustness.md): games
    then ride through dispatcher deaths untouched, with bit-identical
    results (the forward is pure, replay is idempotent).
    ``fleet >= 2`` spreads the games over that many supervised replicas
    behind a FleetRouter (serving/fleet.py) — requests ride the
    ``selfplay`` priority tier, so an overloaded shared fleet sheds them
    before interactive traffic. ``stats["engine"]`` carries the engine's
    occupancy/latency/bucket counters (plus the supervisor's
    restart/shed/poison counters when supervised, or the fleet's
    failover/respawn/shed counters with ``fleet``).

    ``move_selector`` replaces the per-row policy sampling entirely —
    AlphaZero-style search-selfplay
    (deepgo_tpu.search.make_move_selector): called as
    ``move_selector(games, packed, players, legal, rng)`` and returning
    one move index per active game (-1 = pass). The selector owns its
    own inference traffic (the search's wave-batched leaf futures), so
    the per-game policy submission loop is skipped.
    """
    own_engine = engine is None
    if own_engine:
        ecfg = EngineConfig(buckets=ladder_for(n_games).buckets,
                            max_wait_ms=max_wait_ms)
        if fleet and fleet >= 2:
            from .serving import FleetConfig, fleet_policy_engine

            engine = fleet_policy_engine(
                params, cfg, replicas=fleet, config=ecfg,
                fleet=FleetConfig(default_tier="selfplay"))
        elif supervised:
            from .serving import supervised_policy_engine

            engine = supervised_policy_engine(params, cfg, config=ecfg)
        else:
            engine = policy_engine(params, cfg, config=ecfg)
        engine.warmup()
    rng = np.random.default_rng(seed)
    games = [GameState() for _ in range(n_games)]
    positions = 0
    from .obs import get_registry

    reg = get_registry()
    obs_positions = reg.counter(
        "deepgo_selfplay_positions_total", "selfplay positions evaluated")
    obs_rate = reg.gauge(
        "deepgo_selfplay_positions_per_sec",
        "positions/sec of the most recent selfplay run")
    obs_games = reg.gauge(
        "deepgo_selfplay_active_games", "live games in the current fleet")
    t0 = time.time()

    try:
        while True:
            active = [g for g in games if not g.done]
            if not active:
                break
            packed = summarize_states(active)
            players = np.array([g.player for g in active], dtype=np.int32)
            legal = legal_mask(packed, players, active)
            positions += len(active)
            obs_positions.inc(len(active))
            obs_games.set(len(active))

            if move_selector is not None:
                # search-selfplay: the selector runs its own tree search
                # per game (its leaf futures are the inference traffic)
                moves = [int(m) for m in
                         move_selector(active, packed, players, legal, rng)]
            else:
                # every game is an independent submitter: futures out,
                # one coalesced dispatch behind them
                futures = [engine.submit(packed[i], int(players[i]), rank)
                           for i in range(len(active))]
                logp = np.stack([f.result() for f in futures])
                logp = np.where(legal, logp, -np.inf)
                moves = [select_from_log_probs(logp[i], temperature,
                                               pass_threshold, rng)
                         for i in range(len(active))]

            step_games(active, moves, max_moves)

        dt = time.time() - t0
        obs_rate.set(positions / dt)
        obs_games.set(0)
        stats = {
            "games": n_games,
            "positions": positions,
            "seconds": dt,
            "positions_per_sec": positions / dt,
            "mean_moves": float(np.mean([len(g.moves) for g in games])),
            "engine": engine.stats(),
        }
        return games, stats
    finally:
        if own_engine:
            engine.close()


def to_sgf(game: GameState, black_rank: int = 9, white_rank: int = 9,
           result: str | None = None, komi: float | None = None) -> str:
    lines = ["(;GM[1]", "FF[4]", "CA[UTF-8]", "SZ[19]",
             f"BR[{black_rank}d]", f"WR[{white_rank}d]"]
    if komi is not None:
        lines.append(f"KM[{komi:g}]")
    if result is not None:
        lines.append(f"RE[{result}]")
    for m in game.moves:
        tag = "B" if m.player == 1 else "W"
        lines.append(f";{tag}[{coord_to_sgf(m.x, m.y)}]")
    return "\r\n".join(lines) + ")\r\n"


def main(argv=None) -> None:
    import os

    from .utils.atomicio import atomic_write

    ap = argparse.ArgumentParser()
    ap.add_argument("--games", type=int, default=32)
    ap.add_argument("--checkpoint", help="policy checkpoint (default: random init)")
    ap.add_argument("--model", default="small", choices=list(policy_cnn.CONFIGS))
    ap.add_argument("--max-moves", type=int, default=361)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--sgf-out", help="directory to write finished games")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-wait-ms", type=float, default=2.0,
                    help="engine coalescing window: how long the "
                         "dispatcher waits for more submitters before "
                         "padding and dispatching (docs/serving.md)")
    ap.add_argument("--supervised", action="store_true",
                    help="run the engine under the resilience supervisor: "
                         "dispatcher-death auto-restart with request "
                         "replay, batch-poison isolation, circuit "
                         "breaker, deadline-aware shedding "
                         "(docs/robustness.md)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="spread inference over N supervised engine "
                         "replicas behind the failover router "
                         "(serving/fleet.py): least-wait placement, "
                         "replica respawn, selfplay-tier QoS "
                         "(docs/serving.md)")
    args = ap.parse_args(argv)

    from .utils import honor_platform_env

    honor_platform_env()

    if args.checkpoint:
        from .models.serving import load_policy

        _, params, cfg = load_policy(args.checkpoint)
    else:
        cfg = policy_cnn.CONFIGS[args.model]
        params = policy_cnn.init(jax.random.key(args.seed), cfg)

    games, stats = self_play(params, cfg, n_games=args.games,
                             max_moves=args.max_moves,
                             temperature=args.temperature, seed=args.seed,
                             max_wait_ms=args.max_wait_ms,
                             supervised=args.supervised, fleet=args.fleet)
    print({k: round(v, 2) if isinstance(v, float) else v
           for k, v in stats.items()})

    if args.sgf_out:
        from .go.scoring import area_score

        os.makedirs(args.sgf_out, exist_ok=True)
        scored = 0
        for i, g in enumerate(games):
            # only finished games (double pass) get a result: Tromp-Taylor
            # on a move-cap-truncated board would be arbitrary
            s = area_score(g.stones) if g.passes >= 2 else None
            scored += s is not None
            # atomic: selfplay SGFs feed corpus builds; never leave a torn
            # record under the final name (docs/static_analysis.md)
            with atomic_write(os.path.join(args.sgf_out,
                                           f"game_{i:04d}.sgf"),
                              mode="w") as f:
                f.write(to_sgf(g, result=s and s.result_string(),
                               komi=s and s.komi))
        print(f"wrote {len(games)} SGFs ({scored} finished/scored) "
              f"to {args.sgf_out}")


if __name__ == "__main__":
    main()
