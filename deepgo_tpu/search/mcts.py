"""Batched PUCT MCTS over the serving fleet: deep search as a service.

The source paper frames the CNN as a move evaluator whose real strength
appears when paired with search (arXiv:1412.6564 §Conclusion). This
module is that search, built AS A SERVING WORKLOAD rather than a
standalone engine:

  * **Wave-batched leaf evaluation** — descents run in waves of
    ``wave_size`` parallel simulations under virtual loss, every leaf a
    future submitted to whatever engine shape the caller hands over (a
    bare ``InferenceEngine``, a ``SupervisedEngine``, a ``FleetRouter``,
    or a test fake) so hundreds of leaves coalesce into the padded
    serving buckets instead of 1-board dispatches.
  * **Transposition table = content-addressed cache** — tree nodes are
    keyed on the ``utils/digest.py`` CANONICAL digest and store their
    statistics in the canonical dihedral frame; leaf evaluations submit
    the canonical view itself, so every transposition (and every
    symmetry of one) across all concurrent searches lands on the same
    PR 17 cache entry and shares one forward. The table persists across
    consecutive moves of a game: tree reuse is just a table hit.
  * **Anytime deadline contract** — ``deadline_s`` bounds the wall
    clock. A replica kill, a brownout, or a shed mid-search reverts
    that simulation's virtual losses (a LOST simulation, counted, never
    silently absorbed) and burns deadline headroom — the move itself is
    never lost: the search always returns a legal move (falling back to
    the lowest-index legal point only if the very first root evaluation
    cannot complete in budget).
  * **Traceable verdicts** — each search emits one ``search_request``
    event carrying the search id, chosen move, principal variation and
    loss/deadline accounting; leaf submissions ride the fleet with
    ``session="search:<id>"`` so the workload recorder and per-request
    traces join back to the search that caused them (``cli trace``).

Board stepping reuses ``selfplay.GameState`` (native batch kernels where
available); frame conversions are pure gathers through ``PERMS`` /
``INV_PERMS``: canonical edge ``p`` is actual point ``PERMS[k][p]``, an
actual ko point ``q`` is banned at canonical index ``INV_PERMS[k][q]``.

A ``Search`` instance runs one search at a time (not thread-safe);
concurrent searches each build their own ``Search`` and may SHARE one
``TranspositionTable`` (its own lock guards the entry map; concurrent
node-stat updates are benign statistical noise, not corruption — the
determinism tests use private tables). See docs/search.md.
"""

from __future__ import annotations

import dataclasses
import inspect
import time
import uuid
from collections import OrderedDict

import numpy as np

from .. import BOARD_SIZE
from ..analysis.lockcheck import make_lock
from ..features import P_AGE, P_STONES
from ..go.scoring import area_score
from ..obs import get_registry
from ..selfplay import GameState, legal_mask, step_game, summarize_state
from ..utils.digest import INV_PERMS, NUM_POINTS, PERMS, canonicalize

PASS_EDGE = NUM_POINTS   # edge 361: pass (the policy head has no pass output)
NUM_EDGES = NUM_POINTS + 1


@dataclasses.dataclass(frozen=True)
class SearchConfig:
    """One search's budget and shape. ``deadline_s`` is the anytime QoS
    knob ("best move in 200ms" vs "analyze for 10s"); ``tier`` maps the
    leaf traffic onto the fleet's priority ladder — it should stay on a
    CACHED tier (the fleet cache bypasses ``batch`` by default, and the
    transposition-sharing story depends on the cache)."""

    simulations: int = 128       # full budget; a deadline may cut it short
    wave_size: int = 16          # parallel virtual-loss descents per wave
    c_puct: float = 1.25
    virtual_loss: float = 1.0
    tier: str | None = "interactive"
    deadline_s: float | None = None
    eval_timeout_s: float = 30.0  # per-wave future timeout w/o a deadline
    temperature: float = 0.0     # root visit sampling (0 = argmax)
    rank: int = 9
    komi: float = 7.5
    max_moves: int = 450         # descent depth cap (move-cap leaf = draw)
    pass_prior: float = 1e-3     # pass edge prior vs the 361 point edges
    root_noise_frac: float = 0.0  # Dirichlet mix at the root (selfplay)
    root_noise_alpha: float = 0.12
    max_nodes: int = 100_000     # transposition-table LRU capacity


class Node:
    """One canonical position's edge statistics (362 edges, canonical
    frame). ``W`` accumulates values from THIS node's player's
    perspective; ``legal`` is ko-free board legality (ko is a property
    of the path, masked per-descent)."""

    __slots__ = ("digest", "player", "legal", "P", "N", "W", "expanded")

    def __init__(self, digest: str, player: int, legal: np.ndarray):
        self.digest = digest
        self.player = int(player)
        self.legal = legal
        self.P = None
        self.N = np.zeros(NUM_EDGES, dtype=np.float64)
        self.W = np.zeros(NUM_EDGES, dtype=np.float64)
        self.expanded = False

    def expand(self, log_probs: np.ndarray, pass_prior: float) -> None:
        """Priors from one canonical-frame policy row: masked to legal
        points, renormalized, with a fixed sliver for the pass edge
        (all mass when nothing is legal — the node must stay playable)."""
        p = np.zeros(NUM_EDGES, dtype=np.float64)
        row = np.asarray(log_probs, dtype=np.float64).reshape(-1)[:NUM_POINTS]
        if self.legal.any():
            probs = np.where(self.legal, np.exp(row - row.max()), 0.0)
            total = probs.sum()
            if total > 0:
                p[:NUM_POINTS] = probs / total * (1.0 - pass_prior)
                p[PASS_EDGE] = pass_prior
            else:   # degenerate row (all -inf on legal): uniform fallback
                p[:NUM_POINTS] = self.legal / self.legal.sum()
                p[:NUM_POINTS] *= (1.0 - pass_prior)
                p[PASS_EDGE] = pass_prior
        else:
            p[PASS_EDGE] = 1.0
        self.P = p
        self.expanded = True


class TranspositionTable:
    """LRU digest -> Node map shared across searches and across moves.

    Keyed on the canonical digest, so all eight dihedral views of a
    position — and the same position reached through different move
    orders or by different concurrent searches — resolve to one node.
    The lock guards the map only; node statistics are updated lock-free
    by their searches (see module docstring)."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = int(capacity)
        self._lock = make_lock("search.tt")
        self._entries: OrderedDict[str, Node] = OrderedDict()
        self.lookups = 0
        self.hits = 0
        self.evictions = 0

    def get(self, digest: str) -> Node | None:
        with self._lock:
            self.lookups += 1
            node = self._entries.get(digest)
            if node is not None:
                self.hits += 1
                self._entries.move_to_end(digest)
            return node

    def put(self, digest: str, node: Node) -> Node:
        """Insert (or return the already-present node — two searches
        racing to create the same leaf must converge on ONE node)."""
        with self._lock:
            existing = self._entries.get(digest)
            if existing is not None:
                return existing
            self._entries[digest] = node
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
            return node

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "lookups": self.lookups,
                    "hits": self.hits, "evictions": self.evictions,
                    "capacity": self.capacity}


class LeafEvaluator:
    """Adapter from ``engine.submit`` shapes to the search's needs.

    Signature-detects ``tier`` / ``session`` / ``timeout_s`` the same way
    the workload replayer does, so the one descent loop rides a
    FleetRouter (tiered, session-labeled, deadline-aware), a supervised
    or bare engine, or a test fake without per-backend branches."""

    def __init__(self, engine, tier: str | None = None,
                 session: str | None = None):
        self.engine = engine
        self.tier = tier
        self.session = session
        try:
            params = inspect.signature(engine.submit).parameters
        except (TypeError, ValueError):
            params = {}
        self._accepts = {k for k in ("tier", "session", "timeout_s")
                         if k in params}

    def submit(self, packed: np.ndarray, player: int, rank: int,
               timeout_s: float | None = None):
        kw = {}
        if "tier" in self._accepts and self.tier:
            kw["tier"] = self.tier
        if "session" in self._accepts and self.session:
            kw["session"] = self.session
        if "timeout_s" in self._accepts and timeout_s is not None:
            kw["timeout_s"] = timeout_s
        return self.engine.submit(packed, player, rank, **kw)


@dataclasses.dataclass
class SearchResult:
    """One search's verdict plus the accounting the QoS story runs on.

    ``move`` is an ACTUAL-frame flat index (-1 = pass) and is always
    legal for the position searched; ``visits`` are actual-frame root
    visit counts (the AlphaZero-style selfplay target), ``pv`` the
    principal variation as actual-frame indices from the root."""

    move: int
    value: float
    simulations: int
    lost: int
    waves: int
    wave_occupancy: float
    duration_s: float
    deadline_met: bool
    fallback: bool
    pv: list[int]
    search_id: str
    root_digest: str
    visits: np.ndarray
    pass_visits: float
    tt: dict


def game_from_packed(packed: np.ndarray, player: int,
                     legal_row: np.ndarray | None = None) -> GameState:
    """Reconstruct a steppable GameState from one packed record.

    Exact by construction: the packed record stores the stone grid
    (P_STONES) and the age grid (P_AGE, already clipped at go.MAX_AGE —
    ``play`` clips before ``summarize`` writes, so clipping is
    idempotent and re-summarizing the reconstruction is bitwise the
    original record). The simple-ko point is recovered from ``legal_row``
    when given: the unique point that is board-legal by the planes but
    masked from the caller's legal row is the banned recapture.
    """
    g = GameState()
    g.stones[:] = packed[P_STONES]
    g.age[:] = packed[P_AGE]
    g.player = int(player)
    if legal_row is not None:
        board_legal = legal_mask(
            packed[None], np.array([player], dtype=np.int32))[0]
        banned = np.flatnonzero(board_legal & ~np.asarray(legal_row,
                                                          dtype=bool))
        if len(banned) == 1:
            g.ko_point = divmod(int(banned[0]), BOARD_SIZE)
    return g


def _clone(g: GameState) -> GameState:
    c = GameState.__new__(GameState)
    c.stones = g.stones.copy()
    c.age = g.age.copy()
    c.player = g.player
    c.moves = list(g.moves)
    c.passes = g.passes
    c.done = g.done
    c.ko_point = g.ko_point
    return c


def _terminal_value(g: GameState, player: int, komi: float) -> float:
    """z in {-1, 0, +1} from ``player``'s perspective for a finished
    descent: Tromp-Taylor for a double pass, a draw for a move-cap
    truncation (scoring an arbitrary truncation would be noise)."""
    if g.passes < 2:
        return 0.0
    w = area_score(g.stones, komi=komi).winner
    if w == 0:
        return 0.0
    return 1.0 if w == player else -1.0


def make_move_selector(engine, config: SearchConfig | None = None,
                       value_engine=None,
                       table: TranspositionTable | None = None,
                       metrics=None):
    """A ``selfplay.self_play(move_selector=...)`` hook: AlphaZero-style
    search-selfplay. Each active game gets one PUCT search (root
    Dirichlet noise + visit-count temperature by default — the
    exploration mix expert iteration needs); all games in the actor
    share one transposition table, so the selfplay fleet's
    transpositions collapse onto shared forwards like everything else."""
    cfg = config or SearchConfig(simulations=64, wave_size=16,
                                 tier="selfplay", temperature=1.0,
                                 root_noise_frac=0.25)
    tt = table if table is not None else TranspositionTable(cfg.max_nodes)
    search = Search(engine, cfg, table=tt, value_engine=value_engine,
                    metrics=metrics)

    def select(games, packed, players, legal, rng):
        search.rng = rng
        return [search.search(games[i], root_legal=legal[i]).move
                for i in range(len(games))]

    select.search = search   # introspection for stats/tests
    return select


class Search:
    """PUCT MCTS: virtual-loss wave descent, canonical transpositions,
    anytime deadlines. One instance per concurrent searcher; the
    ``TranspositionTable`` may be shared (and persists across moves —
    that IS the tree reuse)."""

    def __init__(self, engine, config: SearchConfig | None = None,
                 table: TranspositionTable | None = None,
                 value_engine=None, rng: np.random.Generator | None = None,
                 metrics=None, search_session: str | None = None):
        self.cfg = config or SearchConfig()
        self.table = table if table is not None else TranspositionTable(
            self.cfg.max_nodes)
        self.value_engine = value_engine
        self.rng = rng if rng is not None else np.random.default_rng(0)
        self._metrics = metrics
        self._session = search_session
        self._engine = engine
        self._evaluator: LeafEvaluator | None = None
        self._root_P: np.ndarray | None = None
        self._root_mask: np.ndarray | None = None
        reg = get_registry()
        self._obs_sims = reg.counter(
            "deepgo_search_simulations_total",
            "completed (backed-up) PUCT simulations")
        self._obs_lost = reg.counter(
            "deepgo_search_lost_simulations_total",
            "simulations reverted after a failed/timed-out leaf eval "
            "(the anytime contract: deadline headroom burned, never "
            "the move)")
        self._obs_waves = reg.counter(
            "deepgo_search_waves_total", "leaf-evaluation waves dispatched")
        self._obs_fallback = reg.counter(
            "deepgo_search_fallback_moves_total",
            "moves answered by the legal-move fallback because the root "
            "evaluation never completed in budget")
        self._obs_rate = reg.gauge(
            "deepgo_search_simulations_per_sec",
            "simulations/sec of the most recent search")
        self._obs_occupancy = reg.gauge(
            "deepgo_search_wave_occupancy",
            "unique leaves per wave / wave_size of the most recent search")
        self._obs_nodes = reg.gauge(
            "deepgo_search_tree_nodes",
            "transposition-table entries after the most recent search")

    # -- descent -----------------------------------------------------------

    def _canonical_legal(self, view: np.ndarray, player: int) -> np.ndarray:
        """(361,) ko-free board legality in the canonical frame —
        computed directly from the canonical view's planes (legality is
        a pure function of the planes, so this equals
        ``legal_actual[PERMS[k]]``)."""
        return legal_mask(view[None],
                          np.array([player], dtype=np.int32))[0]

    def _select_edge(self, node: Node, g: GameState, k: int,
                     is_root: bool) -> int:
        """PUCT argmax over this node's playable edges. Deterministic:
        numpy argmax breaks ties by lowest index."""
        allowed = np.empty(NUM_EDGES, dtype=bool)
        allowed[:NUM_POINTS] = node.legal
        allowed[PASS_EDGE] = True
        if g.ko_point is not None:
            q = g.ko_point[0] * BOARD_SIZE + g.ko_point[1]
            allowed[int(INV_PERMS[k][q])] = False
        if is_root and self._root_mask is not None:
            allowed[:NUM_POINTS] &= self._root_mask
        P = (self._root_P if is_root and self._root_P is not None
             else node.P)
        N, W = node.N, node.W
        q_val = np.divide(W, N, out=np.zeros(NUM_EDGES), where=N > 0)
        u = self.cfg.c_puct * P * (np.sqrt(N.sum() + 1.0) / (1.0 + N))
        score = np.where(allowed, q_val + u, -np.inf)
        return int(score.argmax())

    def _descend(self, root_game: GameState):
        """One virtual-loss simulation from the root. Returns
        ``("terminal", value_player, path)`` with the terminal value
        context, or ``("leaf", (digest, view, k, player), path)`` for a
        position that needs (or is awaiting) a leaf evaluation."""
        g = _clone(root_game)
        path: list[tuple[Node, int]] = []
        is_root = True
        while True:
            if g.done:
                return "terminal", g, path
            packed = summarize_state(g)
            digest, view, k = canonicalize(packed, g.player, self.cfg.rank)
            node = self.table.get(digest)
            if node is None:
                node = self.table.put(digest, Node(
                    digest, g.player, self._canonical_legal(view, g.player)))
            if not node.expanded:
                return "leaf", (digest, view, k, g.player), path
            edge = self._select_edge(node, g, k, is_root)
            is_root = False
            node.N[edge] += 1.0
            node.W[edge] -= self.cfg.virtual_loss
            path.append((node, edge))
            move = -1 if edge == PASS_EDGE else int(PERMS[k][edge])
            step_game(g, move, self.cfg.max_moves)

    def _backup(self, path: list[tuple[Node, int]], value: float,
                value_player: int) -> None:
        """Convert each edge's virtual loss into a real visit: the -vloss
        applied on the way down comes back, plus the value signed into
        each node's own perspective."""
        vloss = self.cfg.virtual_loss
        for node, edge in path:
            signed = value if node.player == value_player else -value
            node.W[edge] += vloss + signed
        self._obs_sims.inc(1)

    def _revert(self, path: list[tuple[Node, int]]) -> None:
        """A lost simulation: undo its virtual losses entirely so a
        failed eval can never bias the tree (the double-count guard the
        determinism tests pin)."""
        vloss = self.cfg.virtual_loss
        for node, edge in path:
            node.N[edge] -= 1.0
            node.W[edge] += vloss
        self._obs_lost.inc(1)

    # -- leaf evaluation ---------------------------------------------------

    def _leaf_values(self, views: list[np.ndarray],
                     players: list[int]) -> np.ndarray:
        """Leaf values in [-1, 1] from each leaf player's perspective:
        the value net's win probability mapped to 2v-1 when a value
        engine is attached, else 0 (pure prior-guided search)."""
        if self.value_engine is None or not views:
            return np.zeros(len(views))
        ranks = np.full(len(views), self.cfg.rank, dtype=np.int32)
        v = np.asarray(self.value_engine.evaluate(
            np.stack(views), np.array(players, dtype=np.int32), ranks),
            dtype=np.float64).reshape(-1)
        return 2.0 * v - 1.0

    def _expand_root(self, game: GameState, deadline: float | None):
        """Make sure the root node is expanded (tree reuse makes this a
        table hit on every move after a game's first). Returns the
        (digest, view, k, node) root context, or None when the eval
        cannot complete in budget (the caller falls back)."""
        packed = summarize_state(game)
        digest, view, k = canonicalize(packed, game.player, self.cfg.rank)
        node = self.table.get(digest)
        if node is None:
            node = self.table.put(digest, Node(
                digest, game.player, self._canonical_legal(view,
                                                           game.player)))
        if node.expanded:
            return digest, view, k, node
        timeout = self._remaining(deadline)
        try:
            fut = self._evaluator.submit(view, game.player, self.cfg.rank,
                                         timeout_s=timeout)
            row = np.asarray(fut.result(timeout=timeout))
        except Exception:  # noqa: BLE001 — any shed/kill/timeout: fallback
            return None
        node.expand(row, self.cfg.pass_prior)
        return digest, view, k, node

    def _remaining(self, t_end: float | None) -> float:
        if t_end is None:
            return self.cfg.eval_timeout_s
        return max(t_end - time.monotonic(), 0.05)

    # -- the search --------------------------------------------------------

    def search(self, game: GameState, simulations: int | None = None,
               deadline_s: float | None = None,
               root_legal: np.ndarray | None = None) -> SearchResult:
        """Best move for ``game``'s side to move under the configured
        budget. ``root_legal`` (actual-frame (361,) bool) further
        restricts the ROOT move set — the superko hook for callers whose
        rules are stricter than the descent's simple ko; the returned
        move always satisfies it."""
        cfg = self.cfg
        sims = int(simulations if simulations is not None
                   else cfg.simulations)
        deadline = (deadline_s if deadline_s is not None
                    else cfg.deadline_s)
        t0 = time.monotonic()
        t_end = None if deadline is None else t0 + deadline
        search_id = uuid.uuid4().hex[:12]
        self._evaluator = LeafEvaluator(
            self._engine, tier=cfg.tier,
            session=self._session or f"search:{search_id}")
        self._root_mask = (np.asarray(root_legal, dtype=bool)
                           if root_legal is not None else None)
        self._root_P = None

        done = lost = waves = 0
        leaves_submitted = 0
        fallback = False
        root_ctx = self._expand_root(game, t_end)
        if root_ctx is None:
            # anytime contract: the move is never lost — answer with the
            # lowest-index legal point (or pass) and account for it
            self._obs_fallback.inc(1)
            fallback = True
            legal = legal_mask(summarize_state(game)[None],
                               np.array([game.player], dtype=np.int32),
                               [game])[0]
            if self._root_mask is not None:
                legal &= self._root_mask
            idx = np.flatnonzero(legal)
            move = int(idx[0]) if len(idx) else -1
            return self._finish(game, search_id, move, 0.0, 0, 0, 0, 0.0,
                                t0, t_end, fallback, [])
        root_digest, _root_view, root_k, root = root_ctx

        if cfg.root_noise_frac > 0.0:
            legal_idx = np.flatnonzero(root.legal)
            if len(legal_idx):
                noise = self.rng.dirichlet(
                    np.full(len(legal_idx), cfg.root_noise_alpha))
                mixed = root.P.copy()
                mixed[legal_idx] = ((1.0 - cfg.root_noise_frac)
                                    * mixed[legal_idx]
                                    + cfg.root_noise_frac * noise)
                self._root_P = mixed

        # `done + lost` bounds the loop: a dead fleet cannot spin the
        # search forever — every failed wave burns budget (and, with a
        # deadline, wall clock) until the anytime finalization fires
        while done + lost < sims:
            if t_end is not None and time.monotonic() >= t_end:
                break
            want = min(cfg.wave_size, sims - done - lost)
            pending: OrderedDict[str, dict] = OrderedDict()
            for _ in range(want):
                kind, info, path = self._descend(game)
                if kind == "terminal":
                    g_t = info
                    z = _terminal_value(g_t, g_t.player, cfg.komi)
                    self._backup(path, z, g_t.player)
                    done += 1
                    continue
                digest, view, k, player = info
                entry = pending.get(digest)
                if entry is None:
                    pending[digest] = {"view": view, "player": player,
                                       "paths": [path]}
                else:   # wave-internal transposition: one submit, n paths
                    entry["paths"].append(path)
            waves += 1
            if not pending:
                continue
            timeout = self._remaining(t_end)
            futs: OrderedDict[str, object] = OrderedDict()
            for digest, entry in pending.items():
                try:
                    futs[digest] = self._evaluator.submit(
                        entry["view"], entry["player"], cfg.rank,
                        timeout_s=timeout)
                except Exception:  # noqa: BLE001 — door shed: lost sims
                    for path in entry["paths"]:
                        self._revert(path)
                        lost += 1
            leaves_submitted += len(futs)
            resolved = []
            for digest, fut in futs.items():
                entry = pending[digest]
                try:
                    row = np.asarray(
                        fut.result(timeout=self._remaining(t_end)))
                except Exception:  # noqa: BLE001 — kill/timeout mid-wave
                    for path in entry["paths"]:
                        self._revert(path)
                        lost += 1
                    continue
                resolved.append((digest, entry, row))
            values = self._leaf_values(
                [e["view"] for _, e, _ in resolved],
                [e["player"] for _, e, _ in resolved])
            for (digest, entry, row), z in zip(resolved, values):
                node = self.table.get(digest)
                if node is not None and not node.expanded:
                    node.expand(row, cfg.pass_prior)
                for path in entry["paths"]:
                    self._backup(path, float(z), entry["player"])
                    done += 1

        # -- move selection over root visits (actual frame) ----------------
        allowed = np.empty(NUM_EDGES, dtype=bool)
        allowed[:NUM_POINTS] = root.legal
        allowed[PASS_EDGE] = True
        if game.ko_point is not None:
            q = game.ko_point[0] * BOARD_SIZE + game.ko_point[1]
            allowed[int(INV_PERMS[root_k][q])] = False
        if self._root_mask is not None:
            allowed[:NUM_POINTS] &= self._root_mask
        counts = np.where(allowed, root.N, -1.0)
        if cfg.temperature > 0 and counts.max() > 0:
            w = np.where(allowed, np.maximum(root.N, 0.0), 0.0)
            w = w ** (1.0 / cfg.temperature)
            edge = int(self.rng.choice(NUM_EDGES, p=w / w.sum()))
        elif counts.max() > 0:
            edge = int(counts.argmax())
        else:   # zero completed sims: fall back to the root prior
            prior = np.where(allowed, root.P, -np.inf)
            edge = int(prior.argmax())
        move = -1 if edge == PASS_EDGE else int(PERMS[root_k][edge])
        q_move = (root.W[edge] / root.N[edge]) if root.N[edge] > 0 else 0.0
        pv = self._principal_variation(game)
        occupancy = (leaves_submitted / (waves * cfg.wave_size)
                     if waves else 0.0)
        return self._finish(game, search_id, move, float(q_move), done,
                            lost, waves, occupancy, t0, t_end, fallback,
                            pv, root=root, root_k=root_k,
                            root_digest=root_digest)

    def _principal_variation(self, game: GameState,
                             max_depth: int = 12) -> list[int]:
        """Max-visit walk from the root through the table: the moves (in
        the ACTUAL frame of each successive position) the search most
        believes in. Stops at unexpanded/unvisited nodes."""
        pv: list[int] = []
        g = _clone(game)
        for _ in range(max_depth):
            if g.done:
                break
            packed = summarize_state(g)
            digest, _, k = canonicalize(packed, g.player, self.cfg.rank)
            node = self.table.get(digest)
            if node is None or not node.expanded or node.N.max() <= 0:
                break
            allowed = np.empty(NUM_EDGES, dtype=bool)
            allowed[:NUM_POINTS] = node.legal
            allowed[PASS_EDGE] = True
            if g.ko_point is not None:
                q = g.ko_point[0] * BOARD_SIZE + g.ko_point[1]
                allowed[int(INV_PERMS[k][q])] = False
            counts = np.where(allowed, node.N, -1.0)
            if counts.max() <= 0:
                break
            edge = int(counts.argmax())
            move = -1 if edge == PASS_EDGE else int(PERMS[k][edge])
            pv.append(move)
            step_game(g, move, self.cfg.max_moves)
        return pv

    def _finish(self, game: GameState, search_id: str, move: int,
                value: float, done: int, lost: int, waves: int,
                occupancy: float, t0: float, t_end: float | None,
                fallback: bool, pv: list[int], root=None,
                root_k: int = 0, root_digest: str = "") -> SearchResult:
        duration = time.monotonic() - t0
        deadline_met = t_end is None or (t0 + duration) <= t_end + 0.05
        self._obs_waves.inc(waves)
        self._obs_rate.set(done / duration if duration > 0 else 0.0)
        self._obs_occupancy.set(occupancy)
        self._obs_nodes.set(len(self.table))
        if root is not None:
            visits = np.zeros(NUM_POINTS)
            # canonical edge p is actual point PERMS[k][p]
            visits[PERMS[root_k]] = np.maximum(root.N[:NUM_POINTS], 0.0)
            pass_visits = float(max(root.N[PASS_EDGE], 0.0))
        else:
            visits = np.zeros(NUM_POINTS)
            pass_visits = 0.0
        result = SearchResult(
            move=move, value=value, simulations=done, lost=lost,
            waves=waves, wave_occupancy=round(occupancy, 4),
            duration_s=round(duration, 6), deadline_met=deadline_met,
            fallback=fallback, pv=pv, search_id=search_id,
            root_digest=root_digest, visits=visits,
            pass_visits=pass_visits, tt=self.table.stats())
        if self._metrics is not None:
            try:
                self._metrics.write(
                    "search_request", search_id=search_id,
                    digest=root_digest, move=move, value=round(value, 4),
                    simulations=done, lost=lost, waves=waves,
                    wave_occupancy=round(occupancy, 4),
                    duration_s=round(duration, 6),
                    deadline_s=(None if t_end is None
                                else round(t_end - t0, 6)),
                    deadline_met=deadline_met, fallback=fallback,
                    pv=list(pv), tier=self.cfg.tier)
            except (OSError, ValueError):
                pass  # a full disk must not fail the search
        return result
