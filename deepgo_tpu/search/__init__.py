"""Deep search as a service: batched PUCT MCTS over the serving fleet.

The tree search the source paper points at (arXiv:1412.6564
§Conclusion: the policy net as a search prior), built as a serving
workload — wave-batched leaf futures through the fleet router, a
transposition table keyed on the content-addressed canonical digests,
anytime deadline QoS on the priority tiers. See docs/search.md.
"""

from .mcts import (NUM_EDGES, PASS_EDGE, LeafEvaluator, Node,  # noqa: F401
                   Search, SearchConfig, SearchResult,
                   TranspositionTable, game_from_packed,
                   make_move_selector)
