"""Deadline-wrapped distributed bootstrap and collectives.

A wedged DCN collective — or a ``jax.distributed.initialize`` dialing a
coordinator that will never answer — blocks inside a C call holding the
GIL, so no in-process timer can interrupt it (the PR 1 watchdog postmortem:
bench watchdog thread never fired; the driver recorded silent rc=124
timeouts). The only robust deadline is the external-process watchdog
(``utils/watchdog.py``); this module arms it around the two places a
multi-host job can wedge forever:

  * **bootstrap** — ``initialize_with_deadline`` retries the coordinator
    dial with bounded full-jitter backoff (a restarting coordinator is the
    common transient; a herd of hosts re-dialing in lockstep is the common
    mistake), wraps reachability failures in a typed
    ``CoordinatorUnreachable``, and keeps the watchdog armed across the
    whole retry envelope so a *hanging* (rather than failing) dial still
    dies loud in seconds.
  * **the first sharded step** — ``guard_first_call`` arms the watchdog
    around a step function's first invocation only (compile + the first
    cross-host collective execution, blocked on to completion inside the
    guard); later calls pass straight through at zero cost.

The ``dist_init`` fault site fires inside the retried bootstrap attempt
(transient faults are absorbed by the retry, hard ones surface), giving
the PR 1 chaos grammar reach into the multi-host layer.
"""

from __future__ import annotations

import contextlib
import functools
import time

from ..utils import watchdog
from ..utils.retry import retry_with_backoff
from . import distributed
from .liveness import CoordinatorUnreachable


def _reachability_errors() -> tuple:
    """Exception types that mean "the coordinator is not answering" (as
    opposed to a logic error): OS-level connect failures plus the XLA
    runtime error jax raises on a dead/timed-out coordination service."""
    errs: list = [ConnectionError, OSError, TimeoutError]
    try:
        import jax

        xla_err = getattr(jax.errors, "JaxRuntimeError", None)
        if xla_err is not None:
            errs.append(xla_err)
    except ImportError:  # pragma: no cover - jax is a hard dep elsewhere
        pass
    return tuple(errs)


@contextlib.contextmanager
def deadline(label: str, timeout_s: float, diagnostic_json: str | None = None,
             arm=watchdog.arm):
    """Arm the external watchdog for the duration of the block; a block
    that outlives ``timeout_s`` is SIGKILLed (loud, diagnosable) instead of
    hanging. ``timeout_s <= 0`` disables (yields an unarmed handle)."""
    wd = (arm(label, timeout_s, diagnostic_json=diagnostic_json)
          if timeout_s and timeout_s > 0 else watchdog.Watchdog(None))
    try:
        yield wd
    finally:
        wd.disarm()


def initialize_with_deadline(coordinator: str | None = None,
                             num_processes: int | None = None,
                             process_id: int | None = None, *,
                             timeout_s: float = 120.0,
                             attempts: int = 5,
                             base_delay: float = 0.5,
                             max_delay: float = 8.0,
                             rng=None,
                             sleep=time.sleep,
                             arm=watchdog.arm) -> None:
    """Join the jax.distributed runtime, loudly bounded in time.

    Reachability failures (connect refused/reset, DEADLINE_EXCEEDED from
    the coordination service, injected ``dist_init`` transients) are
    retried up to ``attempts`` times with **full-jitter** exponential
    backoff — every host observed the same coordinator restart at the same
    instant, and deterministic delays would re-synchronize the herd into
    thundering re-dials. The final failure raises a typed
    ``CoordinatorUnreachable`` naming the coordinator. A dial that *hangs*
    instead of failing is SIGKILLed by the external watchdog after
    ``timeout_s`` (0 disables). Hard injected faults (``dist_init:fail@N``)
    are logic-level and propagate immediately, un-retried.

    Single-process runs (no coordinator, ``num_processes=1``) stay the
    no-op they always were — minus the armed watchdog, which still
    protects the (local, instant) bootstrap path at negligible cost.
    """
    reach = _reachability_errors()

    def attempt() -> None:
        try:
            distributed.initialize(coordinator, num_processes, process_id)
        except reach as e:
            raise CoordinatorUnreachable(
                f"coordinator {coordinator or '<auto>'} unreachable: "
                f"{type(e).__name__}: {e}") from e

    with deadline(f"dist-init({coordinator or 'local'})", timeout_s, arm=arm):
        retry_with_backoff(
            attempt,
            attempts=attempts,
            base_delay=base_delay,
            max_delay=max_delay,
            retry_on=(CoordinatorUnreachable,),
            jitter=True,
            rng=rng,
            sleep=sleep,
        )


def guard_first_call(fn, label: str, timeout_s: float, arm=watchdog.arm):
    """Wrap a (jitted) step function so its FIRST call runs under the
    external watchdog and is blocked on to completion.

    The first sharded step is where a broken multi-host job wedges: the
    compile barrier and the first DCN all-reduce both require every
    participant, so one dead host turns the call into a silent multi-minute
    hang. Blocking on the outputs inside the guard makes the deadline cover
    *execution*, not just dispatch (async dispatch returns before the
    collective runs). Every later call passes through untouched — steady
    -state steps are watched by the heartbeat ledger, not a per-call
    watchdog."""
    state = {"first_done": False}

    @functools.wraps(fn)
    def guarded(*args, **kwargs):
        if state["first_done"]:
            return fn(*args, **kwargs)
        import jax

        with deadline(label, timeout_s, arm=arm):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        state["first_done"] = True
        return out

    return guarded
