"""Device mesh construction and canonical shardings.

The reference's only parallelism is single-host multi-GPU data parallelism
through nn.DataParallelTable (reference experiments.lua:155-168): batch split
on dim 1, gradients reduced across replicas. The TPU-native equivalent is a
("data", "model") mesh with batches sharded on "data" and parameters
replicated; under jit, XLA inserts the gradient all-reduce over ICI
automatically from the sharding constraints — there is no hand-written
collective in the data-parallel path.

The "model" axis is kept open for tensor parallelism (channel-sharded convs,
deepgo_tpu.parallel.tensor) even though the reference has none (SURVEY.md
section 2.3): on a mesh of shape (D, M) every conv weight is sharded on its
output-channel dimension over M.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_data: int | None = None, n_model: int = 1,
              devices=None) -> Mesh:
    """A ("data", "model") mesh. Defaults to all local devices on the data
    axis; n_data=1, n_model=1 gives the degenerate single-device mesh."""
    from .liveness import ConfigError

    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_model
    used = n_data * n_model
    if used > len(devices):  # typed, not assert: must fail under python -O
        raise ConfigError(
            f"mesh {n_data}x{n_model} needs {used} devices, have {len(devices)}"
        )
    grid = np.array(devices[:used]).reshape(n_data, n_model)
    return Mesh(grid, axis_names=("data", "model"))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-axis sharding: dim 0 split over "data", rest replicated."""
    return NamedSharding(mesh, P("data"))


def superbatch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for stacked (K, B, ...) superbatches: the steps dimension is
    unsharded (lax.scan iterates it), the batch dimension splits over
    "data" exactly like data_sharding."""
    return NamedSharding(mesh, P(None, "data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
