"""Elastic multi-host training: checkpoint-coordinated re-mesh recovery.

The recovery contract (docs/robustness.md, "Distributed failure domains"):
when a participating host dies, every survivor independently

  1. **detects** the loss — its heartbeat silence exceeds the miss budget
     (``liveness.HeartbeatLedger``), raised as a typed ``HostLost`` out of
     the training loop's window hook;
  2. **converges** on the newest valid checkpoint in the shared run
     directory (``checkpoint.find_latest_valid`` — PR 1's elastic resume,
     which already skips corrupt candidates), discarding its own
     in-memory state: survivors must agree on *one* restart point, and
     the checkpoint is the only state they provably share;
  3. **re-meshes** over the surviving process set (``remesh``) and
     re-balances the global batch (``per_host_batch`` with the surviving
     count). With ``ElasticConfig.reshard`` the re-mesh may *change the
     tp factor* (``shrink_tp`` scales "model" down with the surviving
     fraction): the converge step then routes through the resharding
     restore (parallel/reshard.py) — checkpoint leaves re-scatter into
     the new composed dp×tp×ZeRO placement, with the sharding-claim
     checker armed for the duration so a silent replicated-instead-of-
     sharded restore is a recorded finding, and ``per_host_batch`` is
     re-derived against the new data width;
  4. **resumes** — and because the synchronous data stream is
     step-indexed (``loader.step_rng``: the batch for step t is a pure
     function of (seed, t)), the continuation is bit-exact against an
     uninterrupted run over the same step indices, re-mesh or not. The
     acceptance test asserts exactly this.

Steps are lost (rollback to the checkpoint), never corrupted — the same
trade PR 1 made for single-host kills. Recovery latency and steps-lost are
measured and reported (``ELASTIC_RECOVERY`` / ``ELASTIC_DONE`` JSON lines
on stdout; ``elastic-<host>.jsonl`` metrics in the run directory), so the
cost of surviving failure is a number, not a hope.

On this container multi-host is *simulated*: each "host" is a process with
its own local device world, coordinated purely through the shared
filesystem (heartbeats + checkpoints) — the CPU backend has no
cross-process collectives ("Multiprocess computations aren't implemented on
the CPU backend"), and a live jax.distributed runtime cannot shrink
in-process anyway. On a real pod the same loop applies per host, with the
relaunch re-entering through the deadline-wrapped bootstrap
(``deadlines.initialize_with_deadline``); checkpoint convergence is what
makes that relaunch safe.
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass

import jax

from ..obs import (configure_flight, flight_dump, get_registry,
                   health_from_ledger, start_exporter)
from ..utils.metrics import MetricsWriter
from .deadlines import guard_first_call, initialize_with_deadline
from .distributed import hybrid_mesh, per_host_batch
from .liveness import (ConfigError, HeartbeatLedger, HeartbeatWriter,
                       HostLost)


@dataclass(frozen=True)
class ElasticConfig:
    """Infrastructure knobs for one elastic host (CLI: ``train --elastic``).

    These are per-launch facts — which host am I, who else should exist,
    how patient is liveness — so they live here, not in ExperimentConfig
    (which rides inside checkpoints and must describe the *model run*)."""

    process_id: int = 0
    expected_hosts: int = 1
    heartbeat_interval_s: float = 1.0
    miss_budget: int = 3
    straggler_factor: float = 3.0
    min_straggler_beats: int = 3
    init_deadline_s: float = 120.0
    step_deadline_s: float = 0.0   # 0 = no watchdog around the first step
    max_recoveries: int = 8
    # allow recovery to shrink the tensor-parallel factor with the
    # surviving fraction (shrink_tp) and reshard the checkpoint state
    # into the new layout; off = re-mesh keeps the stored tp (a loss
    # that strands too few devices for it then surfaces as ConfigError)
    reshard: bool = False
    coordinator: str | None = None
    num_processes: int | None = None
    heartbeat_dir: str = ""        # default: <run_dir>/heartbeats
    # live observability endpoint (docs/observability.md): /metrics +
    # /healthz on this port (0 = ephemeral, None = no exporter). The
    # health verdict composes the heartbeat ledger, so a peer kill flips
    # /healthz to 503 within this host's own miss budget.
    obs_port: int | None = None


def remesh(n_model: int, survivors: set[int]):
    """The mesh after a host loss.

    Real multi-process runtime: the hybrid mesh restricted to surviving
    process indices (hosts-major ordering preserved). Simulated hosts
    (single jax process): the local device world IS the surviving world,
    so the full local hybrid mesh. Raises ConfigError when the surviving
    set owns no devices."""
    if jax.process_count() > 1:
        alive = sorted(p for p in survivors if p < jax.process_count())
        return hybrid_mesh(n_model, processes=alive)
    return hybrid_mesh(n_model)


def shrink_tp(n_model: int, alive: int, expected: int) -> int:
    """Target tensor-parallel factor after shrinking to ``alive`` of
    ``expected`` hosts: scale "model" down with the surviving fraction,
    rounded down to the nearest divisor of the original factor (a
    non-divisor would split the already-partitioned channel dims
    unevenly). Never below 1 — a single survivor still trains, fully
    replicated."""
    if n_model <= 1 or alive >= expected:
        return max(1, n_model)
    target = max(1, (n_model * alive) // max(1, expected))
    while n_model % target:
        target -= 1
    return target


def run_elastic(run_dir: str, total_iters: int, *, overrides: dict | None = None,
                ecfg: ElasticConfig = ElasticConfig(), clock=time.time,
                log=None) -> dict:
    """Train to ``total_iters`` total steps, surviving peer-host death.

    Each participating host runs this over the same ``run_dir`` (shared
    filesystem). Semantics match ``cli train --auto-resume``: ``total_iters``
    is the TOTAL step target, so re-running the identical command after any
    number of kills — of this host or its peers — converges on the same
    final state. Returns the summary dict also printed as the
    ``ELASTIC_DONE`` JSON line."""
    from ..experiments import Experiment

    if log is None:
        def log(msg):
            print(msg, file=sys.stderr, flush=True)

    if ecfg.expected_hosts < 1:
        raise ConfigError(
            f"expected_hosts must be >= 1, got {ecfg.expected_hosts}")
    if not (0 <= ecfg.process_id < ecfg.expected_hosts):
        raise ConfigError(
            f"process_id {ecfg.process_id} outside expected_hosts "
            f"{ecfg.expected_hosts}")

    # bootstrap: deadline-wrapped, retried, typed (a no-op single-process,
    # but the dist_init fault site and the watchdog cover it either way)
    initialize_with_deadline(ecfg.coordinator, ecfg.num_processes,
                             ecfg.process_id, timeout_s=ecfg.init_deadline_s)

    hb_dir = ecfg.heartbeat_dir or os.path.join(run_dir, "heartbeats")
    writer = HeartbeatWriter(hb_dir, ecfg.process_id, clock=clock)
    ledger = HeartbeatLedger(hb_dir, interval_s=ecfg.heartbeat_interval_s,
                             miss_budget=ecfg.miss_budget, clock=clock,
                             log=log)
    survivors = set(range(ecfg.expected_hosts))
    metrics = MetricsWriter(os.path.join(
        run_dir, f"elastic-{ecfg.process_id:04d}.jsonl"))
    metrics.write("elastic_start", host=ecfg.process_id,
                  expected_hosts=ecfg.expected_hosts,
                  budget_s=ledger.budget_s)
    # arm the crash flight recorder over the shared run dir BEFORE the
    # training loop configures its own default: a HostLost dump then
    # lands next to the heartbeats every survivor can read
    configure_flight(run_dir)
    reg = get_registry()
    obs_recoveries = reg.counter(
        "deepgo_elastic_recoveries_total",
        "host losses recovered via checkpoint convergence + re-mesh")
    obs_steps_lost = reg.counter(
        "deepgo_elastic_steps_lost_total",
        "steps rolled back to the converged checkpoint across recoveries")
    obs_alive = reg.gauge(
        "deepgo_elastic_hosts_alive", "surviving host count")
    obs_alive.set(len(survivors))
    exporter = None
    # /healthz state shared with the recovery loop: the ledger check
    # alone is not enough — the loop shrinks ``survivors`` the instant it
    # detects a loss, which would flip the endpoint back to healthy
    # mid-recovery. The latch keeps it 503 from detection until the
    # recovery record is finalized (resumed from the converged
    # checkpoint), so the degraded window is observable from outside at
    # any scrape cadence, not just in the sub-window race.
    recovering = {"active": False, "lost": None}
    if ecfg.obs_port is not None:
        exporter = start_exporter(ecfg.obs_port)
        ledger_check = health_from_ledger(
            ledger, lambda: survivors - {ecfg.process_id})

        def fleet_health() -> dict:
            out = ledger_check()
            if recovering["active"]:
                out["healthy"] = False
                out["recovering"] = True
                out["lost_process_id"] = recovering["lost"]
            return out

        exporter.add_health("heartbeats", fleet_health)

    recoveries: list[dict] = []
    pending_loss: dict | None = None
    # parallelism-layout override for the converge step, set by the
    # HostLost handler when ecfg.reshard shrinks tp; sticky across
    # further losses (later checkpoints carry the new layout anyway)
    remesh_overrides: dict | None = None
    exp = None
    # fresh starts must record that this run is elastic (the flag rides in
    # the checkpoint config and threads the dist_collective fault site
    # through the jitted steps); resumes take the stored config as always
    overrides = dict(overrides or {})
    overrides["elastic"] = True
    try:
        while True:
            if pending_loss is not None:
                # post-loss converge: arm the sharding-claim checker for
                # the duration of the resharding restore — "recovered
                # onto the new mesh" must mean verifiably placed, not
                # silently replicated (docs/robustness.md)
                from ..analysis import xlacheck

                xlacheck.enable(True)
                try:
                    exp = Experiment.auto_resume(
                        run_dir, overrides=dict(overrides), log=log,
                        remesh=remesh_overrides)
                finally:
                    xlacheck.enable(None)
                metrics.write("reshard_restore", host=ecfg.process_id,
                              tp=exp.config.tensor_parallel,
                              findings=len(exp.last_restore_findings))
            else:
                exp = Experiment.auto_resume(run_dir,
                                             overrides=dict(overrides),
                                             log=log)
            if pending_loss is not None:
                # finalize the recovery record now that we know where the
                # fleet converged (the checkpoint step survives; everything
                # the dead host's peers computed past it is rolled back)
                now = clock()
                rec = dict(pending_loss)
                rec.update(
                    resumed_step=exp.step,
                    steps_lost=max(0, rec["step_at_detection"] - exp.step),
                    recovery_latency_s=now - rec["last_seen"],
                    detect_latency_s=rec["detected_at"] - rec["last_seen"],
                    survivors=sorted(survivors),
                    tp=exp.config.tensor_parallel,
                    sharding_findings=len(exp.last_restore_findings),
                )
                del rec["detected_at"]
                recoveries.append(rec)
                obs_recoveries.inc()
                obs_steps_lost.inc(rec["steps_lost"])
                obs_alive.set(len(survivors))
                metrics.write("recovery", **rec)
                print("ELASTIC_RECOVERY " + json.dumps(rec), flush=True)
                pending_loss = None
                recovering["active"] = False
            remaining = total_iters - exp.step
            if remaining <= 0:
                log(f"elastic host {ecfg.process_id}: step {exp.step} already "
                    f"meets --iters {total_iters}; nothing to do")
                summary = {"final_step": exp.step, "final_ewma": exp.ewma}
                break
            if not exp.initialized:
                exp.init()
            if ecfg.step_deadline_s > 0:
                # the first sharded step (compile + first collective) is
                # where a broken fleet wedges; arm the external watchdog
                # around exactly that call
                exp.train_step = guard_first_call(
                    exp.train_step, f"first-step(host {ecfg.process_id})",
                    ecfg.step_deadline_s)
                exp.train_step_many = guard_first_call(
                    exp.train_step_many,
                    f"first-step-many(host {ecfg.process_id})",
                    ecfg.step_deadline_s)

            peers = survivors - {ecfg.process_id}

            def on_window(step: int, window_dt: float, window_steps: int) -> None:
                writer.beat(step, step_latency_s=window_dt / max(1, window_steps))
                if not peers:
                    return
                ledger.poll()
                ledger.check_liveness(peers)  # raises HostLost
                for s in ledger.straggler_report(ecfg.straggler_factor,
                                                 ecfg.min_straggler_beats):
                    log(f"elastic host {ecfg.process_id}: {s}")
                    metrics.write("straggler", host=s.process_id,
                                  latency_s=s.latency_s,
                                  fleet_median_s=s.fleet_median_s)

            exp.on_window = on_window
            writer.beat(exp.step)  # registration / resume announcement
            try:
                run_summary = exp.run(remaining)
                path = exp.save()
                summary = {"final_step": exp.step,
                           "final_ewma": run_summary["final_ewma"],
                           "samples_per_sec": run_summary["samples_per_sec"],
                           "checkpoint": path}
                break
            except HostLost as e:
                detected_at = clock()
                # black box first: the ring holds the windows that led up
                # to the loss (spans, heartbeat latencies, loader waits)
                flight_dump("host_lost", host=ecfg.process_id,
                            lost_process_id=e.process_id,
                            silent_for_s=round(e.silent_for_s, 3),
                            step_at_detection=exp.step)
                if len(recoveries) >= ecfg.max_recoveries:
                    log(f"elastic host {ecfg.process_id}: recovery budget "
                        f"({ecfg.max_recoveries}) exhausted; surfacing {e}")
                    raise
                survivors.discard(e.process_id)
                recovering["active"] = True
                recovering["lost"] = e.process_id
                if not survivors:
                    raise  # cannot happen for a live host; defensive
                log(f"elastic host {ecfg.process_id}: {e}; converging on the "
                    f"latest valid checkpoint and re-meshing over "
                    f"{sorted(survivors)}")
                tp_from = exp.config.tensor_parallel
                new_tp = tp_from
                if ecfg.reshard:
                    new_tp = shrink_tp(tp_from, len(survivors),
                                       ecfg.expected_hosts)
                    if new_tp != tp_from:
                        remesh_overrides = {"tensor_parallel": new_tp}
                        log(f"elastic host {ecfg.process_id}: resharding "
                            f"tp {tp_from} -> {new_tp} over the survivors")
                        metrics.write("elastic_remesh", host=ecfg.process_id,
                                      tp_from=tp_from, tp_to=new_tp,
                                      survivors=sorted(survivors))
                mesh = remesh(new_tp, survivors)
                try:
                    # re-derived after EVERY re-mesh: the data width the
                    # global batch must divide over is a property of the
                    # new mesh, not the original launch
                    local_batch = per_host_batch(exp.config.batch_size,
                                                 process_count=len(survivors))
                    log(f"elastic host {ecfg.process_id}: re-mesh "
                        f"{dict(mesh.shape)}; per-host batch -> {local_batch}")
                except ConfigError as ce:
                    # a non-dividing batch over the shrunken fleet is a real
                    # re-balance constraint; surviving with padding is the
                    # loader's problem, not a reason to abandon recovery
                    local_batch = None
                    log(f"elastic host {ecfg.process_id}: {ce}")
                pending_loss = {
                    "event": "host_lost",
                    "process_id": e.process_id,
                    "last_seen": e.last_seen,
                    "silent_for_s": e.silent_for_s,
                    "budget_s": e.budget_s,
                    "last_step": e.last_step,
                    "step_at_detection": exp.step,
                    "detected_at": detected_at,
                    "per_host_batch": local_batch,
                    "tp_from": tp_from,
                    "tp_to": new_tp,
                }
                metrics.write("host_lost", **{k: v for k, v in
                                              pending_loss.items()
                                              if k != "event"})
                continue

        summary.update(
            host=ecfg.process_id,
            survivors=sorted(survivors),
            recoveries=len(recoveries),
            steps_lost_total=sum(r["steps_lost"] for r in recoveries),
            recovery_latency_s=[round(r["recovery_latency_s"], 3)
                                for r in recoveries],
            heartbeats=writer.beats,
        )
        metrics.write("elastic_done", **{k: v for k, v in summary.items()
                                         if k != "checkpoint"})
        print("ELASTIC_DONE " + json.dumps(summary), flush=True)
        return summary
    finally:
        if exporter is not None:
            exporter.close()
        # per-host close-time registry snapshot: the cross-host join in
        # obs/attribution.py keys on these (the shared metrics.jsonl's
        # snapshots interleave between hosts; this stream is ours alone)
        try:
            if not metrics.closed:
                metrics.write("obs_snapshot", host=ecfg.process_id,
                              metrics=get_registry().snapshot()["metrics"])
        except (OSError, ValueError):
            pass
        metrics.close()
