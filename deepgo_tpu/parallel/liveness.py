"""Host liveness for multi-host training: heartbeats, a ledger, stragglers.

At pod scale host failure is the steady state, not the exception (FireCaffe,
arXiv:1511.00175: failure frequency grows linearly with worker count), and a
data-parallel step is only as fast as its slowest participant — a dead or
wedged host silently hangs every survivor inside the gradient all-reduce.
This module gives each host a cheap, externally observable pulse:

  * ``HeartbeatWriter`` — one atomically-written JSON file per host
    (``heartbeat-NNNN.json`` in a directory every host can reach: the
    run directory on a shared filesystem, or a coordinator-mounted path).
    Beats carry the host's training step and its recent per-step latency.
    Writes are best-effort: transient I/O faults are retried, hard ones
    are logged and *absorbed* — the miss budget exists precisely so a few
    lost beats cannot take down a healthy trainer.
  * ``HeartbeatLedger`` — the read side: parses every host's newest beat
    (corrupt files are skipped with a logged reason, exactly like
    ``find_latest_valid`` skips corrupt checkpoints), declares a host
    lost once its silence exceeds ``interval_s * miss_budget``, and
    flags stragglers from rolling per-host step latencies.
  * a typed error family (``HostLost``, ``StragglerDetected``, ...)
    mirroring ``serving/resilience.py``'s vocabulary, so the elastic
    training loop (``parallel/elastic.py``) can route each failure to
    its recovery path instead of pattern-matching strings.

Clocks are injectable everywhere; the tests drive every transition with a
fake clock and never sleep. Heartbeat times are *wall* times (``time.time``)
because they are compared across processes — a monotonic clock has no
cross-host meaning.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from collections import deque

from ..obs import get_registry
from ..utils.atomicio import atomic_write
from ..utils.retry import retry_with_backoff
from ..utils import faults


# ---- typed error family (mirrors serving/resilience.py) ----


class DistributedError(RuntimeError):
    """Base for the multi-host failure vocabulary. Every distributed
    failure the elastic layer can detect or recover from is one of these,
    so callers route on type, never on message text."""


class ConfigError(DistributedError, ValueError):
    """A distributed configuration that cannot work (indivisible batch,
    empty surviving-process set, ...). Typed — never ``assert``, which
    vanishes under ``python -O``."""


class HostLost(DistributedError):
    """A participating host's heartbeat silence exceeded the miss budget.

    Carries everything recovery needs: ``process_id``, ``last_seen`` (wall
    time of the newest beat), ``silent_for_s``, the ``budget_s`` that was
    exceeded, and ``last_step`` (None if the host never beat at all)."""

    def __init__(self, process_id: int, last_seen: float, silent_for_s: float,
                 budget_s: float, last_step: int | None = None):
        self.process_id = process_id
        self.last_seen = last_seen
        self.silent_for_s = silent_for_s
        self.budget_s = budget_s
        self.last_step = last_step
        super().__init__(
            f"host {process_id} lost: silent for {silent_for_s:.2f}s "
            f"(miss budget {budget_s:.2f}s; last step "
            f"{'never beat' if last_step is None else last_step})")


class StragglerDetected(DistributedError):
    """A host's rolling median step latency exceeds ``factor`` x the fleet
    median — alive, but slowing every synchronous step. Advisory by
    default (the elastic loop logs it); policy decides whether to evict."""

    def __init__(self, process_id: int, latency_s: float,
                 fleet_median_s: float, factor: float):
        self.process_id = process_id
        self.latency_s = latency_s
        self.fleet_median_s = fleet_median_s
        self.factor = factor
        super().__init__(
            f"host {process_id} straggling: median step latency "
            f"{latency_s * 1000:.1f}ms vs fleet median "
            f"{fleet_median_s * 1000:.1f}ms (threshold {factor:g}x)")


class CoordinatorUnreachable(DistributedError, ConnectionError):
    """The jax.distributed coordinator could not be reached within the
    retry budget. Subclasses ConnectionError (an OSError) so generic
    transient-I/O retry policies treat it as retryable."""


_HB_RE = re.compile(r"^heartbeat-(\d+)\.json$")


def heartbeat_name(process_id: int) -> str:
    return f"heartbeat-{process_id:04d}.json"


class HeartbeatWriter:
    """One host's pulse: atomically rewrite ``heartbeat-NNNN.json``.

    ``beat()`` is called from the training loop (once per print window —
    windows are the loop's natural cadence and complete in well under a
    miss budget at any sane configuration). The ``heartbeat`` fault site
    fires inside the retried write, so the chaos grammar can exercise both
    the absorbed-transient and the logged-hard-failure paths."""

    def __init__(self, directory: str, process_id: int,
                 clock=time.time, attempts: int = 3):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.process_id = process_id
        self.path = os.path.join(directory, heartbeat_name(process_id))
        self._clock = clock
        self._attempts = attempts
        self.beats = 0      # beats successfully written
        self.misses = 0     # beats absorbed after a hard write failure

    def beat(self, step: int, step_latency_s: float | None = None) -> bool:
        """Write one beat; returns False (and logs) on a hard failure.

        A heartbeat is advisory — a failed write must never kill a healthy
        trainer (the peers' miss budget absorbs it), so hard faults are
        swallowed after the bounded retry, loudly."""
        record = {
            "process_id": self.process_id,
            "beat": self.beats + self.misses,
            "step": int(step),
            "time": self._clock(),
        }
        if step_latency_s is not None:
            record["step_latency_s"] = float(step_latency_s)

        def write() -> None:
            faults.check("heartbeat")
            with atomic_write(self.path, mode="w") as f:
                json.dump(record, f)

        try:
            retry_with_backoff(write, attempts=self._attempts,
                               base_delay=0.01, max_delay=0.1)
        except (OSError, RuntimeError) as e:
            self.misses += 1
            print(f"heartbeat: write for host {self.process_id} failed ({e}); "
                  f"absorbed (miss {self.misses}) — peers' miss budget covers "
                  f"occasional silence", file=sys.stderr, flush=True)
            return False
        self.beats += 1
        return True


class HeartbeatLedger:
    """The read side: who is alive, who is lost, who is straggling.

    ``interval_s * miss_budget`` is the silence budget: a host whose newest
    beat is older than that is declared lost (``check_liveness`` raises a
    typed ``HostLost``). A host that never wrote a beat at all is measured
    against the ledger's first-poll time, so a peer that dies during
    bootstrap is still detected instead of waited on forever.

    Straggler detection folds each beat's ``step_latency_s`` into a rolling
    per-host window (keyed on the beat sequence number, so re-reading the
    same file does not double count) and compares each host's median
    against the median of its peers'."""

    def __init__(self, directory: str, interval_s: float = 1.0,
                 miss_budget: int = 3, clock=time.time,
                 latency_window: int = 32, log=None, registry=None):
        if interval_s <= 0:
            raise ConfigError(f"interval_s must be > 0, got {interval_s}")
        if miss_budget < 1:
            raise ConfigError(f"miss_budget must be >= 1, got {miss_budget}")
        # live straggler surface: each straggler_report() refreshes a
        # per-host gauge (host median / peers' median; 1.0 = fleet-
        # typical), so a slow host shows on /metrics without anyone
        # calling the report — the scrape IS the call
        self._obs_ratio = (registry or get_registry()).gauge(
            "deepgo_straggler_ratio",
            "per-host rolling median step latency over the peers' median "
            "(1.0 = fleet-typical; above the straggler factor = flagged)")
        self.directory = directory
        self.interval_s = interval_s
        self.miss_budget = miss_budget
        self.budget_s = interval_s * miss_budget
        self._clock = clock
        self._t0: float | None = None  # first-poll time: never-seen grace
        self._latencies: dict[int, deque] = {}
        self._last_beat_seq: dict[int, int] = {}
        self._window = latency_window
        if log is None:
            def log(msg):
                print(msg, file=sys.stderr, flush=True)
        self._log = log

    def read(self) -> dict[int, dict]:
        """Newest beat per host. Corrupt or torn files are skipped with a
        logged reason — the writer is atomic, so these only appear when
        storage itself misbehaves, and a garbled beat must read as silence
        (detectable), never as a crash of the *reader*. The first read
        starts the never-seen grace window: any observation of the world
        is the moment silent peers begin accruing silence."""
        if self._t0 is None:
            self._t0 = self._clock()
        try:
            names = os.listdir(self.directory)
        except (FileNotFoundError, NotADirectoryError):
            return {}
        out: dict[int, dict] = {}
        for name in names:
            m = _HB_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path) as f:
                    record = json.load(f)
                pid = int(record["process_id"])
                float(record["time"])
            except (OSError, ValueError, KeyError, TypeError) as e:
                self._log(f"heartbeat ledger: skipping {path}: {e}")
                continue
            out[pid] = record
        return out

    def poll(self) -> dict[int, dict]:
        """read() + fold new beats' latencies into the rolling windows."""
        records = self.read()
        for pid, rec in records.items():
            seq = rec.get("beat")
            if seq is None or seq == self._last_beat_seq.get(pid):
                continue  # already folded (or unversioned beat)
            self._last_beat_seq[pid] = seq
            latency = rec.get("step_latency_s")
            if latency is not None:
                self._latencies.setdefault(
                    pid, deque(maxlen=self._window)).append(float(latency))
        return records

    def check_liveness(self, expected, now: float | None = None) -> None:
        """Raise ``HostLost`` for the longest-silent expected host whose
        silence exceeds the budget; return normally when all are live.
        ``expected`` is an iterable of process ids (exclude yourself)."""
        if self._t0 is None:
            self._t0 = self._clock()
        now = self._clock() if now is None else now
        records = self.read()
        lost: list[HostLost] = []
        for pid in expected:
            rec = records.get(pid)
            last_seen = rec["time"] if rec else self._t0
            silent = now - last_seen
            if silent > self.budget_s:
                lost.append(HostLost(
                    pid, last_seen, silent, self.budget_s,
                    last_step=None if rec is None else rec.get("step")))
        if lost:
            # deterministic: report the longest-silent host first; the
            # elastic loop re-checks after recovery and picks up the rest
            raise max(lost, key=lambda e: e.silent_for_s)

    def straggler_report(self, factor: float = 3.0,
                         min_beats: int = 3) -> list[StragglerDetected]:
        """Hosts whose rolling median step latency exceeds ``factor`` x the
        median of their *peers'* medians (hosts with >= min_beats samples
        only). Excluding the candidate from its own baseline matters: one
        slow host in a small fleet would otherwise drag the fleet median
        toward itself and hide under its own weight — a 2-host fleet could
        never convict either half. Returned, not raised: straggling is
        advisory — policy belongs to the caller."""
        import statistics

        medians = {pid: statistics.median(lat)
                   for pid, lat in self._latencies.items()
                   if len(lat) >= min_beats}
        if len(medians) < 2:
            return []  # a baseline needs at least one peer to compare
        report = []
        for pid, med in sorted(medians.items()):
            peers = statistics.median(
                [m for p, m in medians.items() if p != pid])
            ratio = med / peers if peers > 0 else 0.0
            self._obs_ratio.set(round(ratio, 4), host=str(pid))
            if peers > 0 and med > factor * peers:
                report.append(StragglerDetected(pid, med, peers, factor))
        return report

    def snapshot(self) -> dict:
        """Observability: everything the ledger currently believes."""
        import statistics

        records = self.read()
        now = self._clock()
        return {
            "budget_s": self.budget_s,
            "hosts": {
                pid: {
                    "step": rec.get("step"),
                    "silent_for_s": now - rec["time"],
                    "median_latency_s": (
                        statistics.median(self._latencies[pid])
                        if self._latencies.get(pid) else None),
                }
                for pid, rec in sorted(records.items())
            },
        }
