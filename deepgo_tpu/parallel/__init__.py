"""Device meshes, shardings, distributed helpers, and elastic resilience.

``elastic`` (the recovery orchestrator) is imported lazily by its callers
(cli, tests) rather than re-exported here: it pulls in the experiment
layer, which itself imports this package.
"""

from .mesh import (  # noqa: F401
    data_sharding,
    make_mesh,
    replicated_sharding,
    superbatch_sharding,
)
from .liveness import (  # noqa: F401
    ConfigError,
    CoordinatorUnreachable,
    DistributedError,
    HeartbeatLedger,
    HeartbeatWriter,
    HostLost,
    StragglerDetected,
)
from .deadlines import (  # noqa: F401
    deadline,
    guard_first_call,
    initialize_with_deadline,
)
from .zero import shard_opt_state, sharded_fraction, zero_sharding  # noqa: F401
