"""Device meshes, shardings, and distributed helpers."""

from .mesh import data_sharding, make_mesh, replicated_sharding  # noqa: F401
