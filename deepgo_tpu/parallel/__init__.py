"""Device meshes, shardings, and distributed helpers."""

from .mesh import (  # noqa: F401
    data_sharding,
    make_mesh,
    replicated_sharding,
    superbatch_sharding,
)
from .zero import shard_opt_state, sharded_fraction, zero_sharding  # noqa: F401
