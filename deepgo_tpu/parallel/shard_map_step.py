"""Explicit-collective data-parallel train step via shard_map + psum.

The default DP path (deepgo_tpu.training.steps + NamedSharding) lets XLA's
SPMD partitioner derive the gradient all-reduce. This module is the other
idiomatic formulation — per-device code with an explicit ``lax.psum`` over
the "data" axis — exactly what nn.DataParallelTable's hidden gradient
reduction does in the reference (experiments.lua:155-168), but spelled out.

Both paths are tested to produce identical numerics; the explicit one is
the template to extend when collectives need manual placement (e.g.
gradient compression, async reduction, or DCN-aware reduction orders on
multi-host meshes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..models import policy_cnn
from ..ops import get_expand_fn
from ..training.optimizers import Optimizer
from ..training.steps import nll_from_logits


def make_shard_map_train_step(cfg: policy_cnn.ModelConfig, optimizer: Optimizer,
                              mesh: Mesh, expand_backend: str = "xla"):
    """step(params, opt_state, batch) with hand-written gradient psum.

    params/opt_state replicated; batch sharded on "data". Each device
    computes loss+grads on its local shard, then all-reduces by mean.
    """
    expand_planes = get_expand_fn(expand_backend)
    batch_spec = {
        "packed": P("data"), "player": P("data"), "rank": P("data"),
        "target": P("data"),
    }

    def per_device(params, opt_state, batch):
        planes = expand_planes(
            batch["packed"], batch["player"], batch["rank"],
            dtype=jnp.dtype(cfg.compute_dtype),
        )

        def loss_fn(p):
            logits = policy_cnn.apply(p, planes, cfg)
            return nll_from_logits(logits, batch["target"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # THE data-parallel collective: mean-reduce grads over ICI
        grads = jax.lax.pmean(grads, axis_name="data")
        loss = jax.lax.pmean(loss, axis_name="data")
        params, opt_state = optimizer.update(params, grads, opt_state)
        return params, opt_state, loss

    mapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch):
        return mapped(params, opt_state, batch)

    return step
