"""Explicit-collective data-parallel train step via shard_map + psum.

The default DP path (deepgo_tpu.training.steps + NamedSharding) lets XLA's
SPMD partitioner derive the gradient all-reduce. This module is the other
idiomatic formulation — per-device code with an explicit ``lax.psum`` over
the "data" axis — exactly what nn.DataParallelTable's hidden gradient
reduction does in the reference (experiments.lua:155-168), but spelled out.

Both paths are tested to produce identical numerics; the explicit one is
the template to extend when collectives need manual placement (e.g.
gradient compression, async reduction, or DCN-aware reduction orders on
multi-host meshes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# jax moved shard_map out of experimental around 0.6; support both homes
# and degrade to None (shard_map_available / a loud call-time ImportError)
# rather than killing every importer's collection on older installs
try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover — depends on installed jax
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:
        _shard_map = None

from ..models import policy_cnn
from ..ops import get_expand_fn
from ..training.optimizers import Optimizer
from ..training.steps import nll_from_logits


def shard_map_available() -> bool:
    """Whether the installed jax exposes shard_map at all (tests skip
    instead of erroring at collection when it doesn't)."""
    return _shard_map is not None


def _wrap_shard_map(f, mesh, in_specs, out_specs):
    """Call shard_map across the replication-check keyword rename
    (check_rep in older jax, check_vma in newer)."""
    if _shard_map is None:
        raise ImportError(
            "this jax installation exposes neither jax.shard_map nor "
            "jax.experimental.shard_map")
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def make_shard_map_train_step(cfg: policy_cnn.ModelConfig, optimizer: Optimizer,
                              mesh: Mesh, expand_backend: str = "xla"):
    """step(params, opt_state, batch) with hand-written gradient psum.

    params/opt_state replicated; batch sharded on "data". Each device
    computes loss+grads on its local shard, then all-reduces by mean.
    """
    expand_planes = get_expand_fn(expand_backend)
    batch_spec = {
        "packed": P("data"), "player": P("data"), "rank": P("data"),
        "target": P("data"),
    }

    def per_device(params, opt_state, batch):
        planes = expand_planes(
            batch["packed"], batch["player"], batch["rank"],
            dtype=jnp.dtype(cfg.compute_dtype),
        )

        def loss_fn(p):
            logits = policy_cnn.apply(p, planes, cfg)
            return nll_from_logits(logits, batch["target"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # THE data-parallel collective: mean-reduce grads over ICI
        grads = jax.lax.pmean(grads, axis_name="data")
        loss = jax.lax.pmean(loss, axis_name="data")
        params, opt_state = optimizer.update(params, grads, opt_state)
        return params, opt_state, loss

    mapped = _wrap_shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec),
        out_specs=(P(), P(), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch):
        return mapped(params, opt_state, batch)

    return step
