"""Resharding checkpoint layer: restore training state under a *different*
mesh than the one that saved it (ROADMAP item 3's tp-crossing recovery).

Checkpoints already store fully-gathered host arrays (save_checkpoint
``np.asarray``s every leaf), so a checkpoint is layout-free by
construction; what was missing is the *contract* around putting those
values back under a new dp×tp×ZeRO layout. This module owns that
contract:

  * **manifest** — every checkpoint's meta carries a ``mesh`` block next
    to the v2 CRC/SHA integrity block: the (data, model) grid that wrote
    it, whether ZeRO was on, and the per-leaf partition specs of params
    and optimizer state. ``checkpoint.validate_manifest`` refuses a
    structurally corrupt manifest as a :class:`CheckpointError`, so
    ``find_latest_valid`` skips it like any other corruption.
  * **gather / scatter** — the two halves of a reshard.
    :func:`gather_to_host` materializes device leaves as host arrays
    (the save path and the planned in-process remesh);
    :func:`scatter` places host leaves under the target mesh's composed
    shardings. Both are ``DEEPGO_FAULTS`` sites (``reshard_gather`` /
    ``reshard_scatter``) wrapped in bounded full-jitter retry —
    transient storage/relay hiccups are absorbed, hard faults surface
    typed. The ``reshard_collective`` site covers the cross-host
    convergence barrier (slow@MS emulates a collective timeout; the
    same bounded retry bounds it).
  * **value preservation** — a reshard is bitwise: gather + scatter
    never touch array contents, only placement. What a tp change DOES
    alter is the accumulation order of *subsequent* steps (XLA splits
    the out-channel reduction in the conv backward across "model"), so
    the bit-exact recovery contract is stated against a reference run
    performing the same planned remesh at the same step — the slow
    chaos test in tests/test_reshard.py asserts exactly that, and
    :func:`composed_shardings` is what both sides share.

Placement policy (the composed first-class path): params channel-shard
over "model" when ``tensor_parallel > 1`` (parallel/tensor.py), the
optimizer state additionally ZeRO-1-shards over "data" on its first free
divisible dim (parallel/zero.py, arXiv:2004.13336) — ZeRO placement is
bitwise-neutral, so it is on by default. Every restore re-verifies the
live placement with the sharding-claim checker
(analysis/xlacheck.check_sharding): "resharded" silently meaning
"replicated" is a recorded finding, not a guess.
"""

from __future__ import annotations

import time

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils import faults
from ..utils.retry import retry_with_backoff
from .mesh import replicated_sharding
from .tensor import param_shardings
from .zero import shard_opt_state

MANIFEST_VERSION = 1


def _registry():
    from ..obs import get_registry

    return get_registry()


def composed_shardings(params, mesh: Mesh, *, tensor_parallel: int):
    """The params half of the composed placement: channel-sharded over
    "model" when tensor parallelism is on, replicated otherwise. (The
    optimizer half is derived from the *placed* params via
    ``zero_sharding`` so ZeRO merges "data" in without resharding
    "model" away — see :func:`place_state`.)"""
    if tensor_parallel > 1:
        return param_shardings(params, mesh)
    rep = replicated_sharding(mesh)
    return jax.tree.map(lambda _: rep, params)


def place_state(params, opt_state, mesh: Mesh, *, tensor_parallel: int,
                zero_opt: bool):
    """Place a (params, opt_state) pair under the composed dp×tp×ZeRO
    policy. ``opt_state`` may be None, in which case the caller creates
    it from the placed params (optimizer.init inherits the params
    placement via zeros_like, which is what lets ZeRO compose)."""
    params = jax.device_put(
        params, composed_shardings(params, mesh,
                                   tensor_parallel=tensor_parallel))
    from ..analysis import xlacheck

    if tensor_parallel > 1:
        xlacheck.check_sharding(
            "tensor.params", params,
            composed_shardings(params, mesh, tensor_parallel=tensor_parallel))
    if opt_state is None:
        return params, None
    if zero_opt:
        opt_state = shard_opt_state(opt_state, mesh)
    else:
        opt_state = jax.device_put(opt_state, replicated_sharding(mesh))
    return params, opt_state


def state_shardings(params, opt_state):
    """Read the live placement off a placed state — the sharding pytrees
    a restore scatters into (restored leaves land exactly where freshly
    initialized ones did)."""
    return (jax.tree.map(lambda l: l.sharding, params),
            jax.tree.map(lambda l: l.sharding, opt_state))


def _spec_str(leaf) -> str:
    sh = getattr(leaf, "sharding", None)
    spec = getattr(sh, "spec", None)
    return str(spec) if spec is not None else "host"


def manifest(mesh: Mesh, params, opt_state, *, zero_opt: bool) -> dict:
    """The mesh/sharding manifest a checkpoint's meta carries: which grid
    wrote it and where every leaf lived. Restore does NOT replay these
    specs (the target mesh derives its own); they make the layout change
    auditable (``elastic_remesh`` events name from/to) and structurally
    verifiable (``checkpoint.validate_manifest``)."""
    return {
        "version": MANIFEST_VERSION,
        "data": int(mesh.shape["data"]),
        "model": int(mesh.shape["model"]),
        "devices": int(mesh.shape["data"] * mesh.shape["model"]),
        "zero_opt": bool(zero_opt),
        "params": [_spec_str(l) for l in jax.tree.leaves(params)],
        "opt_state": [_spec_str(l) for l in jax.tree.leaves(opt_state)],
    }


def gather_to_host(tree):
    """Materialize every leaf as a host array — the gather half of a
    reshard. A ``DEEPGO_FAULTS`` site with bounded full-jitter retry:
    transient faults (flaky storage, a relay drop mid-gather) are
    absorbed; hard faults surface typed."""

    def gather():
        faults.check("reshard_gather")
        faults.maybe_slow("reshard_gather")
        # lint: allow[hot-sync] the reshard gather IS the declared materialization point — recovery path, no pipeline to stall
        return jax.tree.map(np.asarray, tree)

    t0 = time.monotonic()
    out = retry_with_backoff(gather, attempts=4, base_delay=0.05,
                             jitter=True)
    _registry().histogram(
        "deepgo_reshard_gather_seconds",
        "host-gather time of one reshard (params + optimizer state)",
    ).observe(time.monotonic() - t0)
    return out


def scatter(tree, shardings):
    """Place host leaves under the target shardings — the re-scatter half
    of a reshard. Same fault-site + bounded full-jitter retry contract
    as the gather; the ``reshard_collective`` barrier site covers the
    cross-host convergence this scatter is part of (a slow@MS spec
    emulates a collective timeout without killing anything)."""

    def place():
        faults.check("reshard_scatter")
        faults.check("reshard_collective")
        faults.maybe_slow("reshard_scatter")
        faults.maybe_slow("reshard_collective")
        return jax.tree.map(
            lambda leaf, s: jax.device_put(leaf, s), tree, shardings)

    t0 = time.monotonic()
    out = retry_with_backoff(place, attempts=4, base_delay=0.05,
                             jitter=True)
    _registry().histogram(
        "deepgo_reshard_scatter_seconds",
        "device re-scatter time of one reshard under the target mesh",
    ).observe(time.monotonic() - t0)
    return out


def restore(params, opt_state, p_shardings, o_shardings) -> tuple:
    """One full reshard: gather host values, re-scatter under the target
    shardings, verify the live placement. Returns ``(params, opt_state,
    findings)`` where ``findings`` are the sharding-claim mismatches
    (empty in parity, or when the checker is off — the elastic recovery
    loop arms it for the duration of every post-loss restore)."""
    from ..analysis import xlacheck

    params = scatter(gather_to_host(params), p_shardings)
    opt_state = scatter(gather_to_host(opt_state), o_shardings)
    findings = list(xlacheck.check_sharding(
        "reshard.params", params, p_shardings))
    findings += xlacheck.check_sharding(
        "reshard.opt_state", opt_state, o_shardings)
    _registry().counter(
        "deepgo_reshard_restores_total",
        "training states re-scattered under a (possibly different) mesh",
    ).inc()
    return params, opt_state, findings
