"""Tensor parallelism: channel-sharded convolution parameters.

The reference has no tensor parallelism (SURVEY.md section 2.3) — this is the
"model axis kept open" design: conv weights shard their output-channel
dimension over the mesh's "model" axis, biases likewise; the final 1-channel
head stays replicated. Under jit, XLA's SPMD partitioner propagates these
parameter shardings through the conv stack and inserts the collectives over
ICI; there are no hand-written all-gathers.

With ("data", "model") = (D, M), each device holds 1/M of every hidden
conv's filters and sees 1/D of the batch.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_shardings(params: dict, mesh: Mesh):
    """A pytree of NamedShardings matching a policy_cnn params pytree."""
    n_layers = len(params["layers"])

    def layer_sharding(i: int, name: str, leaf):
        c_out = leaf.shape[-1]
        if c_out % mesh.shape["model"] != 0:  # e.g. the 1-channel head
            return NamedSharding(mesh, P())
        if name == "w":
            return NamedSharding(mesh, P(None, None, None, "model"))
        return NamedSharding(mesh, P(None, None, "model"))  # (19, 19, C) bias

    return {
        "layers": [
            {name: layer_sharding(i, name, leaf) for name, leaf in layer.items()}
            for i, layer in enumerate(params["layers"])
        ]
    }


def shard_params(params: dict, mesh: Mesh):
    """Place params according to ``param_shardings``.

    With ``DEEPGO_XLACHECK=1`` the placement is verified leaf-by-leaf
    against the declared map (analysis/xlacheck.py): "channel-sharded"
    silently becoming "fully replicated" — the fallback arXiv:2004.13336
    warns about — is a recorded sharding-claim finding, not a guess."""
    shardings = param_shardings(params, mesh)
    placed = jax.device_put(params, shardings)
    from ..analysis import xlacheck

    xlacheck.check_sharding("tensor.params", placed, shardings)
    return placed
