"""Multi-host (multi-process) distributed setup.

The reference is strictly single-process (SURVEY.md section 2.3: no
NCCL/MPI/Gloo anywhere; one AWS instance). This module is the TPU-native
scale-out layer above it: ``jax.distributed`` process bootstrap plus a
hybrid mesh whose "data" axis spans hosts (gradient reduction rides DCN
between hosts, ICI within) while "model" stays inside a host's ICI domain —
the layout the scaling playbook prescribes for data-parallel conv training.

Single-host degenerates cleanly: ``initialize()`` is a no-op and
``hybrid_mesh`` equals ``make_mesh``. Multi-host batches are assembled with
``per_host_batch`` -> ``jax.make_array_from_process_local_data`` so each
host feeds only its own shard (no cross-host host-side traffic).

Resilience (PR 4): the bootstrap carries the ``dist_init`` fault site and
is normally entered through ``deadlines.initialize_with_deadline`` (bounded
full-jitter retry + external watchdog); ``hybrid_mesh`` accepts a
``processes`` filter so the elastic recovery path (``parallel/elastic.py``)
can re-mesh over the surviving process set after a ``HostLost``; and
``per_host_batch`` takes the surviving process count and raises a typed
``ConfigError`` (asserts vanish under ``python -O``) when the global batch
does not divide.
"""

from __future__ import annotations

import jax
import numpy as np

from ..utils import faults
from .liveness import ConfigError
from .mesh import make_mesh


def initialize(coordinator: str | None = None, num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or skip, when single-process) the JAX distributed runtime.

    On Cloud TPU pods the three arguments are auto-detected from the
    metadata server; pass them explicitly elsewhere. The ``dist_init``
    fault site fires first — before the single-process short-circuit — so
    the chaos grammar reaches the bootstrap on any topology; prefer
    ``deadlines.initialize_with_deadline``, which absorbs transient
    faults with bounded full-jitter retry and arms the external watchdog
    against a hanging (rather than failing) dial.
    """
    faults.check("dist_init")
    if num_processes == 1 or (num_processes is None and coordinator is None
                              and jax.process_count() == 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def hybrid_mesh(n_model: int = 1, devices=None, processes=None):
    """("data", "model") mesh over every device of every process, with the
    data axis ordered hosts-major so intra-host neighbors stay on ICI.

    ``processes`` restricts the mesh to a set of process indices — the
    re-mesh entry point after a host loss: surviving hosts rebuild the
    mesh over exactly the surviving process set and training continues on
    the shrunken data axis. ``devices`` overrides device discovery (tests
    exercise multi-host layouts with fake device objects)."""
    devices = list(devices if devices is not None else jax.devices())
    if processes is not None:
        processes = set(processes)
        devices = [d for d in devices if d.process_index in processes]
        if not devices:
            raise ConfigError(
                f"re-mesh over processes {sorted(processes)} matches no "
                f"devices — every surviving host must own at least one")
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    if n_model < 1 or len(devices) % n_model != 0:
        raise ConfigError(
            f"hybrid mesh: {len(devices)} devices do not divide over "
            f"n_model={n_model}")
    n_data = len(devices) // n_model
    return make_mesh(n_data, n_model, devices=devices)


def per_host_batch(global_batch: int, process_count: int | None = None) -> int:
    """How many samples this process should contribute per step.

    ``process_count`` defaults to ``jax.process_count()``; the elastic
    recovery path passes the *surviving* count so the global batch is
    re-balanced over the shrunken fleet after a re-mesh."""
    n = jax.process_count() if process_count is None else process_count
    if n < 1:
        raise ConfigError(f"process_count must be >= 1, got {n}")
    if global_batch % n != 0:
        raise ConfigError(
            f"global batch {global_batch} does not divide over {n} "
            f"processes ({global_batch} % {n} = {global_batch % n}); pick a "
            f"global batch that is a multiple of the process count")
    return global_batch // n


def global_array_from_local(mesh, local_batch: dict) -> dict:
    """Assemble a globally-sharded batch from this host's local samples
    (each process calls this with its own shard). The ``dist_collective``
    fault site fires at this host->global boundary — the first place a
    batch becomes a cross-host object. Assembly time feeds the
    ``collective`` attribution bucket (docs/observability.md)."""
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..obs import get_registry

    faults.check("dist_collective")
    sharding = NamedSharding(mesh, P("data"))
    t0 = time.monotonic()
    out = {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in local_batch.items()
    }
    get_registry().histogram(
        "deepgo_collective_seconds",
        "host-side cross-host array assembly").observe(
            time.monotonic() - t0)
    return out
