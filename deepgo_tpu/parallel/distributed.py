"""Multi-host (multi-process) distributed setup.

The reference is strictly single-process (SURVEY.md section 2.3: no
NCCL/MPI/Gloo anywhere; one AWS instance). This module is the TPU-native
scale-out layer above it: ``jax.distributed`` process bootstrap plus a
hybrid mesh whose "data" axis spans hosts (gradient reduction rides DCN
between hosts, ICI within) while "model" stays inside a host's ICI domain —
the layout the scaling playbook prescribes for data-parallel conv training.

Single-host degenerates cleanly: ``initialize()`` is a no-op and
``hybrid_mesh`` equals ``make_mesh``. Multi-host batches are assembled with
``per_host_batch`` -> ``jax.make_array_from_process_local_data`` so each
host feeds only its own shard (no cross-host host-side traffic).
"""

from __future__ import annotations

import jax
import numpy as np

from .mesh import make_mesh


def initialize(coordinator: str | None = None, num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or skip, when single-process) the JAX distributed runtime.

    On Cloud TPU pods the three arguments are auto-detected from the
    metadata server; pass them explicitly elsewhere.
    """
    if num_processes == 1 or (num_processes is None and coordinator is None
                              and jax.process_count() == 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def hybrid_mesh(n_model: int = 1):
    """("data", "model") mesh over every device of every process, with the
    data axis ordered hosts-major so intra-host neighbors stay on ICI."""
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n_data = len(devices) // n_model
    return make_mesh(n_data, n_model, devices=devices)


def per_host_batch(global_batch: int) -> int:
    """How many samples this process should contribute per step."""
    assert global_batch % jax.process_count() == 0
    return global_batch // jax.process_count()


def global_array_from_local(mesh, local_batch: dict) -> dict:
    """Assemble a globally-sharded batch from this host's local samples
    (each process calls this with its own shard)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("data"))
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in local_batch.items()
    }
