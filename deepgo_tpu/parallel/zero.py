"""Cross-replica weight-update (optimizer-state) sharding — ZeRO stage 1.

Plain data parallelism replicates the optimizer state on every replica
and every replica redundantly applies the identical weight update.
arXiv:2004.13336 ("Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training", one of this project's retrieved technique
papers) shards that state — and the update computation — across the
replicas instead: each replica updates 1/D of the parameters and the
fresh shards are all-gathered. Under GSPMD this needs no hand-written
collectives: placing the optimizer-state leaves with sharded
NamedShardings is the whole program change, and XLA's partitioner turns
the gradient all-reduce + sharded update + replicated-param read into
reduce-scatter + local update + all-gather over ICI.

For this framework's CNN scale the memory win is modest (the flagship's
momentum buffer is ~8 MB), but the capability is what the multi-host
scaffold (parallel/distributed.py) needs at larger scale, and it costs
one placement function. Reference anchor: none (the reference's
DataParallelTable keeps optimizer state on one GPU, experiments.lua:
155-168); this is a beyond-reference axis like tensor parallelism.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _leaf_spec(leaf, n_data: int) -> P:
    """Merge "data" into the leaf's existing spec on the first free,
    divisible dimension; scalars and indivisible shapes keep their
    current placement (correct, just not ZeRO-sharded).

    Preserving the existing spec is what makes this compose with tensor
    parallelism: a TP-sharded momentum buffer (out-channels on "model",
    inherited from the params via zeros_like) gains "data" on another
    dimension instead of losing its "model" placement to a reshard.
    """
    shape = getattr(leaf, "shape", ())
    existing = getattr(leaf, "sharding", None)
    base = (list(existing.spec) if isinstance(existing, NamedSharding)
            else [])
    base += [None] * (len(shape) - len(base))
    if not any(a == "data" or (isinstance(a, tuple) and "data" in a)
               for a in base):
        for axis, size in enumerate(shape):
            if base[axis] is None and size % n_data == 0 and size >= n_data:
                base[axis] = "data"
                break
    return P(*base)


def zero_sharding(opt_state, mesh: Mesh):
    """A pytree of NamedShardings placing optimizer state ZeRO-1 style.

    Each array leaf is split over the mesh's "data" axis along its first
    free divisible dimension (conv momentum on in-channels when
    out-channels carry "model", biases on their channel dim); indivisible
    leaves (the scalar learning rate, odd shapes) keep their existing
    placement. Params themselves stay wherever the caller put them —
    replicated for pure DP, channel-sharded under TP.
    """
    n_data = mesh.shape["data"]
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _leaf_spec(leaf, n_data)), opt_state)


def shard_opt_state(opt_state, mesh: Mesh):
    """device_put the optimizer state under zero_sharding placements.

    Verified against the declared map when ``DEEPGO_XLACHECK=1``
    (analysis/xlacheck.py): a ZeRO leaf that silently fell back to full
    replication is a recorded sharding-claim finding."""
    shardings = zero_sharding(opt_state, mesh)
    placed = jax.tree.map(
        lambda leaf, s: jax.device_put(leaf, s), opt_state, shardings)
    from ..analysis import xlacheck

    xlacheck.check_sharding("zero.opt_state", placed, shardings)
    return placed


def sharded_fraction(opt_state) -> float:
    """Diagnostic: fraction of optimizer-state elements actually sharded
    (i.e. not fully replicated) — lets tests and logs verify the
    placement did something."""
    total = sharded = 0
    for leaf in jax.tree.leaves(opt_state):
        n = int(np.prod(getattr(leaf, "shape", ()) or (1,)))
        total += n
        sh = getattr(leaf, "sharding", None)
        if sh is not None and not sh.is_fully_replicated:
            sharded += n
    return sharded / max(total, 1)
