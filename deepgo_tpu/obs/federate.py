"""Cross-host federation: N telemetry sources, one labeled view.

A fleet is never one process: serving replicas, elastic hosts, and the
loop service each own a registry and (optionally) a time-series store.
This module joins them — live ``/metrics`` endpoints scraped over HTTP,
or run-directory stores read offline — into one keyspace where every
series carries a ``host`` label, so ``cli dash`` and ``cli obs`` can
render the fleet as one system.

Failure discipline: a dead endpoint is *data*, not an exception. One
failed scrape becomes a labeled ``ts_scrape_failed`` event plus a
``deepgo_ts_scrape_failed_total{host}`` increment and an ``ok: false``
row in the collected view; the other hosts' series are unaffected. The
federation layer must keep working while the thing it observes is
half-dead — that is the only time anybody needs it.

The scrape side parses Prometheus text exposition 0.0.4 (what
obs/exporter.py renders — but any conformant exporter works): counters
and gauges pass through, histogram ``_bucket``/``_sum``/``_count``
ladders are re-folded into the same ``:count``/``:sum``/``:p50``/
``:p99`` series keys the local flattener produces, with quantiles
interpolated from the cumulative bucket ladder."""

from __future__ import annotations

import math
import re
import time
import urllib.request

from .registry import MetricsRegistry, get_registry
from .timeseries import (key_matches, load_samples, series_from_samples,
                         series_key)

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (value.replace(r"\"", '"').replace(r"\n", "\n")
            .replace("\\\\", "\\"))


def _quantile_from_buckets(buckets: list[tuple[float, float]],
                           q: float) -> float | None:
    """Interpolated q-quantile from a cumulative (le, count) ladder."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_edge, prev_cum = 0.0, 0.0
    for edge, cum in buckets:
        if cum >= target:
            if math.isinf(edge):
                return prev_edge  # the overflow bucket has no upper edge
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span > 0 else 1.0
            return prev_edge + (edge - prev_edge) * frac
        prev_edge, prev_cum = edge, cum
    return prev_edge


def parse_prometheus(text: str) -> dict[str, float]:
    """Prometheus text -> the flattened ``{series_key: value}`` sample
    format of obs/timeseries.flatten_snapshot. Unparseable lines are
    skipped (a half-written scrape is a degraded sample, not a crash)."""
    plain: dict[tuple[str, str], float] = {}
    hists: dict[tuple[str, str], dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labelstr, raw = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(raw)
        except ValueError:
            continue
        labels = {k: _unescape(v) for k, v in _LABEL_RE.findall(labelstr)}
        if name.endswith("_bucket") and "le" in labels:
            le = labels.pop("le")
            edge = float("inf") if le in ("+Inf", "inf") else float(le)
            key = (name[:-len("_bucket")], _label_string(labels))
            hists.setdefault(key, {"buckets": []})["buckets"].append(
                (edge, value))
        elif name.endswith("_sum"):
            plain[(name, _label_string(labels))] = value
        elif name.endswith("_count"):
            plain[(name, _label_string(labels))] = value
        else:
            plain[(name, _label_string(labels))] = value
    out: dict[str, float] = {}
    for (base, label), h in hists.items():
        buckets = sorted(h["buckets"])
        count = plain.pop((base + "_count", label), None)
        total_sum = plain.pop((base + "_sum", label), None)
        if count is not None:
            out[series_key(base, label, "count")] = count
        if total_sum is not None:
            out[series_key(base, label, "sum")] = total_sum
        for q, field in ((0.50, "p50"), (0.99, "p99")):
            v = _quantile_from_buckets(buckets, q)
            if v is not None:
                out[series_key(base, label, field)] = v
    for (name, label), value in plain.items():
        out[series_key(name, label)] = value
    return out


def _label_string(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def with_labels(values: dict, **extra) -> dict[str, float]:
    """Fold labels (``host=...``, ``replica=...``) into every series key
    — the federation stamp that keeps N sources distinct in one view."""
    from .timeseries import split_key

    out: dict[str, float] = {}
    for key, value in values.items():
        name, labelstr, field = split_key(key)
        labels = dict(kv.split("=", 1)
                      for kv in labelstr.split(",") if "=" in kv)
        labels.update({k: str(v) for k, v in extra.items()})
        out[series_key(name, _label_string(labels), field)] = value
    return out


def scrape_series(url: str, timeout_s: float = 2.0) -> dict[str, float]:
    """One flattened sample from a live exporter. ``url`` may be the
    exporter base or the full ``/metrics`` path."""
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return parse_prometheus(r.read().decode("utf-8", "replace"))


class FederatedView:
    """N named sources -> one host-labeled sample per ``collect()``.

    Sources are scrape endpoints (live replicas/hosts), on-disk stores
    (offline run dirs), or arbitrary getters (tests). A source that
    raises is reported — event + counter + ``ok: false`` — and skipped;
    ``collect`` itself never raises on a source failure."""

    def __init__(self, sink=None, registry: MetricsRegistry | None = None,
                 clock=time.time, timeout_s: float = 2.0):
        self._sources: list[tuple[str, str, object]] = []
        self._sink = sink
        self._clock = clock
        self.timeout_s = timeout_s
        self._obs_failed = (registry or get_registry()).counter(
            "deepgo_ts_scrape_failed_total",
            "federation scrapes that failed, by host label")

    def add_scrape(self, host: str, url: str) -> "FederatedView":
        self._sources.append(
            (host, "scrape",
             lambda url=url: scrape_series(url, self.timeout_s)))
        return self

    def add_store(self, host: str, ts_dir: str) -> "FederatedView":
        """Offline source: the LATEST sample of a run directory's
        time-series store (the store itself keeps the history —
        ``store_series`` reads it per-metric)."""
        self._sources.append(
            (host, "store", lambda d=ts_dir: _latest_store_sample(d)))
        return self

    def add_getter(self, host: str, fn) -> "FederatedView":
        self._sources.append((host, "getter", fn))
        return self

    @property
    def hosts(self) -> list[str]:
        return [h for h, _, _ in self._sources]

    def collect(self) -> dict:
        """One federated sample: ``values`` merges every healthy source
        with ``host=`` folded into each key; ``hosts`` reports per-
        source health including the failure that excused an absence."""
        hosts: dict[str, dict] = {}
        values: dict[str, float] = {}
        for host, kind, fn in self._sources:
            try:
                sample = fn()
            except Exception as e:  # noqa: BLE001 — a dead endpoint is data, not a crash
                self._obs_failed.inc(1, host=host)
                if self._sink is not None:
                    try:
                        self._sink.write("ts_scrape_failed", host=host,
                                         source=kind,
                                         error=repr(e)[:200])
                    except Exception:  # noqa: BLE001 — best-effort event
                        pass
                hosts[host] = {"ok": False, "kind": kind,
                               "error": repr(e)[:200]}
                continue
            hosts[host] = {"ok": True, "kind": kind,
                           "series": len(sample)}
            values.update(with_labels(sample, host=host))
        return {"time": self._clock(), "hosts": hosts, "values": values}


def _latest_store_sample(ts_dir: str) -> dict[str, float]:
    samples = load_samples(ts_dir)
    if not samples:
        raise FileNotFoundError(f"no ts-*.jsonl samples under {ts_dir}")
    return dict(samples[-1].get("values") or {})


def store_series(run_dirs: dict[str, str],
                 metric: str) -> dict[str, list[tuple[float, float]]]:
    """Offline federation of full histories: ``{host: ts_dir}`` ->
    host-labeled (t, value) series for one metric family. Missing or
    empty stores contribute nothing (and never raise) — the offline
    mirror of the dead-endpoint rule."""
    out: dict[str, list[tuple[float, float]]] = {}
    for host, ts_dir in sorted(run_dirs.items()):
        per_key = series_from_samples(load_samples(ts_dir), metric)
        for key, points in per_key.items():
            labeled = next(iter(with_labels({key: 0.0}, host=host)))
            out[labeled] = points
    return out


def federated_series(collected: dict, metric: str) -> dict[str, float]:
    """Filter one ``FederatedView.collect()`` sample down to a metric
    family (host labels preserved) — the dash health-grid helper."""
    return {k: v for k, v in (collected.get("values") or {}).items()
            if key_matches(metric, k)}
