"""Live observability endpoint + the rotating JSONL sink.

Two halves, both intentionally dependency-free (stdlib ``http.server``
and files — nothing to ``pip install`` on the container):

  * ``JsonlSink`` — the one JSONL event stream everything writes through
    (``utils.metrics.MetricsWriter`` is now a thin shim over it, so all
    existing consumers — experiments/plot.py, the supervisor/elastic
    event streams, the tests' ``read_jsonl`` assertions — keep working
    unchanged). Adds what the bare appender lacked: idempotent close,
    context-manager support, thread-safe writes, and size-based rotation
    (``path`` -> ``path.1`` -> ``path.2`` ...) so a chaos soak cannot
    grow one file without bound.
  * ``ObsExporter`` — a daemon-thread HTTP server: ``/metrics`` renders
    the registry in Prometheus text exposition format (scrape it with
    curl or a real Prometheus), ``/healthz`` composes registered health
    callables (SupervisedEngine.health(), HeartbeatLedger liveness, ...)
    into one JSON verdict — HTTP 200 when every component is healthy,
    503 the moment one is not, so a kill injection flips the endpoint
    within the detector's own budget — plus ``/trace`` (the live
    tail-exemplar ring), ``/cost`` (the AOT device cost ledger), and
    ``/series`` (the recent window of the live time-series store,
    obs/timeseries.py — ``?metric=NAME`` for aligned (t, value) points).

Port 0 binds an ephemeral port (tests); ``exporter.port`` reports the
real one. The server thread is a daemon and ``close()`` is idempotent —
an exporter must never be the thing that keeps a dying process alive.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..analysis.lockcheck import make_lock
from .registry import Counter, Gauge, Histogram, MetricsRegistry, get_registry


class JsonlSink:
    """Append-only JSONL event stream with size-based rotation."""

    def __init__(self, path: str, max_bytes: int = 0, max_files: int = 5,
                 buffering: int = 1):
        """``max_bytes=0`` disables rotation (the historical MetricsWriter
        behavior). With rotation on, a write that would push the current
        file past ``max_bytes`` first shifts ``path.N`` -> ``path.N+1``
        (dropping anything past ``max_files``) and renames ``path`` to
        ``path.1`` — newest-first numbering, logrotate-style, so readers
        concatenate ``path.N .. path.1, path`` for the full stream.
        ``buffering`` is the underlying file mode: 1 (default) flushes
        per line — every record durable the instant write() returns;
        high-rate streams (the workload recorder) pass a block size and
        ``flush()`` on idle instead, trading bounded staleness for not
        paying a syscall per record (readers are torn-line-tolerant
        either way)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.max_bytes = max_bytes
        self.max_files = max_files
        self._buffering = buffering
        self._lock = make_lock(f"obs.sink.{os.path.basename(path)}")
        self._f = open(path, "a", buffering=buffering)
        self._size = self._f.tell()

    def write(self, kind: str, **fields) -> None:
        record = {"kind": kind, "time": time.time(), **fields}
        line = json.dumps(record) + "\n"
        with self._lock:
            if self._f.closed:
                raise ValueError(f"JsonlSink({self.path}) is closed")
            if (self.max_bytes > 0 and self._size > 0
                    and self._size + len(line) > self.max_bytes):
                self._rotate()
            self._f.write(line)
            self._size += len(line)

    def _rotate(self) -> None:
        self._f.close()
        for n in range(self.max_files - 1, 0, -1):
            src = f"{self.path}.{n}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{n + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a", buffering=self._buffering)
        self._size = 0

    def flush(self) -> None:
        """Push buffered records to the OS (block-buffered sinks)."""
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self) -> None:
        """Idempotent: the supervisor, the experiment, and an atexit hook
        may all reasonably close the same sink."""
        with self._lock:
            if not self._f.closed:
                self._f.close()

    @property
    def closed(self) -> bool:
        return self._f.closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def sink_files(path: str, max_files: int | None = None) -> list[str]:
    """Every existing file of a (possibly rotated) sink, oldest first —
    what read-side consumers concatenate for the full stream. Rotations
    are discovered on disk (``path.N``), so readers need not know the
    writer's retention setting; ``max_files`` optionally caps how many
    rotations to include (newest-first)."""
    import glob as _glob
    import re as _re

    numbered = []
    pattern = _re.compile(_re.escape(os.path.basename(path)) + r"\.(\d+)$")
    for p in _glob.glob(path + ".*"):
        m = pattern.match(os.path.basename(p))
        if m:
            numbered.append((int(m.group(1)), p))
    numbered.sort()  # .1 is newest; oldest = highest N
    if max_files is not None:
        numbered = numbered[:max_files]
    out = [p for _, p in reversed(numbered)]
    if os.path.exists(path):
        out.append(path)
    return out


# ---- Prometheus text rendering ----


def _escape_label(value: object) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_labels(key: tuple, extra: tuple = ()) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + inner + "}"


def render_prometheus(registry: MetricsRegistry) -> str:
    """The registry in Prometheus text exposition format 0.0.4."""
    lines: list[str] = []
    for m in registry.metrics():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            for key, (counts, total, total_sum) in sorted(
                    m.collect_raw().items()):
                cum = 0
                for edge, c in zip(m.buckets, counts):
                    cum += c
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(key, (('le', f'{edge:g}'),))} {cum}")
                cum += counts[-1]
                lines.append(
                    f"{m.name}_bucket{_fmt_labels(key, (('le', '+Inf'),))} "
                    f"{cum}")
                lines.append(f"{m.name}_sum{_fmt_labels(key)} {total_sum:g}")
                lines.append(f"{m.name}_count{_fmt_labels(key)} {total}")
        elif isinstance(m, (Counter, Gauge)):
            for key, value in sorted(m.collect().items()):
                lines.append(f"{m.name}{_fmt_labels(key)} {value:g}")
    return "\n".join(lines) + "\n"


# ---- health adapters ----


def health_from_engine(engine):
    """Health callable over a SupervisedEngine (or anything exposing
    ``health()`` with a ``state`` field): healthy while serving."""

    def check() -> dict:
        h = engine.health()
        return {"healthy": h.get("state") == "serving", **h}

    return check


def health_from_ledger(ledger, expected):
    """Health callable over a HeartbeatLedger: healthy while no expected
    peer's silence exceeds the miss budget. ``expected`` is a callable
    returning the peer ids to watch (the surviving set shrinks as the
    elastic loop recovers, so it must be read live, not captured)."""

    def check() -> dict:
        from ..parallel.liveness import HostLost

        try:
            ledger.check_liveness(expected())
        except HostLost as e:
            return {"healthy": False, "error": str(e),
                    "lost_process_id": e.process_id,
                    "silent_for_s": round(e.silent_for_s, 3),
                    "budget_s": e.budget_s}
        snap = ledger.snapshot()
        return {"healthy": True, "budget_s": snap["budget_s"],
                "hosts": {str(k): v for k, v in snap["hosts"].items()}}

    return check


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        exporter: ObsExporter = self.server.exporter  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = render_prometheus(exporter.registry).encode()
            self._reply(200, body, "text/plain; version=0.0.4")
        elif path == "/healthz":
            payload, healthy = exporter.check_health()
            body = (json.dumps(payload, default=str) + "\n").encode()
            self._reply(200 if healthy else 503, body, "application/json")
        elif path == "/trace":
            # the live tail-exemplar view: the request-tracing ring +
            # its accounting, while the process serves (obs/tracing.py)
            from .tracing import get_trace_recorder

            rec = get_trace_recorder()
            payload = {"enabled": rec is not None}
            if rec is not None:
                payload["stats"] = rec.stats()
                payload["exemplars"] = rec.exemplars()
            body = (json.dumps(payload, default=str) + "\n").encode()
            self._reply(200, body, "application/json")
        elif path == "/cost":
            # the AOT device cost ledger (obs/costmodel.py): per-
            # entrypoint FLOPs / bytes / HBM + the detected platform
            # peak, as installed by bench / cli cost / the train loop
            from .costmodel import get_cost_ledger

            ledger = get_cost_ledger()
            payload = {"enabled": ledger is not None}
            if ledger is not None:
                payload["ledger"] = ledger.to_dict()
            body = (json.dumps(payload, default=str) + "\n").encode()
            self._reply(200, body, "application/json")
        elif path == "/series":
            # the recent telemetry window from the live time-series
            # store (obs/timeseries.py): ?metric=NAME[&n=POINTS] returns
            # aligned (t, value) points per matching series key; without
            # ?metric=, the known keys. Served from the store's
            # in-memory tail — a scrape never touches the chunk files.
            from urllib.parse import parse_qs

            from .timeseries import get_live_store

            qs = parse_qs(self.path.partition("?")[2])
            store = get_live_store()
            payload = {"enabled": store is not None}
            if store is not None:
                metric = qs.get("metric", [None])[0]
                try:
                    n = max(1, int(qs.get("n", ["240"])[0]))
                except ValueError:
                    n = 240
                if metric:
                    payload["metric"] = metric
                    payload["series"] = store.recent_series(metric, n)
                else:
                    payload["keys"] = sorted(
                        {k for rec in store.recent_window(n)
                         for k in (rec.get("values") or {})})
            body = (json.dumps(payload, default=str) + "\n").encode()
            self._reply(200, body, "application/json")
        else:
            self._reply(404, b"not found\n", "text/plain")

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:
        pass  # scrapes are high-frequency; stderr is for failures


class ObsExporter:
    """Daemon-thread HTTP endpoint over one registry + health callables."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: MetricsRegistry | None = None):
        self.registry = registry or get_registry()
        self._health_fns: dict[str, object] = {}
        self._lock = make_lock("obs.exporter")
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._server.exporter = self  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="obs-exporter",
            daemon=True)
        self._thread.start()
        self._closed = False

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def add_health(self, name: str, fn) -> None:
        """Register one component check: ``fn() -> dict`` with a boolean
        ``healthy`` key (missing reads as healthy — components may report
        pure detail). Re-registering a name replaces the check."""
        with self._lock:
            self._health_fns[name] = fn

    def remove_health(self, name: str) -> None:
        with self._lock:
            self._health_fns.pop(name, None)

    def check_health(self) -> tuple[dict, bool]:
        """(payload, overall) — overall is the AND over components; a
        raising check reads as unhealthy WITH the error in the payload
        (a dying component's exception is the diagnosis, not a scrape
        crash)."""
        with self._lock:
            fns = dict(self._health_fns)
        components = {}
        healthy = True
        degraded = False
        for name, fn in sorted(fns.items()):
            try:
                detail = dict(fn())
            except Exception as e:  # noqa: BLE001 — reported, not raised
                detail = {"healthy": False, "error": repr(e)}
            ok = bool(detail.get("healthy", True))
            detail["healthy"] = ok
            healthy = healthy and ok
            # degraded (an SLO burning, a breaker half-open) is an
            # operator signal, NOT a 503: the endpoint stays 200 so load
            # balancers keep the replica while humans see the warning
            degraded = degraded or bool(detail.get("degraded"))
            components[name] = detail
        return ({"healthy": healthy, "degraded": degraded,
                 "time": time.time(), "components": components}, healthy)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def start_exporter(port: int, host: str = "127.0.0.1",
                   registry: MetricsRegistry | None = None) -> ObsExporter:
    """Convenience used by the CLI/bench ``--obs-port`` paths; prints the
    bound URL once so an operator watching stdout knows where to curl."""
    exporter = ObsExporter(port=port, host=host, registry=registry)
    print(f"obs: serving /metrics and /healthz at {exporter.url}",
          flush=True)
    return exporter
