"""Declarative SLOs with multi-window error-budget burn rates.

PR 5 made the system scrapeable; this module makes it *judgeable*: a set
of declared objectives — serving dispatch latency, training throughput
floor, endpoint availability — evaluated continuously against the live
registry, with the SRE-workbook multi-window burn-rate logic deciding
between "fine", "burning fast" (page-grade: the error budget dies within
hours), and "burning slow" (ticket-grade drift).

Mechanics: each objective reports cumulative (good, total) event counts.
The tracker samples those counts on every ``evaluate()``, keeps a
time-stamped ring of samples, and computes the bad-event fraction over a
short and a long window. The **burn rate** is that fraction divided by
the objective's error budget (1 - target): burn 1.0 spends the budget
exactly at the allowed pace, burn 14.4 exhausts a 30-day budget in 2
days. State machine per objective:

  fast_burn   short-window burn >= fast_burn threshold (default 14.4)
  slow_burn   long-window burn >= slow_burn threshold (default 6.0)
  ok          neither — recovery is automatic once the windows drain

Transitions emit ``slo_burn`` events (to the configured sink, else an
``SLO_BURN`` JSON line on stdout), every evaluation updates the
``deepgo_slo_burn_ratio{slo=...,window=fast|slow}`` gauge, and entering
``fast_burn`` trips the flight recorder — an incident ships with its
black box. ``health()`` plugs into the ObsExporter as a component that
reports **degraded without failing**: a burning SLO is a warning the
operator reads on /healthz, not a reason for the load balancer to pull
the replica (the endpoint stays HTTP 200; docs/observability.md).

Clocks are injectable; tests drive every window transition without
sleeping (the liveness/supervisor discipline).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass

from .registry import Histogram, MetricsRegistry, get_registry
from .sentinel import flight_dump


@dataclass(frozen=True)
class SLOConfig:
    """Window/threshold knobs shared by every objective in a tracker.
    Defaults are the SRE-workbook pairing scaled to this repo's runs:
    5-minute fast window at burn 14.4, 1-hour slow window at burn 6."""

    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn: float = 14.4
    slow_burn: float = 6.0


class Objective:
    """One SLO: a name, a compliance target, and a cumulative event feed.

    Subclasses implement ``sample() -> (good, total)`` as *cumulative*
    counts; the tracker differences consecutive samples, so feeds may be
    registry counters, histogram buckets, or per-tick probes that keep
    their own counters."""

    def __init__(self, name: str, target: float = 0.99):
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"SLO {name!r} target must be in (0, 1), got {target}")
        self.name = name
        self.target = target
        self.budget = max(1.0 - target, 1e-9)

    def sample(self) -> tuple[float, float]:  # pragma: no cover - abstract
        raise NotImplementedError

    def describe(self) -> dict:
        return {"name": self.name, "target": self.target,
                "kind": type(self).__name__}


class HistogramLatencyObjective(Objective):
    """"``target`` of observations complete within ``threshold_s``" over a
    registry histogram (e.g. serving p99 dispatch latency). Good events
    are counted from the cumulative bucket whose upper edge does not
    exceed the threshold — align thresholds to bucket edges
    (registry.DEFAULT_BUCKETS_S) for exact accounting; an off-edge
    threshold rounds down, i.e. judges *stricter*, never laxer."""

    def __init__(self, name: str, metric: str, threshold_s: float,
                 target: float = 0.99,
                 registry: MetricsRegistry | None = None, **labels):
        super().__init__(name, target)
        self.metric = metric
        self.threshold_s = float(threshold_s)
        self._registry = registry or get_registry()
        self._labels = {k: str(v) for k, v in labels.items()}

    def sample(self) -> tuple[float, float]:
        h = self._registry.histogram(self.metric)
        good = total = 0
        if isinstance(h, Histogram):
            for key, (counts, n, _) in h.collect_raw().items():
                labels = dict(key)
                if any(str(labels.get(k)) != v
                       for k, v in self._labels.items()):
                    continue
                total += n
                for edge, c in zip(h.buckets, counts):
                    if edge <= self.threshold_s + 1e-12:
                        good += c
        return float(good), float(total)

    def describe(self) -> dict:
        return {**super().describe(), "metric": self.metric,
                "threshold_s": self.threshold_s}


class GaugeFloorObjective(Objective):
    """"``target`` of evaluation ticks find the gauge at or above
    ``floor``" — the training samples/sec floor. Ticks taken before the
    gauge's first set are skipped (a run that has not produced its first
    window is not in violation of its throughput SLO)."""

    def __init__(self, name: str, metric: str, floor: float,
                 target: float = 0.99,
                 registry: MetricsRegistry | None = None, **labels):
        super().__init__(name, target)
        self.metric = metric
        self.floor = float(floor)
        self._registry = registry or get_registry()
        self._key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        self._good = 0
        self._total = 0

    def sample(self) -> tuple[float, float]:
        g = self._registry.gauge(self.metric)
        series = g.collect()
        if self._key not in series:
            return float(self._good), float(self._total)  # not yet set
        self._total += 1
        if series[self._key] >= self.floor:
            self._good += 1
        return float(self._good), float(self._total)

    def describe(self) -> dict:
        return {**super().describe(), "metric": self.metric,
                "floor": self.floor}


class HealthObjective(Objective):
    """"``target`` of probes find the component healthy" — availability
    over any health callable (an ObsExporter's ``check_health``, an
    engine's ``health()``). Probe exceptions count as bad: an unreachable
    health check *is* unavailability."""

    def __init__(self, name: str, check, target: float = 0.999):
        super().__init__(name, target)
        self._check = check
        self._good = 0
        self._total = 0

    def sample(self) -> tuple[float, float]:
        self._total += 1
        try:
            verdict = self._check()
            if isinstance(verdict, tuple):  # check_health -> (payload, ok)
                ok = bool(verdict[1])
            elif isinstance(verdict, dict):
                ok = bool(verdict.get("healthy", True))
            else:
                ok = bool(verdict)
        except Exception:  # noqa: BLE001 — a dead probe is unavailability
            ok = False
        if ok:
            self._good += 1
        return float(self._good), float(self._total)


class SloTracker:
    """Evaluate a set of objectives against time; emit burns, gauge,
    health. One ``evaluate()`` per tick (the background thread, a window
    hook, or a test's fake clock); all state is per-objective rings of
    (t, good, total) cumulative samples."""

    def __init__(self, objectives: list[Objective],
                 config: SLOConfig = SLOConfig(),
                 registry: MetricsRegistry | None = None,
                 sink=None, clock=time.time):
        self.config = config
        self.objectives = list(objectives)
        self._sink = sink
        self._clock = clock
        reg = registry or get_registry()
        self._gauge = reg.gauge(
            "deepgo_slo_burn_ratio",
            "error-budget burn rate per objective (window=fast|slow); "
            "1.0 spends the budget exactly at the allowed pace")
        self._samples: dict[str, deque] = {
            o.name: deque() for o in self.objectives}
        self.states: dict[str, str] = {o.name: "ok"
                                       for o in self.objectives}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- burn arithmetic ---------------------------------------------------

    def _window_burn(self, samples: deque, now: float, window_s: float,
                     budget: float) -> tuple[float, float]:
        """(burn, bad_fraction) over [now - window_s, now]. The oldest
        in-window sample anchors the delta; fewer than two in-window
        samples (or no events between them) reads as burn 0 — no data is
        not a violation."""
        anchor = None
        for t, good, total in samples:
            if t >= now - window_s:
                anchor = (good, total)
                break
        if anchor is None or not samples:
            return 0.0, 0.0
        g1, t1 = samples[-1][1], samples[-1][2]
        d_total = t1 - anchor[1]
        if d_total <= 0:
            return 0.0, 0.0
        d_bad = max(0.0, d_total - (g1 - anchor[0]))
        bad_frac = d_bad / d_total
        return bad_frac / budget, bad_frac

    def evaluate(self, now: float | None = None) -> dict:
        """One tick: sample every objective, update windows, gauge, and
        state; emit ``slo_burn`` on transitions. Returns the per-objective
        verdict dict (what ``health()`` also reports)."""
        now = self._clock() if now is None else now
        cfg = self.config
        out: dict = {}
        for obj in self.objectives:
            try:
                good, total = obj.sample()
            except Exception as e:  # noqa: BLE001 — a broken feed is a fact
                out[obj.name] = {"state": self.states[obj.name],
                                 "error": repr(e)}
                continue
            ring = self._samples[obj.name]
            ring.append((now, good, total))
            while ring and now - ring[0][0] > cfg.slow_window_s * 1.5:
                ring.popleft()
            fast, fast_bad = self._window_burn(
                ring, now, cfg.fast_window_s, obj.budget)
            slow, slow_bad = self._window_burn(
                ring, now, cfg.slow_window_s, obj.budget)
            self._gauge.set(round(fast, 4), slo=obj.name, window="fast")
            self._gauge.set(round(slow, 4), slo=obj.name, window="slow")
            if fast >= cfg.fast_burn:
                state = "fast_burn"
            elif slow >= cfg.slow_burn:
                state = "slow_burn"
            else:
                state = "ok"
            prev = self.states[obj.name]
            verdict = {
                "state": state,
                "burn_fast": round(fast, 4),
                "burn_slow": round(slow, 4),
                "bad_fraction_fast": round(fast_bad, 6),
                "target": obj.target,
            }
            out[obj.name] = verdict
            if state != prev:
                self.states[obj.name] = state
                self._emit(slo=obj.name, from_state=prev, to_state=state,
                           time=now, **{k: v for k, v in verdict.items()
                                        if k != "state"})
                if state == "fast_burn":
                    # page-grade: ship the black box with the incident
                    flight_dump("slo_fast_burn", slo=obj.name,
                                burn_fast=verdict["burn_fast"],
                                bad_fraction=verdict["bad_fraction_fast"])
        return out

    def _emit(self, **fields) -> None:
        if self._sink is not None:
            try:
                self._sink.write("slo_burn", **fields)
                return
            except (OSError, ValueError):
                pass
        print("SLO_BURN " + json.dumps({"kind": "slo_burn", **fields}),
              flush=True)

    # -- surfaces ----------------------------------------------------------

    def health(self) -> dict:
        """ObsExporter component: degraded-but-healthy while burning.
        ``healthy`` stays True by design — SLO burn is an operator signal
        on /healthz, not a 503 (the breaker/ledger components own hard
        unhealthiness)."""
        burning = {name: state for name, state in self.states.items()
                   if state != "ok"}
        return {
            "healthy": True,
            "degraded": bool(burning),
            "burning": burning,
            "objectives": [o.describe() for o in self.objectives],
        }

    def start(self, interval_s: float = 5.0, sleep=None) -> None:
        """Background evaluator: one evaluate() + flight-recorder tick per
        interval, as a daemon thread (the production wiring for
        ``cli train --slo`` and the serving bench)."""
        if self._thread is not None:
            return
        sleep = sleep or self._stop.wait

        def loop() -> None:
            from .sentinel import get_flight_recorder

            while not self._stop.is_set():
                try:
                    self.evaluate()
                    get_flight_recorder().tick()
                except Exception as e:  # noqa: BLE001 — keep evaluating
                    print(f"slo tracker: evaluate failed: {e}",
                          file=sys.stderr, flush=True)
                sleep(interval_s)

        self._thread = threading.Thread(target=loop, name="slo-tracker",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None


def parse_slo_spec(spec: str, registry: MetricsRegistry | None = None,
                   health_fn=None) -> list[Objective]:
    """The CLI grammar: comma-separated ``name=value[@target]`` pairs.

      dispatch_ms=50         serving dispatch p-latency: 99% of coalesced
                             dispatches within 50 ms
                             (deepgo_serving_dispatch_seconds)
      request_ms=250         end-to-end request latency, same shape
      train_sps=1000         training throughput floor: 99% of ticks find
                             deepgo_train_samples_per_sec >= 1000
      availability=0.999     health-probe availability (requires a health
                             callable — the CLI passes the exporter's)

    ``@target`` overrides the default compliance target:
    ``dispatch_ms=50@0.999``. Unknown names fail loudly — an SLO that is
    silently not tracked is worse than none."""
    objectives: list[Objective] = []
    for raw in (spec or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        name, sep, rest = raw.partition("=")
        if not sep:
            raise ValueError(f"bad SLO spec {raw!r}: expected name=value")
        value, _, target_s = rest.partition("@")
        try:
            value_f = float(value)
            target = float(target_s) if target_s else None
        except ValueError:
            raise ValueError(
                f"bad SLO spec {raw!r}: value/target must be numbers"
            ) from None
        if name == "dispatch_ms":
            objectives.append(HistogramLatencyObjective(
                "serving_dispatch", "deepgo_serving_dispatch_seconds",
                value_f / 1000.0, target=target or 0.99, registry=registry))
        elif name == "request_ms":
            objectives.append(HistogramLatencyObjective(
                "serving_request", "deepgo_serving_request_seconds",
                value_f / 1000.0, target=target or 0.99, registry=registry))
        elif name == "train_sps":
            objectives.append(GaugeFloorObjective(
                "train_throughput", "deepgo_train_samples_per_sec",
                floor=value_f, target=target or 0.99, registry=registry))
        elif name == "availability":
            if health_fn is None:
                raise ValueError(
                    "availability SLO needs a health endpoint — use it "
                    "with --obs-port")
            objectives.append(HealthObjective(
                "availability", health_fn, target=value_f))
        else:
            raise ValueError(
                f"unknown SLO {name!r}; known: dispatch_ms, request_ms, "
                "train_sps, availability")
    return objectives
