"""Streaming anomaly detection over the telemetry sample stream.

The sampler (obs/timeseries.py) turns the registry into a stream of
flattened samples; this module watches a *declared* list of series over
that stream and turns "something changed" into a typed signal the rest
of the stack already knows how to carry: a ``deepgo_anomaly_total``
counter increment, an ``anomaly`` JSONL event, a pinned series window in
the store (so retention never decimates the evidence), and a flight-
recorder dump whose ``series_window`` section carries the surrounding
samples — the postmortem shape PR 6 established for restarts and burns.

Detectors are streaming and robust (no history buffers, no percentile
sorts — O(1) state per series):

  * **step** — robust z-score of the new value against an EWMA mean,
    scaled by an EWMA of absolute deviation (the streaming stand-in for
    MAD; 1.4826 x MAD estimates sigma for a normal). A step change in a
    series that has settled fires immediately; gaussian noise around a
    stable mean stays far under the default z=6 floor.
  * **drift** — divergence between a fast and a slow EWMA, in the same
    robust units, required to persist ``drift_consecutive`` samples: a
    slow degradation the step detector tracks right past. Hysteresis
    re-arms only after the divergence halves.
  * **rate** — mode ``increase``: any positive delta on a failure
    counter (failovers, restarts, poisons, stalls) is anomalous by
    definition — no warmup, so a replica kill is flagged on the very
    next sample. Mode ``drop`` is the gauge mirror, optionally floored
    (``drop_to``): a replica's state gauge falling to 0 (= failed)
    fires, a planned drain to 0.5 does not.
  * mode ``counter_rate`` first differentiates a throughput counter
    into a per-second rate, then runs step+drift over the rate — this
    is how "when did boards/sec start degrading" becomes an event.

False-positive discipline: value detectors arm only after
``min_samples`` ticks (a ramping-up run is not an anomaly), every
detector has hysteresis (one incident = one event, not one per sample),
and flight dumps are additionally budgeted per detector instance.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from collections import deque

from .registry import MetricsRegistry, get_registry
from .sentinel import get_flight_recorder
from .timeseries import TimeSeriesStore, key_matches, split_key


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One typed detection: ``metric`` is the watch family, ``series``
    the exact key that fired, ``kind`` the detector (step|drift|rate)."""

    metric: str
    series: str
    kind: str
    value: float
    baseline: float
    score: float
    t: float

    def to_dict(self) -> dict:
        return {
            "metric": self.metric, "series": self.series,
            "detector": self.kind, "value": round(self.value, 6),
            "baseline": round(self.baseline, 6),
            "score": round(self.score, 3), "t": self.t,
        }


@dataclasses.dataclass(frozen=True)
class WatchSpec:
    """One declared watch: a metric family + how to judge it.

    ``mode``: ``value`` (step+drift over the sampled value),
    ``counter_rate`` (differentiate first), ``increase`` (any positive
    delta fires), ``drop`` (any negative delta fires). ``field`` selects
    a histogram snapshot field (``p99``/``p50``/``count``/``sum``)."""

    metric: str
    mode: str = "value"
    field: str | None = None
    z_threshold: float = 6.0
    drift_threshold: float = 4.0
    drift_consecutive: int = 3
    min_samples: int = 8
    # ``drop`` refinement: fire only when the new value lands at or
    # below this floor. A rolling reload legitimately dips a replica to
    # "draining" (0.5) — only the fall to "failed" (0) is anomalous.
    drop_to: float | None = None

    def matches(self, key: str) -> bool:
        if not key_matches(self.metric, key):
            return False
        _name, _label, field = split_key(key)
        return field == self.field


# the declared watchlist: the operator metrics the ROADMAP arcs steer by
# — serving throughput + tail latency, every fleet/supervisor failure
# counter, loop ingest rate, and the variant-quality gauges. Absent
# series are simply never matched, so one list serves every deployment
# shape (engine, fleet, loop, train).
DEFAULT_WATCHLIST: tuple[WatchSpec, ...] = (
    WatchSpec("deepgo_serving_boards_total", mode="counter_rate"),
    WatchSpec("deepgo_serving_dispatch_seconds", field="p99"),
    WatchSpec("deepgo_serving_restarts_total", mode="increase"),
    WatchSpec("deepgo_serving_poisoned_total", mode="increase"),
    WatchSpec("deepgo_serving_timeouts_total", mode="increase"),
    WatchSpec("deepgo_fleet_failovers_total", mode="increase"),
    WatchSpec("deepgo_fleet_respawns_total", mode="increase"),
    # the gray-failure defenses (serving/fleet.py + deepgo_tpu/chaos):
    # hedges ticking means a tail is being papered over — worth a look;
    # an ejection or canary failure is a replica judged bad while still
    # "healthy"; a breaker-state RISE (0 closed -> 1 half-open -> 2
    # open) is a replica's supervisor cutting traffic
    WatchSpec("deepgo_fleet_hedges_total", mode="increase"),
    WatchSpec("deepgo_fleet_ejections_total", mode="increase"),
    WatchSpec("deepgo_fleet_canary_failures_total", mode="increase"),
    WatchSpec("deepgo_fleet_integrity_failures_total", mode="increase"),
    WatchSpec("deepgo_fleet_breaker_state", mode="increase"),
    # per-replica, not the fleet total: a planned rolling reload dips
    # replicas_serving (drain is not an incident); a replica hitting the
    # FAILED state is one
    WatchSpec("deepgo_fleet_replica_state", mode="drop", drop_to=0.0),
    # per-tier arrival rate (the workload recorder's counter, one series
    # per tier label): the dash sparkline that shows WHO is hammering
    # the fleet, and a collapse in interactive arrivals is an incident
    # even when the fleet itself is healthy
    WatchSpec("deepgo_workload_requests_total", mode="counter_rate"),
    # the position cache (ISSUE 17): hit rate collapsing shows up as the
    # hits counter-rate stepping down while misses step up; ANY stale
    # hit is an incident — the counter is structurally zero (reload
    # bumps the cache generation and old-generation fills are refused),
    # so a single increment means that invariant broke
    WatchSpec("deepgo_cache_hits_total", mode="counter_rate"),
    WatchSpec("deepgo_cache_misses_total", mode="counter_rate"),
    WatchSpec("deepgo_cache_stale_hits_total", mode="increase"),
    WatchSpec("deepgo_loop_games_ingested_total", mode="counter_rate"),
    WatchSpec("deepgo_loop_stalls_total", mode="increase"),
    WatchSpec("deepgo_loop_component_restarts_total", mode="increase"),
    WatchSpec("deepgo_train_samples_per_sec"),
    WatchSpec("deepgo_quant_top1_agreement", mode="drop"),
)

_MAD_SIGMA = 1.4826  # MAD -> sigma for a normal distribution


class _SeriesState:
    """O(1) streaming state for one (spec, series) pair."""

    __slots__ = ("n", "ewma", "slow", "mad", "prev", "prev_t",
                 "drift_run", "step_armed", "drift_armed")

    def __init__(self):
        self.n = 0
        self.ewma = 0.0
        self.slow = 0.0
        self.mad = 0.0
        self.prev: float | None = None
        self.prev_t: float | None = None
        self.drift_run = 0
        self.step_armed = True
        self.drift_armed = True


class AnomalyDetector:
    """Watchlist evaluator; plug ``observe`` into a TelemetrySampler.

    ``sink`` (any ``.write(kind, **fields)`` stream — a JsonlSink or the
    MetricsWriter shim) receives one ``anomaly`` event per detection;
    ``store`` gets its surrounding window pinned and is registered as
    the flight recorder's ``series_window`` section so every dump — this
    detector's own anomaly dumps included — carries the evidence."""

    def __init__(self, watchlist=None, sink=None,
                 registry: MetricsRegistry | None = None,
                 store: TimeSeriesStore | None = None,
                 flight: bool = True, clock=time.time,
                 pin_window: int = 16, max_flight_dumps: int = 8,
                 fast_alpha: float = 0.3, slow_alpha: float = 0.03,
                 scale_alpha: float = 0.05, max_kept: int = 256):
        self.watchlist = tuple(watchlist
                               if watchlist is not None
                               else DEFAULT_WATCHLIST)
        self._sink = sink
        self._store = store
        self._flight = flight
        self._clock = clock
        self._pin_window = pin_window
        self._flight_budget = max_flight_dumps
        self._fast_alpha = fast_alpha
        self._slow_alpha = slow_alpha
        self._scale_alpha = scale_alpha
        self._states: dict[tuple[int, str], _SeriesState] = {}
        # set after the first tick: a labeled failure-counter series
        # often does not EXIST until its first increment, so a series
        # appearing mid-stream baselines at 0 (its implicit prior value)
        # — the first restart is detected, not swallowed as "new
        # series". Series present at the first tick baseline at their
        # observed value: attaching to a running process must not
        # re-announce its history.
        self._primed = False
        self.anomalies: deque = deque(maxlen=max_kept)
        self.count = 0
        self.by_kind: dict[str, int] = {}
        self.first: Anomaly | None = None
        self._obs_anomalies = (registry or get_registry()).counter(
            "deepgo_anomaly_total",
            "streaming-detector anomalies by watch metric and detector "
            "kind (step|drift|rate)")
        if store is not None and flight:
            get_flight_recorder().add_section(
                "series_window", lambda: store.recent_window())

    # -- the listener hook -------------------------------------------------

    def observe(self, t: float, values: dict) -> list[Anomaly]:
        """One sampler tick: run every watch over the sample, emit and
        return any detections. Never raises — the sampler's listener
        contract."""
        found: list[Anomaly] = []
        for idx, spec in enumerate(self.watchlist):
            for key, raw in values.items():
                if not spec.matches(key):
                    continue
                state = self._states.setdefault((idx, key), _SeriesState())
                found.extend(self._judge(spec, key, state, float(raw), t))
        self._primed = True
        for a in found:
            self._emit(a)
        return found

    # -- per-sample judgement ----------------------------------------------

    def _judge(self, spec: WatchSpec, key: str, state: _SeriesState,
               x: float, t: float) -> list[Anomaly]:
        if spec.mode == "increase" or spec.mode == "drop":
            prev, state.prev, state.prev_t = state.prev, x, t
            if prev is None:
                if spec.mode == "increase" and self._primed:
                    prev = 0.0  # a counter series born mid-stream
                else:
                    return []
            delta = x - prev
            if spec.mode == "increase" and delta > 0:
                return [Anomaly(spec.metric, key, "rate", x, prev,
                                delta, t)]
            if spec.mode == "drop" and delta < 0 \
                    and (spec.drop_to is None or x <= spec.drop_to):
                return [Anomaly(spec.metric, key, "step", x, prev,
                                -delta, t)]
            return []
        if spec.mode == "counter_rate":
            prev, prev_t = state.prev, state.prev_t
            state.prev, state.prev_t = x, t
            if prev is None or prev_t is None or t <= prev_t:
                return []
            x = max(0.0, (x - prev) / (t - prev_t))  # the per-second rate
        return self._judge_value(spec, key, state, x, t)

    def _judge_value(self, spec: WatchSpec, key: str, state: _SeriesState,
                     x: float, t: float) -> list[Anomaly]:
        out: list[Anomaly] = []
        state.n += 1
        if state.n == 1:
            state.ewma = state.slow = x
            return out
        dev = abs(x - state.ewma)
        sigma = _MAD_SIGMA * state.mad + 1e-12 + 1e-6 * abs(state.ewma)
        warm = state.n > spec.min_samples
        if warm:
            score = dev / sigma
            if score >= spec.z_threshold and state.step_armed:
                state.step_armed = False
                out.append(Anomaly(spec.metric, key, "step", x,
                                   state.ewma, score, t))
                # a confirmed step RE-BASELINES the series: the level
                # moved, so both means jump to it (one incident = one
                # event — the drift detector must not re-announce the
                # same move while the slow mean catches up) and the
                # scale estimate is left alone (the firing deviation is
                # not noise to absorb)
                state.ewma = state.slow = x
                state.drift_run = 0
                return out
            elif score < spec.z_threshold / 2.0:
                state.step_armed = True
        # update AFTER scoring: the new value must not defend itself.
        # The scale estimate warms in fast (a near-zero MAD inflates
        # every early score) then adapts SLOWLY: a noise-speed scale
        # tracker makes robust-z heavy-tailed and fires on healthy jitter
        state.ewma += self._fast_alpha * (x - state.ewma)
        state.slow += self._slow_alpha * (x - state.slow)
        scale_alpha = (self._fast_alpha if state.n <= spec.min_samples
                       else self._scale_alpha)
        state.mad += scale_alpha * (dev - state.mad)
        if warm:
            drift_score = abs(state.ewma - state.slow) / sigma
            if drift_score >= spec.drift_threshold:
                state.drift_run += 1
                if state.drift_run >= spec.drift_consecutive \
                        and state.drift_armed:
                    state.drift_armed = False
                    out.append(Anomaly(spec.metric, key, "drift", x,
                                       state.slow, drift_score, t))
            else:
                state.drift_run = 0
                if drift_score < spec.drift_threshold / 2.0:
                    state.drift_armed = True
        return out

    # -- emission ----------------------------------------------------------

    def _emit(self, a: Anomaly) -> None:
        self.count += 1
        self.by_kind[a.kind] = self.by_kind.get(a.kind, 0) + 1
        if self.first is None:
            self.first = a
        self.anomalies.append(a)
        self._obs_anomalies.inc(1, metric=a.metric, kind=a.kind)
        if self._sink is not None:
            try:
                self._sink.write("anomaly", **a.to_dict())
            except Exception as e:  # noqa: BLE001 — a closed sink must not mask the detection
                print(f"anomaly detector: sink write failed: {e!r}",
                      file=sys.stderr, flush=True)
        if self._store is not None:
            self._store.pin_recent(self._pin_window)
        if self._flight and self._flight_budget > 0:
            self._flight_budget -= 1
            get_flight_recorder().dump("anomaly", **a.to_dict())

    # -- accounting --------------------------------------------------------

    def summary(self, t0: float | None = None) -> dict:
        """The bench/loop JSON block: counts, kinds, and how fast the
        first detection landed relative to ``t0``."""
        out: dict = {"count": self.count, "by_kind": dict(self.by_kind)}
        if self.first is not None:
            out["first"] = self.first.to_dict()
            if t0 is not None:
                out["first_detect_s"] = round(self.first.t - t0, 3)
        return out
