"""Offline run summarizer: one per-stage table out of a run's JSONL streams.

A finished (or killed) run directory holds up to three event streams —
``metrics.jsonl`` (train/validation/serving/obs events, possibly rotated),
``trace.jsonl`` (obs_span records), and ``elastic-NNNN.jsonl`` (per-host
recovery events) — that describe the same timeline from different angles.
This module joins them into the table the next perf PR argues from:
loader wait, dispatch latency, step time, span durations, recovery
counts, side by side with p50/p99 where a distribution exists.

Distributions come from two places and the report prefers the richer one:
the final ``obs_snapshot`` event (the registry's full histogram state at
close — exact counts, interpolated percentiles) and, for spans, the raw
per-occurrence records in ``trace.jsonl`` (exact percentiles, since every
occurrence is on disk).

CLI: ``python -m deepgo_tpu.cli obs RUN_DIR [--json]``.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from .attribution import attribute_run, format_attribution
from .exporter import sink_files


def read_events(path: str) -> list[dict]:
    """Every record of a (possibly rotated) JSONL stream, oldest first.
    Corrupt lines are skipped — a report over a killed run must work on
    a stream whose final line was torn mid-write."""
    out: list[dict] = []
    for p in sink_files(path):
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return out


def _pct(values: list[float], q: float) -> float | None:
    return float(np.percentile(values, q)) if values else None


def _hist_row(name: str, snap: dict, unit_scale: float = 1000.0) -> dict:
    """One table row from a registry histogram snapshot (seconds -> ms)."""
    return {
        "count": snap["count"],
        "p50_ms": round(snap["p50"] * unit_scale, 3),
        "p95_ms": round(snap["p95"] * unit_scale, 3),
        "p99_ms": round(snap["p99"] * unit_scale, 3),
        "mean_ms": round(snap["mean"] * unit_scale, 3),
    }


def summarize_run(run_dir: str) -> dict:
    """The joined per-stage summary for one run directory."""
    metrics = read_events(os.path.join(run_dir, "metrics.jsonl"))
    # loop runs stream their events (loop_* / fleet_* / lineage_*) to
    # loop.jsonl — same grammar, different file; fold them in so the
    # loop and fleet sections below see both deployment shapes
    metrics.extend(read_events(os.path.join(run_dir, "loop.jsonl")))
    trace_stream = read_events(os.path.join(run_dir, "trace.jsonl"))
    spans = [r for r in trace_stream if r.get("kind") == "obs_span"]
    requests = [r for r in trace_stream
                if r.get("kind") == "trace_request"]
    elastic: list[dict] = []
    for p in sorted(glob.glob(os.path.join(run_dir, "elastic-*.jsonl"))):
        elastic.extend(read_events(p))

    summary: dict = {"run_dir": run_dir, "stages": {}, "events": {}}

    # ---- training cadence (the train/validation/summary event grammar)
    train = [r for r in metrics if r.get("kind") == "train"]
    if train:
        sps = [r["samples_per_sec"] for r in train
               if r.get("samples_per_sec")]
        summary["stages"]["train"] = {
            "windows": len(train),
            "last_step": train[-1].get("step"),
            "last_ewma": train[-1].get("ewma"),
            "samples_per_sec_p50": round(_pct(sps, 50) or 0.0, 1),
            "samples_per_sec_min": round(min(sps), 1) if sps else None,
        }
    vals = [r for r in metrics if r.get("kind") == "validation"]
    if vals:
        summary["stages"]["validation"] = {
            "count": len(vals),
            "best_cost": round(min(r["cost"] for r in vals), 4),
            "last_accuracy": round(vals[-1]["accuracy"], 4),
        }

    # ---- registry snapshot (the hot-path histograms: loader wait,
    # dispatch latency, step windows) — the last one wins: it is the
    # close-time state and subsumes the others
    snaps = [r for r in metrics if r.get("kind") == "obs_snapshot"]
    hists: dict = {}
    if snaps:
        hists = snaps[-1].get("metrics", {})
        stage_of = {
            "deepgo_loader_wait_seconds": "loader_wait",
            "deepgo_train_window_seconds": "train_window",
            "deepgo_serving_dispatch_seconds": "serving_dispatch",
            "deepgo_serving_request_seconds": "serving_request",
            "deepgo_fleet_failover_seconds": "fleet_failover",
        }
        for metric_name, stage in stage_of.items():
            m = hists.get(metric_name)
            if not m or m.get("kind") != "histogram":
                continue
            for label, snap in m["series"].items():
                if not snap:
                    continue
                key = stage if not label else f"{stage}[{label}]"
                summary["stages"][key] = _hist_row(metric_name, snap)
        counters = {}
        for metric_name, m in hists.items():
            if m.get("kind") == "counter":
                for label, v in m["series"].items():
                    key = metric_name if not label \
                        else f"{metric_name}[{label}]"
                    counters[key] = v
        if counters:
            summary["events"]["counters"] = counters
        # the serving supervisor's resilience counters, surfaced as their
        # own section (restarts / shed / poisoned / replayed): the fleet
        # health row an operator reads first, not buried in the generic
        # counter dump
        sup = {}
        for short, metric_name in (("restarts",
                                    "deepgo_serving_restarts_total"),
                                   ("shed", "deepgo_serving_shed_total"),
                                   ("poisoned",
                                    "deepgo_serving_poisoned_total"),
                                   ("replayed",
                                    "deepgo_serving_replayed_total")):
            m = hists.get(metric_name)
            if m and m.get("kind") == "counter":
                sup[short] = sum(m["series"].values())
        if sup:
            summary["events"].setdefault("serving", {}).update(
                supervisor=sup)

    # ---- spans (exact per-occurrence durations from the trace stream)
    by_name: dict[str, list[float]] = {}
    errors: dict[str, int] = {}
    for r in spans:
        by_name.setdefault(r["name"], []).append(float(r["duration_s"]))
        if r.get("status") == "error":
            errors[r["name"]] = errors.get(r["name"], 0) + 1
    for name, durs in sorted(by_name.items()):
        row = {
            "count": len(durs),
            "p50_ms": round(_pct(durs, 50) * 1000, 3),
            "p95_ms": round(_pct(durs, 95) * 1000, 3),
            "p99_ms": round(_pct(durs, 99) * 1000, 3),
            "mean_ms": round(float(np.mean(durs)) * 1000, 3),
        }
        if errors.get(name):
            row["errors"] = errors[name]
        summary["stages"][f"span:{name}"] = row

    # ---- serving events (engine/supervisor JSONL grammar)
    restarts = [r for r in metrics if r.get("kind") == "serving_restart"]
    poisons = [r for r in metrics if r.get("kind") == "serving_poison"]
    if restarts or poisons:
        # merge, don't assign: the snapshot section above may already
        # have parked the supervisor counter block under this key
        summary["events"].setdefault("serving", {}).update(
            restarts=len(restarts), poisoned=len(poisons))

    def _counter_series(metric_name: str) -> dict:
        m = hists.get(metric_name)
        if m and m.get("kind") == "counter":
            return {lbl: v for lbl, v in m["series"].items()}
        return {}

    def _gauge_value(metric_name: str):
        m = hists.get(metric_name)
        if m and m.get("kind") == "gauge" and m["series"]:
            return list(m["series"].values())[-1]
        return None

    # ---- fleet (router counters, per-replica restart/failover/respawn
    # attribution — the deepgo_fleet_* / fleet_* grammar, previously
    # invisible in this report)
    fleet_sec: dict = {}
    for short, metric_name in (("failovers", "deepgo_fleet_failovers_total"),
                               ("respawns", "deepgo_fleet_respawns_total"),
                               ("reloads", "deepgo_fleet_reloads_total")):
        series = _counter_series(metric_name)
        if series:
            fleet_sec[short] = int(sum(series.values()))
    shed = _counter_series("deepgo_fleet_shed_total")
    if shed:
        fleet_sec["shed"] = {lbl: int(v) for lbl, v in sorted(shed.items())}
    if fleet_sec:
        # per-replica restarts: the supervisor counter's engine label IS
        # the replica name when the engines sit behind a fleet router
        per_engine = _counter_series("deepgo_serving_restarts_total")
        if per_engine:
            fleet_sec["replica_restarts"] = {
                lbl or "(unlabeled)": int(v)
                for lbl, v in sorted(per_engine.items())}
    fleet_events = [r for r in metrics
                    if str(r.get("kind", "")).startswith("fleet_")]
    if fleet_events:
        by_replica: dict = {}
        for r in fleet_events:
            if r["kind"] == "fleet_respawn" and "replica" in r:
                key = str(r["replica"])
                by_replica[key] = by_replica.get(key, 0) + 1
        fleet_sec.setdefault(
            "respawns", sum(1 for r in fleet_events
                            if r["kind"] == "fleet_respawn"))
        if by_replica:
            fleet_sec["respawns_by_replica"] = by_replica
        failed = [r for r in fleet_events
                  if r["kind"] == "fleet_replica_failed"]
        if failed:
            fleet_sec["replicas_failed"] = [r.get("replica") for r in failed]
        reload_events = [r for r in fleet_events
                         if r["kind"] == "fleet_reload"]
        if reload_events:
            fleet_sec.setdefault("reloads", len(reload_events))
    if fleet_sec:
        summary["events"]["fleet"] = fleet_sec

    # ---- loop (the deepgo_loop_* / loop_* expert-iteration grammar)
    loop_sec: dict = {}
    for short, metric_name in (
            ("games_ingested", "deepgo_loop_games_ingested_total"),
            ("positions_ingested", "deepgo_loop_positions_ingested_total"),
            ("windows_trained", "deepgo_loop_windows_trained_total"),
            ("gates_passed", "deepgo_loop_gates_passed_total"),
            ("gates_rejected", "deepgo_loop_gates_rejected_total"),
            ("stalls", "deepgo_loop_stalls_total")):
        series = _counter_series(metric_name)
        if series:
            loop_sec[short] = int(sum(series.values()))
    comp_restarts = _counter_series("deepgo_loop_component_restarts_total")
    if comp_restarts:
        loop_sec["component_restarts"] = {
            lbl or "(unlabeled)": int(v)
            for lbl, v in sorted(comp_restarts.items())}
    step = _gauge_value("deepgo_loop_learner_step")
    if step is not None:
        loop_sec["learner_step"] = int(step)
    loop_events = [r for r in metrics
                   if str(r.get("kind", "")).startswith("loop_")]
    if loop_events:
        loop_sec.setdefault(
            "windows_trained",
            sum(1 for r in loop_events if r["kind"] == "loop_window"))
        loop_sec.setdefault(
            "games_ingested",
            sum(1 for r in loop_events if r["kind"] == "loop_ingest"))
        gates = [r for r in loop_events if r["kind"] == "loop_gate"]
        if gates:
            loop_sec.setdefault(
                "gates_passed",
                sum(1 for r in gates if r.get("outcome") == "passed"))
            loop_sec.setdefault(
                "gates_rejected",
                sum(1 for r in gates if r.get("outcome") == "rejected")
                + sum(1 for r in loop_events
                      if r["kind"] == "loop_gate_rejected"))
        crashes: dict = {}
        for r in loop_events:
            if r["kind"] == "loop_restart":
                key = str(r.get("component", "?"))
                crashes[key] = crashes.get(key, 0) + 1
        if crashes:
            loop_sec.setdefault("component_restarts", crashes)
        closes = [r for r in loop_events if r["kind"] == "loop_close"]
        if closes:
            last = closes[-1]
            for k in ("games_acked", "games_durable", "champion_step"):
                if last.get(k) is not None:
                    loop_sec[k] = last[k]
    if loop_sec:
        summary["events"]["loop"] = loop_sec

    # ---- slowest-request exemplars (trace_request records sampled by
    # obs/tracing.py: the tail anatomy next to the aggregate table)
    if requests:
        top = sorted(requests,
                     key=lambda r: -float(r.get("duration_s", 0.0)))[:10]
        summary["exemplars"] = [{
            "trace_id": r.get("trace_id"),
            "duration_ms": round(float(r.get("duration_s", 0.0)) * 1000, 3),
            "status": r.get("status"),
            "tier": r.get("tier"),
            "replica": r.get("replica"),
            "bucket": r.get("bucket"),
            "hops": len(r.get("hops") or []),
            "events": len(r.get("events") or []),
        } for r in top]

    # ---- elastic recovery (per-host streams)
    recoveries = [r for r in elastic if r.get("kind") == "recovery"]
    losses = [r for r in elastic if r.get("kind") == "host_lost"]
    stragglers = [r for r in elastic if r.get("kind") == "straggler"]
    if elastic:
        row: dict = {
            "hosts_seen": len({r.get("host") for r in elastic
                               if "host" in r}),
            "host_losses": len(losses),
            "recoveries": len(recoveries),
            "stragglers_flagged": len(stragglers),
        }
        if recoveries:
            lat = [r["recovery_latency_s"] for r in recoveries]
            row.update(
                steps_lost_total=sum(r.get("steps_lost", 0)
                                     for r in recoveries),
                recovery_latency_s_p50=round(_pct(lat, 50), 3),
                recovery_latency_s_max=round(max(lat), 3),
            )
        summary["events"]["elastic"] = row

    # ---- profiler trace discoverability (utils.profiling.trace logs it)
    traces = [r for r in metrics if r.get("kind") == "profile_trace"]
    if traces:
        summary["events"]["profiler_traces"] = [
            r.get("out_dir") for r in traces]

    # ---- SLO burns (the tracker's transition events, when streamed)
    burns = [r for r in metrics if r.get("kind") == "slo_burn"]
    if burns:
        summary["events"]["slo_burns"] = [
            {k: r.get(k) for k in ("slo", "from_state", "to_state",
                                   "burn_fast", "burn_slow")}
            for r in burns]

    # ---- telemetry time-series (the ts-NNNN.jsonl chunk store written
    # by obs/timeseries.py): the historical view next to the point-in-
    # time snapshot — per-watchlist series last/min/max over the whole
    # retained window, so "when did it degrade" is answerable offline
    from .dash import find_store_dir
    from .timeseries import (key_field, list_keys, load_samples,
                             series_from_samples)

    ts_samples = load_samples(find_store_dir(run_dir))
    if ts_samples:
        from .anomaly import DEFAULT_WATCHLIST

        watch: dict = {}
        for spec in DEFAULT_WATCHLIST:
            per_key = series_from_samples(ts_samples, spec.metric)
            for key, points in sorted(per_key.items()):
                if key_field(key) != spec.field:
                    continue
                values = [v for _, v in points]
                watch[key] = {
                    "points": len(points),
                    "last": round(values[-1], 6),
                    "min": round(min(values), 6),
                    "max": round(max(values), 6),
                }
        summary["timeseries"] = {
            "samples": len(ts_samples),
            "series": len(list_keys(ts_samples)),
            "span_s": round(ts_samples[-1]["t"] - ts_samples[0]["t"], 3),
            "pinned": sum(1 for r in ts_samples if r.get("pin")),
            "watch": watch,
        }

    # ---- anomalies (the streaming detector's typed events,
    # obs/anomaly.py) — what fired, when, and how hard, plus the scrape
    # failures the federation layer absorbed
    anomalies = [r for r in metrics if r.get("kind") == "anomaly"]
    anomalies.extend(r for r in trace_stream
                     if r.get("kind") == "anomaly")
    if anomalies:
        by_kind: dict = {}
        for r in anomalies:
            k = str(r.get("detector", "?"))
            by_kind[k] = by_kind.get(k, 0) + 1
        summary["anomalies"] = {
            "count": len(anomalies),
            "by_kind": by_kind,
            "events": [{k: r.get(k) for k in ("metric", "series",
                                              "detector", "value",
                                              "baseline", "score", "t")}
                       for r in anomalies[-20:]],
        }
    scrape_failures = [r for r in metrics
                       if r.get("kind") == "ts_scrape_failed"]
    if scrape_failures:
        summary["events"]["scrape_failures"] = {
            str(r.get("host", "?")): sum(
                1 for s in scrape_failures if s.get("host") == r.get("host"))
            for r in scrape_failures}

    # ---- workload observatory (the obs/workload.py capture streams —
    # a bench run's <flight-dir>/workload/ or a capture dir itself):
    # what the run was ASKED to serve, characterized — the projected
    # cache hit rate next to the dispatch latencies it would remove
    wl_dir = None
    for cand in (os.path.join(run_dir, "workload"), run_dir):
        if os.path.exists(os.path.join(cand, "workload.jsonl")):
            wl_dir = cand
            break
    if wl_dir is not None:
        from .workload import analyze_capture

        summary["workload"] = analyze_capture(wl_dir)

    # ---- the AOT device cost ledger (cost_ledger events streamed by
    # obs/costmodel.py at train start / bench warmup): the per-entrypoint
    # FLOPs / bytes / HBM bill the attribution roofline divides by
    cost_events = [r for r in metrics if r.get("kind") == "cost_ledger"]
    if cost_events:
        summary["cost_ledger"] = {
            "version": cost_events[-1].get("version"),
            "platform": cost_events[-1].get("platform"),
            "device_kind": cost_events[-1].get("device_kind"),
            "entries": [{k: v for k, v in r.items()
                         if k not in ("kind", "time", "version",
                                      "platform", "device_kind")}
                        for r in cost_events],
        }

    # ---- step-time attribution (obs/attribution.py): the per-host
    # wall-clock decomposition, joined across elastic hosts when present
    att = attribute_run(run_dir)
    if att is not None:
        summary["attribution"] = att

    return summary


def format_report(summary: dict) -> str:
    """The human rendering: one fixed-width per-stage table plus an
    events block — terminal-greppable, no dependencies."""
    lines = [f"run: {summary['run_dir']}"]
    stages = summary.get("stages", {})
    if stages:
        cols = ["stage", "count", "p50_ms", "p95_ms", "p99_ms", "notes"]
        rows = []
        for name, row in stages.items():
            notes = ", ".join(
                f"{k}={v}" for k, v in row.items()
                if k not in ("count", "p50_ms", "p95_ms", "p99_ms",
                             "mean_ms") and v is not None)
            rows.append([
                name,
                str(row.get("count", row.get("windows", ""))),
                str(row.get("p50_ms", "")),
                str(row.get("p95_ms", "")),
                str(row.get("p99_ms", "")),
                notes,
            ])
        widths = [max(len(c), *(len(r[i]) for r in rows))
                  for i, c in enumerate(cols)]
        lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
        lines.append("  ".join("-" * w for w in widths))
        for r in rows:
            lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    else:
        lines.append("(no stage data: no metrics.jsonl / trace.jsonl "
                     "events found)")
    events = summary.get("events", {})
    for section, payload in events.items():
        lines.append("")
        lines.append(f"{section}:")
        if isinstance(payload, dict):
            for k, v in payload.items():
                lines.append(f"  {k}: {v}")
        else:
            for item in payload:
                lines.append(f"  {item}")
    exemplars = summary.get("exemplars")
    if exemplars:
        lines.append("")
        lines.append("slowest requests (sampled exemplars — "
                     "`cli trace RUN_DIR <id>` for the waterfall):")
        cols = ["trace_id", "ms", "status", "tier", "replica", "bucket",
                "hops"]
        rows = [[str(e.get("trace_id", "")),
                 str(e.get("duration_ms", "")),
                 str(e.get("status", "")),
                 str(e.get("tier") or ""),
                 str(e.get("replica") if e.get("replica") is not None
                     else ""),
                 str(e.get("bucket") if e.get("bucket") is not None
                     else ""),
                 str(e.get("hops", 0))] for e in exemplars]
        widths = [max(len(c), *(len(r[i]) for r in rows))
                  for i, c in enumerate(cols)]
        lines.append("  " + "  ".join(c.ljust(w)
                                      for c, w in zip(cols, widths)))
        for r in rows:
            lines.append("  " + "  ".join(v.ljust(w)
                                          for v, w in zip(r, widths)))
    ts = summary.get("timeseries")
    if ts:
        lines.append("")
        lines.append(f"telemetry time-series ({ts['samples']} samples, "
                     f"{ts['series']} series over {ts['span_s']}s, "
                     f"{ts['pinned']} pinned — `cli dash` for sparklines):")
        watch = ts.get("watch", {})
        if watch:
            cols = ["series", "points", "last", "min", "max"]
            rows = [[key[:72], str(row["points"]), f"{row['last']:g}",
                     f"{row['min']:g}", f"{row['max']:g}"]
                    for key, row in watch.items()]
            widths = [max(len(c), *(len(r[i]) for r in rows))
                      for i, c in enumerate(cols)]
            lines.append("  " + "  ".join(c.ljust(w)
                                          for c, w in zip(cols, widths)))
            for r in rows:
                lines.append("  " + "  ".join(v.ljust(w)
                                              for v, w in zip(r, widths)))
    anom = summary.get("anomalies")
    if anom:
        lines.append("")
        lines.append(f"anomalies ({anom['count']} total, "
                     + ", ".join(f"{k}={v}"
                                 for k, v in sorted(anom["by_kind"]
                                                    .items()))
                     + "):")
        for e in anom["events"]:
            lines.append(
                f"  {e.get('detector', '?'):5s}  "
                f"{e.get('series') or e.get('metric')}  "
                f"value {e.get('value')} vs baseline {e.get('baseline')} "
                f"(score {e.get('score')})")
    wl = summary.get("workload")
    if wl:
        from .workload import format_workload

        lines.append("")
        lines.append("workload (obs/workload.py capture — "
                     "`cli workload analyze` for the full report):")
        for row in format_workload(wl).splitlines():
            lines.append(f"  {row}")
    cost = summary.get("cost_ledger")
    if cost:
        lines.append("")
        lines.append(f"device cost ledger (v{cost.get('version')}, "
                     f"{cost.get('platform')}):")
        cols = ["entrypoint", "GFLOPs", "MB moved", "AI", "HBM MB", "src"]
        rows = []
        for e in cost["entries"]:
            fn, bucket = e.get("fn", "?"), e.get("bucket")
            ai = e.get("arithmetic_intensity")
            rows.append([
                fn if bucket is None else f"{fn}/b{bucket}",
                f"{(e.get('flops') or 0) / 1e9:,.1f}",
                f"{(e.get('bytes_accessed') or 0) / 2**20:,.1f}"
                if e.get("bytes_accessed") else "-",
                f"{ai:.1f}" if ai else "-",
                f"{(e.get('hbm_peak_bytes') or 0) / 2**20:,.1f}"
                if e.get("hbm_peak_bytes") is not None else "-",
                str(e.get("source", "")),
            ])
        widths = [max(len(c), *(len(r[i]) for r in rows))
                  for i, c in enumerate(cols)]
        lines.append("  " + "  ".join(c.ljust(w)
                                      for c, w in zip(cols, widths)))
        for r in rows:
            lines.append("  " + "  ".join(v.ljust(w)
                                          for v, w in zip(r, widths)))
    att = summary.get("attribution")
    if att:
        lines.append("")
        lines.append(format_attribution(att))
    return "\n".join(lines)


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        prog="deepgo_tpu.obs.report",
        description="join a run's metrics/trace/elastic JSONL streams "
                    "into one per-stage table")
    ap.add_argument("run_dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of the table")
    args = ap.parse_args(argv)
    summary = summarize_run(args.run_dir)
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(format_report(summary))


if __name__ == "__main__":
    main()
