"""Workload observatory: what the fleet is actually asked to serve.

PRs 5-14 made the SYSTEM exhaustively observable — metrics, traces,
cost, anomalies — but the TRAFFIC not at all: nothing records which
positions arrive, how duplicated they are, or how bursty the arrival
process is. ROADMAP item 3 (the content-addressed position cache) is
justified by ">2x effective boards/sec under a realistic opening-heavy
trace" and item 5's chaos benches by SLOs under mixed workloads — both
claims need a measured trace before they can be honest (FireCaffe,
arXiv:1511.00175, attributes its scaling gap before closing it). This
module is that measurement layer:

  * ``WorkloadRecorder`` — tapped into ``FleetRouter.submit`` /
    ``SupervisedEngine.submit`` / ``InferenceEngine.submit`` exactly
    like request tracing: OFF by default, every plumbing site a
    ``workload is None`` check, overhead A/B-bounded under the same
    <2% budget (``bench.py --mode serving`` measures it). Each request
    streams one ``workload_request`` JSONL record — arrival wall time,
    tier, bucket, outcome, latency — keyed by TWO content digests of
    the packed feature planes: the exact digest and the 8-fold-symmetry
    CANONICAL digest (all dihedral views of a position share one key —
    the cache-entry identity item 3 will coalesce on). Digests are
    computed on the recorder's writer thread, never on the submit path;
    the hot path pays one ~3.2KB ``tobytes`` copy and a bounded-queue
    put. Each distinct exact digest additionally writes one
    ``workload_position`` record carrying the packed payload (base64),
    so a capture is REPLAYABLE: the position store is content-addressed
    and deduplicated — an opening-heavy hour of traffic stores each
    opening once.
  * the **analyzer** (``analyze_capture``) — joins a capture into the
    characterization report: unique-vs-total positions, the
    symmetry-dedup gain, popularity skew (top-k mass, Zipf fit),
    inter-arrival burstiness, tier/bucket/outcome mix, and the
    **projected cache hit rate** — the number the cache PR's ">2x"
    claim will be gated against (``cli workload analyze``).
  * the replay side lives in ``serving/replay.py`` (``WorkloadReplayer``
    + the synthetic opening-heavy generator); this module owns the
    capture format both ends share.

Capture layout: one directory holding ``workload.jsonl`` (the request
stream) and ``positions.jsonl`` (the deduplicated position store), both
rotation-aware ``JsonlSink`` streams read back through the torn-line-
tolerant ``report.read_events``. See docs/observability.md "Workload
observatory".
"""

from __future__ import annotations

import base64
import os
import queue
import threading
import time

import numpy as np

from ..analysis.lockcheck import make_lock
from ..utils import digest as _digest
from .registry import get_registry

# digest math lives in utils/digest.py — ONE implementation shared with
# the position cache (serving/cache.py) and training augmentation
# (ops/augment.py); the names below stay re-exported because captures,
# tools, and tests address them through this module
PACKED_SHAPE = _digest.PACKED_SHAPE
_NUM_POINTS = _digest.NUM_POINTS

_DIGEST_HEX = _digest.DIGEST_HEX

# request outcomes a capture distinguishes (the replay side reproduces
# the submit mix; outcomes re-resolve live)
OUTCOMES = ("ok", "shed", "timeout", "poisoned", "failed")

_SHED_ERRORS = frozenset({"EngineOverloaded", "CircuitOpen", "EngineBusy",
                          "FleetUnavailable"})
_POISON_ERRORS = frozenset({"PoisonedRequest"})


class WorkloadCaptureError(RuntimeError):
    """A capture directory is missing, unreadable, or not a capture."""


_dihedral_perms = _digest.dihedral_perms
_PERMS = _digest.PERMS
NUM_SYMMETRIES = _digest.NUM_SYMMETRIES

_digest_bytes = _digest.digest_bytes
exact_digest = _digest.exact_digest
canonical_digest = _digest.canonical_digest
dihedral_views = _digest.dihedral_views


def encode_packed(packed: np.ndarray) -> str:
    return base64.b64encode(
        np.ascontiguousarray(np.asarray(packed, dtype=np.uint8))
        .tobytes()).decode("ascii")


def decode_packed(payload: str) -> np.ndarray:
    raw = base64.b64decode(payload)
    arr = np.frombuffer(raw, dtype=np.uint8)
    if arr.size != int(np.prod(PACKED_SHAPE)):
        raise WorkloadCaptureError(
            f"position payload has {arr.size} bytes, expected "
            f"{int(np.prod(PACKED_SHAPE))}")
    return arr.reshape(PACKED_SHAPE).copy()


class WorkloadToken:
    """One request's tap: created by the OUTERMOST serving layer the
    caller entered (fleet router, supervisor, or bare engine — the same
    ownership discipline as tracing.TraceContext), handed down so the
    engine can stamp the bucket the request coalesced into. ``finish``
    is idempotent; exactly one record reaches the recorder."""

    __slots__ = ("payload", "player", "rank", "tier", "fields", "t_wall",
                 "t_mono", "bucket", "_recorder", "_finished")

    def __init__(self, recorder: "WorkloadRecorder", payload: bytes,
                 player: int, rank: int, tier: str | None, **fields):
        self.payload = payload
        self.player = int(player)
        self.rank = int(rank)
        self.tier = tier
        self.fields = {k: v for k, v in fields.items() if v is not None}
        self.t_wall = time.time()
        self.t_mono = time.monotonic()
        self.bucket: int | None = None
        self._recorder = recorder
        self._finished = False

    def finish(self, outcome: str) -> None:
        if self._finished:
            return
        self._finished = True
        latency = time.monotonic() - self.t_mono
        rec = self._recorder
        if rec is not None:
            rec.commit(self, outcome, latency)

    def finish_future(self, f) -> None:
        """The owner's done-callback target: classify the resolved
        future into a workload outcome. Never raises — a recording bug
        must not strand the future's waiter."""
        try:
            exc = f.exception()
        except BaseException:  # noqa: BLE001 — cancelled future
            exc = None
        if exc is None:
            self.finish("ok")
            return
        name = type(exc).__name__
        if isinstance(exc, TimeoutError):
            self.finish("timeout")
        elif name in _SHED_ERRORS:
            self.finish("shed")
        elif name in _POISON_ERRORS:
            self.finish("poisoned")
        else:
            self.finish("failed")


class WorkloadRecorder:
    """Streams the capture: a bounded hand-off queue feeds one writer
    thread that computes both digests, deduplicates the position store,
    and writes the two JSONL streams. The submit path never hashes and
    never touches disk; a full queue DROPS (counted — a flooded
    recorder backs off rather than backpressuring the serving path)."""

    def __init__(self, sink, position_sink=None, max_queue: int = 4096,
                 store_positions: bool = True):
        self.sink = sink
        self.position_sink = position_sink if position_sink is not None \
            else sink
        self.store_positions = store_positions
        self.enabled = True
        self._queue: queue.Queue = queue.Queue(maxsize=max_queue)
        self._lock = make_lock("obs.workload")
        self._seen: set[str] = set()        # exact digests already stored
        self._canonical: set[str] = set()
        # exact -> canonical memo: a duplicate request (the common case
        # in the opening-heavy workloads this exists to measure) costs
        # the writer ONE content hash, not the nine of a fresh orbit
        self._canon_of: dict[str, str] = {}
        self.started = 0
        self.finished = 0
        self.dropped = 0
        self.by_tier: dict[str, int] = {}
        self.by_outcome: dict[str, int] = {}
        reg = get_registry()
        self._obs_requests = reg.counter(
            "deepgo_workload_requests_total",
            "requests entering the serving path with the workload "
            "recorder armed, by priority tier")
        self._closing = threading.Event()
        self._thread = threading.Thread(
            target=self._writer_loop, name="workload-writer", daemon=True)
        self._thread.start()

    # -- the hot path ------------------------------------------------------

    def note(self, packed, player: int, rank: int, tier: str | None = None,
             **fields) -> WorkloadToken:
        token = WorkloadToken(
            self, np.ascontiguousarray(np.asarray(packed, dtype=np.uint8))
            .tobytes(), player, rank, tier, **fields)
        with self._lock:
            self.started += 1
        return token

    def commit(self, token: WorkloadToken, outcome: str,
               latency_s: float) -> None:
        try:
            self._queue.put_nowait((token, outcome, latency_s))
        except queue.Full:
            with self._lock:
                self.dropped += 1

    # -- the writer thread -------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            try:
                item = self._queue.get(timeout=0.05)
            except queue.Empty:
                self._flush_sinks()
                if self._closing.is_set():
                    return
                continue
            try:
                self._write_one(*item)
            except (OSError, ValueError):
                pass  # a full disk must not kill the serving path

    def _flush_sinks(self) -> None:
        """Idle flush for block-buffered sinks: records become durable
        within one poll interval of the stream going quiet."""
        for sink in (self.sink, self.position_sink):
            flush = getattr(sink, "flush", None)
            if flush is not None:
                try:
                    flush()
                except (OSError, ValueError):
                    pass

    def _write_one(self, token: WorkloadToken, outcome: str,
                   latency_s: float) -> None:
        digest = _digest_bytes(token.payload, token.player, token.rank)
        canonical = self._canon_of.get(digest)
        if canonical is None:
            arr = np.frombuffer(token.payload, dtype=np.uint8) \
                .reshape(PACKED_SHAPE)
            canonical = canonical_digest(arr, token.player, token.rank)
        with self._lock:
            fresh = digest not in self._seen
            if fresh:
                self._seen.add(digest)
                self._canon_of[digest] = canonical
            self._canonical.add(canonical)
            self.finished += 1
            tier = token.tier or "untiered"
            self.by_tier[tier] = self.by_tier.get(tier, 0) + 1
            self.by_outcome[outcome] = self.by_outcome.get(outcome, 0) + 1
        # the arrival counter rides the writer, not the submit path —
        # the scrape lags by at most the hand-off queue's depth
        self._obs_requests.inc(tier=tier)
        if fresh and self.store_positions:
            self.position_sink.write(
                "workload_position", digest=digest, canonical=canonical,
                player=token.player, rank=token.rank,
                packed=base64.b64encode(token.payload).decode("ascii"))
        record = {
            "t": token.t_wall,
            "digest": digest,
            "canonical": canonical,
            "player": token.player,
            "rank": token.rank,
            "outcome": outcome,
            "latency_s": round(latency_s, 9),
            **token.fields,
        }
        if token.tier is not None:
            record["tier"] = token.tier
        if token.bucket is not None:
            record["bucket"] = int(token.bucket)
        self.sink.write("workload_request", **record)

    # -- read side ---------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "started": self.started,
                "finished": self.finished,
                "pending": self.started - self.finished - self.dropped,
                "dropped": self.dropped,
                "unique": len(self._seen),
                "canonical_unique": len(self._canonical),
                "by_tier": dict(self.by_tier),
                "by_outcome": dict(self.by_outcome),
            }

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every committed record is on disk (bounded)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._queue.empty():
                return True
            time.sleep(0.005)
        return self._queue.empty()

    def close(self, timeout_s: float = 10.0) -> dict:
        """Drain, stamp the ``workload_capture`` summary record, stop
        the writer. Returns the final stats. Idempotent."""
        self.drain(timeout_s)
        self._closing.set()
        self._thread.join(timeout=timeout_s)
        stats = self.stats()
        if self.enabled:
            self.enabled = False
            try:
                self.sink.write("workload_capture", **{
                    k: v for k, v in stats.items() if k != "pending"})
            except (OSError, ValueError):
                pass
        return stats


# ---------------------------------------------------------------------------
# the process-wide recorder (the serving layers' entry point)

_recorder: WorkloadRecorder | None = None
_owned_sinks: list = []


def configure_workload(capture_dir: str | None = None, sink=None,
                       position_sink=None, **kw) -> WorkloadRecorder:
    """Arm process-wide workload capture (idempotent — reconfiguring
    replaces the recorder). ``capture_dir`` builds the standard layout
    (``workload.jsonl`` + ``positions.jsonl``); alternatively pass
    explicit sinks (tests, bench A/B arms)."""
    global _recorder
    disable_workload()
    if sink is None:
        if capture_dir is None:
            raise ValueError("configure_workload needs capture_dir or sink")
        from .exporter import JsonlSink

        os.makedirs(capture_dir, exist_ok=True)
        # block-buffered: the writer thread flushes on idle instead of
        # paying a syscall per record — at serving rates the per-line
        # flush is most of the recorder's measured overhead
        sink = JsonlSink(os.path.join(capture_dir, "workload.jsonl"),
                         buffering=1 << 16)
        position_sink = JsonlSink(os.path.join(capture_dir,
                                               "positions.jsonl"),
                                  buffering=1 << 16)
        _owned_sinks.extend([sink, position_sink])
    _recorder = WorkloadRecorder(sink, position_sink=position_sink, **kw)
    return _recorder


def disable_workload() -> None:
    """Disarm: ``note_request`` returns None again and every plumbing
    site reverts to its zero-cost ``workload is None`` branch. Closes
    the recorder (capture summary stamped) and any owned sinks."""
    global _recorder
    rec, _recorder = _recorder, None
    if rec is not None:
        rec.close()
    while _owned_sinks:
        try:
            _owned_sinks.pop().close()
        except (OSError, ValueError):  # pragma: no cover — close race
            pass


def workload_enabled() -> bool:
    return _recorder is not None and _recorder.enabled


def get_workload_recorder() -> WorkloadRecorder | None:
    return _recorder


def note_request(packed, player: int, rank: int, tier: str | None = None,
                 **fields) -> WorkloadToken | None:
    """The serving layers' creation point: a live WorkloadToken when the
    recorder is armed, None (the zero-overhead path) otherwise."""
    rec = _recorder
    if rec is None or not rec.enabled:
        return None
    return rec.note(packed, player, rank, tier=tier, **fields)


# ---------------------------------------------------------------------------
# capture reading + the characterization report

def _capture_paths(path: str) -> tuple[str, str]:
    """(requests stream, positions stream) for a capture directory or a
    direct workload.jsonl path."""
    if os.path.isdir(path):
        return (os.path.join(path, "workload.jsonl"),
                os.path.join(path, "positions.jsonl"))
    return path, os.path.join(os.path.dirname(path), "positions.jsonl")


def load_capture(path: str) -> dict:
    """Read one capture back: requests oldest-first (by arrival stamp),
    the position store keyed by exact digest, and the close-time
    summary when the capture was cleanly closed. Torn lines are skipped
    (report.read_events); a missing stream is a typed error."""
    from .report import read_events

    req_path, pos_path = _capture_paths(path)
    if not os.path.exists(req_path):
        raise WorkloadCaptureError(
            f"no workload capture at {path!r} (expected {req_path})")
    requests = []
    captures = []
    positions: dict[str, dict] = {}
    for r in read_events(req_path):
        kind = r.get("kind")
        if kind == "workload_request":
            requests.append(r)
        elif kind == "workload_capture":
            captures.append(r)
        elif kind == "workload_position":
            positions[r["digest"]] = r
    for r in read_events(pos_path):
        if r.get("kind") == "workload_position":
            positions[r["digest"]] = r
    requests.sort(key=lambda r: float(r.get("t", 0.0)))
    return {"requests": requests, "positions": positions,
            "summary": captures[-1] if captures else None}


def _zipf_fit(counts: list[int]) -> float | None:
    """Least-squares slope of log(freq) on log(rank) over the sorted
    popularity counts — the Zipf exponent estimate (negated, so ~1.0
    is classic Zipf). None below 3 distinct positions."""
    if len(counts) < 3:
        return None
    ranks = np.arange(1, len(counts) + 1, dtype=np.float64)
    freqs = np.array(sorted(counts, reverse=True), dtype=np.float64)
    x, y = np.log(ranks), np.log(freqs)
    slope = float(np.polyfit(x, y, 1)[0])
    return round(-slope, 4)


def _characterize_sessions(by_session: dict[str, list[float]],
                           top_n: int = 8) -> dict:
    """The per-session view of a labeled capture: how many distinct
    sessions, how much of the traffic carries a label, and — for the
    busiest ``top_n`` — each session's own arrival burstiness (Goh &
    Barabasi, same convention as the global interarrival block), which
    is what distinguishes session-shaped traffic (think-time gaps) from
    scan-shaped saturation."""
    top = {}
    busiest = sorted(by_session.items(),
                     key=lambda kv: (-len(kv[1]), kv[0]))[:top_n]
    for sid, times in busiest:
        entry: dict = {"requests": len(times)}
        inter = np.diff(np.array(sorted(times)))
        if inter.size >= 2:
            mean = float(inter.mean())
            cv = float(inter.std()) / mean if mean > 0 else None
            entry["burstiness"] = (round((cv - 1) / (cv + 1), 4)
                                   if cv is not None else None)
        top[sid] = entry
    return {
        "count": len(by_session),
        "labeled_requests": sum(len(t) for t in by_session.values()),
        "top": top,
    }


def characterize(requests: list[dict]) -> dict:
    """The analyzer core over already-loaded request records (the
    capture-file-free entry bench and tests use)."""
    total = len(requests)
    if total == 0:
        return {"requests": 0}
    exact: dict[str, int] = {}
    canon: dict[str, int] = {}
    by_tier: dict[str, int] = {}
    by_bucket: dict[str, int] = {}
    by_outcome: dict[str, int] = {}
    by_session: dict[str, list[float]] = {}
    latencies: list[float] = []
    search_canon: dict[str, int] = {}
    search_sessions: set[str] = set()
    for r in requests:
        d = r.get("digest")
        c = r.get("canonical", d)
        exact[d] = exact.get(d, 0) + 1
        canon[c] = canon.get(c, 0) + 1
        if r.get("session") is not None:
            sid = str(r["session"])
            by_session.setdefault(sid, []).append(float(r.get("t", 0.0)))
            if sid.startswith("search:"):
                search_canon[c] = search_canon.get(c, 0) + 1
                search_sessions.add(sid)
        tier = str(r.get("tier") or "untiered")
        by_tier[tier] = by_tier.get(tier, 0) + 1
        if r.get("bucket") is not None:
            b = str(r["bucket"])
            by_bucket[b] = by_bucket.get(b, 0) + 1
        outcome = str(r.get("outcome") or "unknown")
        by_outcome[outcome] = by_outcome.get(outcome, 0) + 1
        if r.get("latency_s") is not None:
            latencies.append(float(r["latency_s"]))
    unique = len(exact)
    canonical_unique = len(canon)
    counts = sorted(canon.values(), reverse=True)
    top_mass = {
        str(k): round(sum(counts[:k]) / total, 4)
        for k in (1, 10, 100) if k <= len(counts) or k == 1}
    times = sorted(float(r.get("t", 0.0)) for r in requests)
    span = times[-1] - times[0] if total > 1 else 0.0
    inter = np.diff(np.array(times)) if total > 1 else np.array([])
    interarrival = None
    if inter.size:
        mean = float(inter.mean())
        std = float(inter.std())
        cv = std / mean if mean > 0 else None
        interarrival = {
            "mean_ms": round(mean * 1000, 4),
            "p99_ms": round(float(np.percentile(inter, 99)) * 1000, 4),
            "cv": round(cv, 4) if cv is not None else None,
            # Goh & Barabasi burstiness: -1 periodic, 0 Poisson, ->1 bursty
            "burstiness": round((cv - 1) / (cv + 1), 4)
            if cv is not None else None,
        }
    out = {
        "requests": total,
        "unique": unique,
        "canonical_unique": canonical_unique,
        "dup_ratio": round(1.0 - unique / total, 4),
        "symmetry_dedup_gain": round(unique / canonical_unique, 4),
        # the cache-PR gate numbers: an infinite exact-hit cache serves
        # dup requests for free; the canonical variant also folds all 8
        # dihedral views of a position onto one entry
        "projected_hit_rate": round(1.0 - unique / total, 4),
        "projected_hit_rate_canonical": round(
            1.0 - canonical_unique / total, 4),
        "top_mass": top_mass,
        "zipf_exponent": _zipf_fit(list(canon.values())),
        "span_s": round(span, 4),
        "requests_per_sec": round(total / span, 2) if span > 0 else None,
        "tiers": {t: by_tier[t] for t in sorted(by_tier)},
        "outcomes": {o: by_outcome[o] for o in sorted(by_outcome)},
    }
    if by_bucket:
        out["buckets"] = {b: by_bucket[b]
                          for b in sorted(by_bucket, key=int)}
    if by_session:
        out["sessions"] = _characterize_sessions(by_session)
    if search_canon:
        # the search-shaped slice: leaf evaluations labeled
        # ``search:<id>`` by the PUCT searcher. The transposition dup
        # ratio is how much of the search's leaf traffic the
        # transposition table / canonical cache serves for free —
        # the measured justification for keying the tree on the
        # content-addressed digests (docs/search.md)
        s_total = sum(search_canon.values())
        out["search"] = {
            "requests": s_total,
            "searches": len(search_sessions),
            "canonical_unique": len(search_canon),
            "transposition_dup_ratio": round(
                1.0 - len(search_canon) / s_total, 4),
        }
    if interarrival is not None:
        out["interarrival"] = interarrival
    if latencies:
        lat = np.array(latencies)
        out["latency_ms"] = {
            "p50": round(float(np.percentile(lat, 50)) * 1000, 3),
            "p99": round(float(np.percentile(lat, 99)) * 1000, 3),
        }
    return out


def analyze_capture(path: str) -> dict:
    """The full characterization report for one capture directory."""
    cap = load_capture(path)
    out = characterize(cap["requests"])
    out["capture"] = path
    out["positions_stored"] = len(cap["positions"])
    replayable = bool(cap["positions"]) and all(
        r.get("digest") in cap["positions"] for r in cap["requests"])
    out["replayable"] = replayable
    if cap["summary"] is not None:
        out["recorder_dropped"] = cap["summary"].get("dropped", 0)
    return out


def format_workload(stats: dict) -> str:
    """Terminal rendering of one characterization report (the report.py
    fixed-width discipline)."""
    if not stats.get("requests"):
        return "(empty capture: no workload_request records)"
    lines = []
    if stats.get("capture"):
        lines.append(f"capture: {stats['capture']}")
    lines.append(
        f"requests {stats['requests']}  unique {stats['unique']}  "
        f"canonical {stats['canonical_unique']}  "
        f"dup_ratio {stats['dup_ratio']:.2%}")
    lines.append(
        f"projected cache hit rate: exact {stats['projected_hit_rate']:.2%}"
        f"  canonical {stats['projected_hit_rate_canonical']:.2%}  "
        f"(symmetry dedup gain {stats['symmetry_dedup_gain']:.2f}x)")
    top = stats.get("top_mass", {})
    if top:
        lines.append("popularity: " + "  ".join(
            f"top-{k} mass {v:.2%}" for k, v in top.items())
            + (f"  zipf~{stats['zipf_exponent']}"
               if stats.get("zipf_exponent") is not None else ""))
    inter = stats.get("interarrival")
    if inter:
        lines.append(
            f"arrivals: {stats.get('requests_per_sec')}/s over "
            f"{stats.get('span_s')}s  interarrival mean "
            f"{inter['mean_ms']}ms p99 {inter['p99_ms']}ms  "
            f"cv {inter['cv']}  burstiness {inter['burstiness']}")
    sess = stats.get("sessions")
    if sess:
        parts = []
        for sid, entry in sess["top"].items():
            b = entry.get("burstiness")
            parts.append(f"{sid}={entry['requests']}"
                         + (f" (B {b})" if b is not None else ""))
        lines.append(
            f"sessions: {sess['count']} distinct  "
            f"{sess['labeled_requests']} labeled requests  "
            + "  ".join(parts))
    search = stats.get("search")
    if search:
        lines.append(
            f"search: {search['requests']} leaf evals across "
            f"{search['searches']} searches  canonical "
            f"{search['canonical_unique']}  transposition dup ratio "
            f"{search['transposition_dup_ratio']:.2%}")
    for name in ("tiers", "buckets", "outcomes"):
        mix = stats.get(name)
        if mix:
            total = sum(mix.values())
            lines.append(f"{name}: " + "  ".join(
                f"{k}={v} ({v / total:.1%})" for k, v in mix.items()))
    if stats.get("latency_ms"):
        lines.append(f"latency: p50 {stats['latency_ms']['p50']}ms  "
                     f"p99 {stats['latency_ms']['p99']}ms")
    if "replayable" in stats:
        lines.append(
            f"positions stored: {stats.get('positions_stored')}  "
            f"replayable: {stats['replayable']}"
            + (f"  recorder_dropped: {stats['recorder_dropped']}"
               if stats.get("recorder_dropped") else ""))
    return "\n".join(lines)
