"""Context-manager span tracing: host timelines that line up with device
traces.

A span is one named region of host work — a validation pass, a checkpoint
save, an elastic recovery — with an id, a parent id (nesting is tracked
per-context via ``contextvars``, so concurrently running threads build
independent trees), a status, and exception capture. Each completed span
is

  * streamed to the configured JSONL trace sink as an ``obs_span`` event
    (the offline report joins these against the metrics stream);
  * folded into the process registry's ``deepgo_span_seconds`` histogram,
    keyed by span name, so /metrics serves live p50/p99 per stage;
  * bridged onto ``jax.profiler.TraceAnnotation`` while active, so when a
    profiler capture is running (``utils.profiling.trace``) the same
    named region appears on the TensorBoard host timeline, aligned with
    the device ops it caused — one vocabulary across both tools.

Spans deliberately do NOT wrap the per-step hot path: a JSONL line per
training step would be measurable overhead (the ≤2 % budget), and the
hot paths already feed histograms directly. Spans are for the coarse
stages whose individual occurrences matter.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
import uuid

from .registry import get_registry

# the active span id for the current execution context; threads started
# fresh see None (their spans root a new tree), which is the honest
# answer — a loader worker's I/O is not causally inside one train window
_current: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "deepgo_obs_span", default=None)

_trace_sink = None  # process-wide span sink (a JsonlSink, or None)

# completed-span listeners (the flight recorder's ring buffer rides here);
# listeners receive the same record dict the sink gets and must never be
# able to break a traced region — exceptions are swallowed per listener
_listeners: list = []


def add_span_listener(fn) -> None:
    """Register ``fn(record: dict)`` to observe every completed span."""
    if fn not in _listeners:
        _listeners.append(fn)


def remove_span_listener(fn) -> None:
    try:
        _listeners.remove(fn)
    except ValueError:
        pass


def set_trace_sink(sink) -> None:
    """Install the process-wide span sink (``None`` disables streaming).
    The registry histogram and the profiler bridge stay active either
    way — spans are cheap enough to always aggregate."""
    global _trace_sink
    _trace_sink = sink


def get_trace_sink():
    return _trace_sink


@contextlib.contextmanager
def trace_to(sink):
    """Scoped sink installation: the experiment's train() wraps itself in
    ``trace_to(JsonlSink(<run>/trace.jsonl))`` so spans stream to the run
    directory for exactly the duration of the run, with the previous sink
    (usually None) restored even when training raises."""
    global _trace_sink
    previous = _trace_sink
    _trace_sink = sink
    try:
        yield sink
    finally:
        _trace_sink = previous


def current_span_id() -> str | None:
    return _current.get()


def capture_context() -> str | None:
    """Snapshot the current span parent for an explicit cross-thread
    handoff.

    ``contextvars`` do NOT cross thread boundaries: a span opened inside
    a worker thread (the serving dispatcher, a loader thread) roots a new
    tree even when the work is causally inside a submitting request's
    span. The fix is an explicit handoff — the submitting thread calls
    ``capture_context()`` and ships the value with the work item; the
    worker wraps its processing in ``attach_context(captured)`` so spans
    it opens parent under the submitter's span. Request tracing
    (obs/tracing.py) uses the same capture to stamp each request's
    ``parent_span``."""
    return _current.get()


@contextlib.contextmanager
def attach_context(parent_id: str | None):
    """Adopt a captured span context on THIS thread for the duration of
    the block: spans opened inside parent under ``parent_id`` (from
    ``capture_context()`` on the originating thread). Always restores the
    previous context, even when the body raises — a worker that processes
    many handoffs must not leak one request's context into the next."""
    token = _current.set(parent_id)
    try:
        yield
    finally:
        _current.reset(token)


def _profiler_annotation(name: str):
    """A jax.profiler.TraceAnnotation for ``name``, or a no-op when jax
    (or its profiler) is unavailable — spans must work in any process,
    including ones that never touch a device."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class Span:
    """One open span; exposed so the body can attach fields mid-flight
    (``span.fields["step"] = n``) that land in the JSONL record."""

    __slots__ = ("name", "span_id", "parent_id", "fields", "t0_wall",
                 "t0_mono")

    def __init__(self, name: str, parent_id: str | None, fields: dict):
        self.name = name
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.fields = fields
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()


@contextlib.contextmanager
def span(name: str, registry=None, **fields):
    """Trace one named region: ``with span("validate", step=n): ...``.

    On exit the record carries span/parent ids, wall start time, duration,
    ``status`` ("ok" | "error"), and the exception repr when the body
    raised — the exception itself always propagates (observability must
    never change control flow)."""
    parent = _current.get()
    s = Span(name, parent, dict(fields))
    token = _current.set(s.span_id)
    status, error = "ok", None
    try:
        with _profiler_annotation(name):
            yield s
    except BaseException as e:
        status, error = "error", repr(e)
        raise
    finally:
        _current.reset(token)
        duration = time.monotonic() - s.t0_mono
        reg = registry or get_registry()
        reg.histogram(
            "deepgo_span_seconds",
            "duration of named host spans (obs/spans.py)",
        ).observe(duration, name=name, status=status)
        sink = _trace_sink
        if sink is not None or _listeners:
            record = {
                "name": s.name,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "t_start": s.t0_wall,
                "duration_s": round(duration, 9),
                "status": status,
                **s.fields,
            }
            if error is not None:
                record["error"] = error
            if sink is not None:
                try:
                    sink.write("obs_span", **record)
                except (OSError, ValueError):
                    # a full disk or a concurrently closed sink must not
                    # turn a healthy traced region into a crash
                    pass
            for fn in list(_listeners):
                try:
                    fn(dict(record))
                except Exception:  # noqa: BLE001 — observers never raise out
                    pass
