"""Process-wide metrics registry: labeled counters, gauges, histograms.

The failure machinery from PRs 1-4 (restarts, breakers, poison quarantine,
elastic recovery) emits JSONL *events*; this module adds the *aggregates*
nobody could build from those streams without replaying them: monotonic
counters, last-value gauges, and fixed-bucket histograms with percentile
snapshots, all scrapeable live (obs/exporter.py renders Prometheus text)
and snapshottable as one dict (bench attachments, the offline report).

Design rules, in the repo's house style:

  * thread-safe — instrumented call sites live in dispatcher threads,
    loader workers, and the train loop simultaneously; one lock per
    metric family keeps contention off the hot path (no global lock);
  * injectable clock — `Histogram.time()` and snapshot timestamps take
    the registry's clock, so tests drive every duration with a fake
    clock and never sleep (the liveness/supervisor discipline);
  * labels are kwargs — `counter.inc(engine="policy")` — and each label
    combination is an independent series, matching the Prometheus data
    model the exporter renders;
  * fixed buckets — histograms never allocate per-observation; the
    percentile snapshot interpolates inside the owning bucket, with the
    observed min/max pinning the edge buckets so small known datasets
    report honest p50/p95/p99 (tests/test_obs.py asserts against known
    data).

A process-wide default registry (`get_registry()`) is what the built-in
instrumentation uses; tests that need isolation construct private
`MetricsRegistry` instances.
"""

from __future__ import annotations

import bisect
import re
import threading
import time

from ..analysis.lockcheck import make_lock

# seconds-scale latency ladder: sub-millisecond loader waits up to
# multi-second recoveries land in distinct buckets
DEFAULT_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Metric:
    """Base: one named family of label-keyed series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        if not _NAME_RE.match(name):
            # typed, never assert: a bad metric name must fail at
            # registration under ``python -O`` too, not at scrape time
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self._lock = make_lock(f"obs.metric.{name}")
        self._series: dict[tuple, object] = {}

    def labelnames(self) -> list[tuple]:
        with self._lock:
            return list(self._series)


class Counter(Metric):
    """Monotonically increasing count per label set."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def collect(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._series)


class Gauge(Metric):
    """Last-set value per label set; ``set_function`` registers a live
    callable read at collect time (queue depths, breaker states)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            current = self._series.get(key, 0.0)
            if callable(current):
                raise ValueError(
                    f"gauge {self.name}{dict(key)} is callback-backed")
            self._series[key] = float(current) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn, **labels) -> None:
        """Collect-time callback: the scrape reads ``fn()`` live. A raising
        callback reads as the last resort value 0.0 — a scrape must never
        crash on a dying component (that is what /healthz is for)."""
        with self._lock:
            self._series[_label_key(labels)] = fn

    def value(self, **labels) -> float:
        with self._lock:
            v = self._series.get(_label_key(labels), 0.0)
        if callable(v):
            try:
                return float(v())
            except Exception:
                return 0.0
        return float(v)

    def collect(self) -> dict[tuple, float]:
        with self._lock:
            items = list(self._series.items())
        out = {}
        for key, v in items:
            if callable(v):
                try:
                    v = float(v())
                except Exception:
                    v = 0.0
            out[key] = float(v)
        return out


class _HistSeries:
    __slots__ = ("counts", "total", "sum", "vmin", "vmax")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1: the +Inf overflow bucket
        self.total = 0
        self.sum = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")


class Histogram(Metric):
    """Fixed-bucket distribution per label set.

    ``observe()`` is O(log buckets) and allocation-free — cheap enough for
    the loader-wait and dispatch-latency hot paths. Percentiles come from
    bucket interpolation: exact to within one bucket's width, with the
    running min/max tightening the estimate at the edges (a dataset that
    fits one bucket still reports a sane spread)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS_S):
        super().__init__(name, help)
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram {name} buckets must be a sorted "
                             f"non-empty sequence, got {buckets!r}")
        self.buckets = tuple(float(b) for b in buckets)

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = _label_key(labels)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets))
            s.counts[i] += 1
            s.total += 1
            s.sum += value
            s.vmin = min(s.vmin, value)
            s.vmax = max(s.vmax, value)

    def time(self, clock=None, **labels):
        """Context manager observing the wrapped block's duration."""
        return _Timer(self, clock or time.monotonic, labels)

    def _percentile(self, s: _HistSeries, q: float) -> float:
        """Interpolated q-quantile (0 < q <= 1) from the bucket counts."""
        target = q * s.total
        edges = self.buckets
        cum = 0
        for i, c in enumerate(s.counts):
            if c == 0:
                continue
            lo = edges[i - 1] if i > 0 else min(s.vmin, edges[0])
            hi = edges[i] if i < len(edges) else s.vmax
            # clamp both edges by the observed extremes: a bucket's
            # occupants cannot lie outside [vmin, vmax]
            lo = max(lo, s.vmin)
            hi = min(hi, s.vmax)
            if cum + c >= target:
                frac = (target - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return s.vmax

    def snapshot(self, **labels) -> dict | None:
        """count / sum / min / max / p50 / p95 / p99 for one label set,
        or None before the first observation."""
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None or s.total == 0:
                return None
            counts = list(s.counts)
            frozen = _HistSeries(len(self.buckets))
            frozen.counts, frozen.total = counts, s.total
            frozen.sum, frozen.vmin, frozen.vmax = s.sum, s.vmin, s.vmax
        return {
            "count": frozen.total,
            "sum": round(frozen.sum, 9),
            "min": frozen.vmin,
            "max": frozen.vmax,
            "mean": frozen.sum / frozen.total,
            "p50": self._percentile(frozen, 0.50),
            "p95": self._percentile(frozen, 0.95),
            "p99": self._percentile(frozen, 0.99),
        }

    def collect(self) -> dict[tuple, dict]:
        with self._lock:
            keys = list(self._series)
        return {k: self.snapshot(**dict(k)) for k in keys}

    def collect_raw(self) -> dict[tuple, tuple[list[int], int, float]]:
        """(bucket counts, total, sum) per series — the exporter's
        cumulative ``_bucket`` rendering needs the raw counts."""
        with self._lock:
            return {k: (list(s.counts), s.total, s.sum)
                    for k, s in self._series.items()}


class _Timer:
    def __init__(self, hist: Histogram, clock, labels: dict):
        self._hist = hist
        self._clock = clock
        self._labels = labels

    def __enter__(self):
        self._t0 = self._clock()
        return self

    def __exit__(self, *exc):
        self._hist.observe(self._clock() - self._t0, **self._labels)


class MetricsRegistry:
    """One namespace of metrics; get-or-create semantics per name.

    Re-registering an existing name returns the existing metric when the
    kind matches (instrumented modules can be imported in any order) and
    raises when it doesn't (two subsystems fighting over one name is a
    bug, not a race to tolerate)."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = make_lock("obs.registry")
        self._metrics: dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, requested {cls.kind}")
                return existing
            metric = self._metrics[name] = cls(name, help, **kw)
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS_S) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def metrics(self) -> list[Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """Everything the registry knows, as one JSON-serializable dict:
        {name: {kind, help, series: {label-string: value-or-histogram}}}.
        This is what bench attaches to its JSON artifacts and what the
        train loop writes as the final ``obs_snapshot`` metrics event."""
        out: dict = {"time": self._clock(), "metrics": {}}
        for m in self.metrics():
            series = {}
            for key, value in m.collect().items():
                label = ",".join(f"{k}={v}" for k, v in key) or ""
                series[label] = value
            out["metrics"][m.name] = {
                "kind": m.kind, "help": m.help, "series": series}
        return out


_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every built-in instrumentation point
    uses; the exporter scrapes it and bench snapshots it."""
    return _default
