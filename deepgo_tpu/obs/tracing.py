"""Request-scoped tracing: per-request timelines, tail exemplars, lineage.

PRs 5-6 made the system observable in AGGREGATE — histograms, burn
rates, step-time attribution — but no individual request could be
followed through it: a p99 outlier, a failover hop, or a champion's
provenance was invisible below the histogram. This module adds the
per-request (and per-champion) anatomy:

  * ``TraceContext`` — one request's identity (trace id + the submitting
    thread's span, via ``spans.capture_context()``) and its live
    timeline. The serving layers stamp it as the request moves:
    ``queued`` (fleet door / engine queue) → ``routed`` (placement, with
    the replica id) → ``coalesced`` (batch formed, with bucket) →
    ``dispatched`` (forward begins) → ``resolved`` — plus the failure
    vocabulary: ``hop`` (failover off a dead replica, with the error),
    ``replayed`` (supervisor restart replay), ``isolated`` (poison
    bisection solo retry), ``failed`` / ``expired``. One trace id
    survives restarts and failovers end to end; the timeline is the
    proof.
  * ``TraceRecorder`` — bounded-memory exemplar sampling over completed
    timelines: always keep the slowest-k per window, p99+ outliers
    (against a rolling duration window), and every *notable* trace
    (error status, failover hops, replay/isolation events). Kept
    exemplars stream as ``trace_request`` JSONL records to the
    configured sink and sit in a fixed-size ring that the crash flight
    recorder (obs/sentinel.py) folds into every dump — a restart/SLO-
    burn/HostLost postmortem carries the actual anatomy of the slow or
    failed requests that preceded it, not just aggregate snapshots.
  * **lineage** — the same id discipline extended to the expert-
    iteration loop as durable ``lineage_*`` events: actors tag ingested
    games (``lineage_game``), the buffer records game→segment at seal
    (``lineage_segment``), the learner records extent→window→checkpoint
    ``params_digest`` (``lineage_window``), and the gatekeeper records
    checkpoint→gate-verdict→champion-publish (``lineage_gate`` /
    ``lineage_champion``) — so ``cli trace RUN_DIR champion`` walks the
    chain backwards and answers "which games trained the champion
    currently serving".

Tracing is OFF by default and every plumbing site is a ``trace is None``
check — the measured overhead budget is <2% boards/sec, enforced by the
tracing-on/off A/B in ``bench.py --mode serving``. ``cli trace RUN_DIR
ID`` reconstructs either view offline: a request waterfall from
``trace_request`` records, or a champion's provenance from the
``lineage_*`` stream (docs/observability.md).
"""

from __future__ import annotations

import heapq
import time
import uuid

import numpy as np

from ..analysis.lockcheck import make_lock
from .registry import get_registry
from .spans import capture_context

# timeline event names that make a trace "notable" (kept as an exemplar
# regardless of duration: they are the failure anatomy)
NOTABLE_EVENTS = frozenset({"hop", "replayed", "isolated", "failed",
                            "expired"})

# the event grammar a COMPLETE successful timeline must contain, in
# order — what the no-orphan acceptance check verifies per request
REQUIRED_OK_EVENTS = ("queued", "dispatched", "resolved")


class TraceContext:
    """One request's trace id + live timeline.

    Created by the outermost serving layer the caller entered (fleet
    router, supervisor, or bare engine — whichever sees the request
    first owns ``finish``); inner layers stamp events on the SAME
    context, so the id survives failovers, restarts, and replays.
    Marks are list appends (GIL-atomic); ``finish`` is idempotent —
    exactly one resolution reaches the recorder."""

    __slots__ = ("trace_id", "parent_span", "t0_wall", "t0_mono",
                 "events", "hops", "fields", "_recorder", "_finished")

    def __init__(self, recorder: "TraceRecorder", **fields):
        self.trace_id = uuid.uuid4().hex[:16]
        self.parent_span = capture_context()
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()
        self.events: list[dict] = []
        self.hops: list[dict] = []
        self.fields = {k: v for k, v in fields.items() if v is not None}
        self._recorder = recorder
        self._finished = False

    def _t_ms(self) -> float:
        return round((time.monotonic() - self.t0_mono) * 1000.0, 3)

    def mark(self, name: str, **fields) -> None:
        """Stamp one timeline event at now (ms offset from creation)."""
        self.events.append({"name": name, "t_ms": self._t_ms(), **fields})

    def hop(self, replica, error: str) -> None:
        """Record one failover hop: the request fled ``replica`` after
        ``error``. Hops ride both the hop list (the anatomy the ISSUE
        asks for) and the merged timeline."""
        t = self._t_ms()
        self.hops.append({"replica": replica, "error": error, "t_ms": t})
        self.events.append({"name": "hop", "t_ms": t, "replica": replica,
                            "error": error})

    def set(self, **fields) -> None:
        """Merge request-level fields (tier, bucket, replica, engine)."""
        for k, v in fields.items():
            if v is not None:
                self.fields[k] = v

    def finish(self, status: str = "ok", error: str | None = None) -> None:
        if self._finished:
            return
        self._finished = True
        duration = time.monotonic() - self.t0_mono
        rec = self._recorder
        if rec is not None:
            rec.record(self, duration, status, error)

    def finish_future(self, f) -> None:
        """The owner's done-callback target: classify the resolved
        future into a trace status. Never raises — a tracing bug must
        not strand the future's waiter."""
        try:
            exc = f.exception()
        except BaseException:  # noqa: BLE001 — cancelled future
            exc = None
        if exc is None:
            self.finish("ok")
        else:
            self.finish("error", error=type(exc).__name__)

    def to_record(self, duration_s: float, status: str,
                  error: str | None) -> dict:
        record = {
            "trace_id": self.trace_id,
            "parent_span": self.parent_span,
            "t_start": self.t0_wall,
            "duration_s": round(duration_s, 9),
            "status": status,
            **self.fields,
            "hops": list(self.hops),
            "events": list(self.events),
        }
        if error is not None:
            record["error"] = error
        return record


class TraceRecorder:
    """Bounded-memory exemplar sampler over completed request timelines.

    Keep policy (all three independent, all bounded):

      * the slowest-k of the current sampling window (a min-heap of
        size k, reset every ``window_s``);
      * p99+ outliers against a rolling window of recent durations
        (percentile recomputed every ``p99_refresh`` finishes, so the
        hot path pays a deque append, not a sort);
      * every notable trace — error status, failover hops, replay or
        isolation events (the failure anatomy is always worth a slot).

    Kept exemplars land in a fixed-size ring (``exemplars()``, what the
    flight recorder folds into dumps) and stream as ``trace_request``
    JSONL records when a sink is configured. Memory is bounded by
    ``ring_size + p99_window + slowest_k`` records regardless of load —
    pinned by the sustained-load test."""

    def __init__(self, sink=None, slowest_k: int = 8,
                 window_s: float = 30.0, ring_size: int = 256,
                 p99_window: int = 2048, p99_refresh: int = 128,
                 clock=time.monotonic):
        self.sink = sink
        self.slowest_k = slowest_k
        self.window_s = window_s
        self.enabled = True
        self._clock = clock
        self._lock = make_lock("obs.trace")
        self._ring: list[dict] = []
        self._ring_size = ring_size
        self._durations: list[float] = []   # rolling p99 window
        self._p99_window = p99_window
        self._p99_refresh = p99_refresh
        self._p99: float | None = None
        self._window_heap: list[tuple[float, str]] = []  # (duration, id)
        self._window_t0 = clock()
        # accounting for the no-orphan acceptance check
        self.started = 0
        self.finished = 0
        self.incomplete = 0       # ok-status traces missing timeline events
        self.multi_hop = 0        # traces that failed over at least once
        self.errors = 0
        self.kept = 0
        reg = get_registry()
        self._obs_started = reg.counter(
            "deepgo_trace_requests_total",
            "requests that entered the serving path with tracing on")
        self._obs_kept = reg.counter(
            "deepgo_trace_exemplars_total",
            "traced requests kept as exemplars (slowest-k, p99+, notable)")

    # -- the hot path ------------------------------------------------------

    def start(self, **fields) -> TraceContext:
        with self._lock:
            self.started += 1
        self._obs_started.inc(1)
        return TraceContext(self, **fields)

    def record(self, ctx: TraceContext, duration_s: float, status: str,
               error: str | None) -> None:
        """One finished timeline: update accounting, decide exemplar."""
        notable = bool(ctx.hops) or any(
            e["name"] in NOTABLE_EVENTS for e in ctx.events)
        names = None
        if status == "ok":
            names = {e["name"] for e in ctx.events}
        with self._lock:
            self.finished += 1
            if ctx.hops:
                self.multi_hop += 1
            if status != "ok":
                self.errors += 1
            if names is not None and not names.issuperset(REQUIRED_OK_EVENTS):
                self.incomplete += 1
            keep = notable or status != "ok"
            # rolling p99 window + outlier check
            self._durations.append(duration_s)
            if len(self._durations) > self._p99_window:
                del self._durations[:len(self._durations) - self._p99_window]
            if self._p99 is None or self.finished % self._p99_refresh == 0:
                self._p99 = float(np.percentile(self._durations, 99))
            if duration_s >= self._p99:
                keep = True
            # slowest-k of the current window
            now = self._clock()
            if now - self._window_t0 > self.window_s:
                self._window_heap = []
                self._window_t0 = now
            if len(self._window_heap) < self.slowest_k:
                heapq.heappush(self._window_heap,
                               (duration_s, ctx.trace_id))
                keep = True
            elif duration_s > self._window_heap[0][0]:
                heapq.heapreplace(self._window_heap,
                                  (duration_s, ctx.trace_id))
                keep = True
            if not keep:
                return
            record = ctx.to_record(duration_s, status, error)
            self._ring.append(record)
            if len(self._ring) > self._ring_size:
                del self._ring[:len(self._ring) - self._ring_size]
            self.kept += 1
            sink = self.sink
        self._obs_kept.inc(1)
        if sink is not None:
            try:
                sink.write("trace_request", **record)
            except (OSError, ValueError):
                pass  # a full disk must not fail the traced request

    # -- read side ---------------------------------------------------------

    def exemplars(self) -> list[dict]:
        """The exemplar ring, oldest first — what the flight recorder
        dumps and ``/trace`` serves."""
        with self._lock:
            return list(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return {
                "started": self.started,
                "finished": self.finished,
                "orphans": self.started - self.finished,
                "incomplete": self.incomplete,
                "multi_hop": self.multi_hop,
                "errors": self.errors,
                "exemplars_kept": self.kept,
                "ring": len(self._ring),
            }


# ---------------------------------------------------------------------------
# the process-wide recorder (the serving layers' entry point)

_recorder: TraceRecorder | None = None


def configure_tracing(sink=None, **kw) -> TraceRecorder:
    """Arm process-wide request tracing (idempotent — reconfiguring
    replaces the recorder). Registers the exemplar ring as a flight-
    recorder dump section, so every incident postmortem carries the
    slow/failed request anatomy that preceded it."""
    global _recorder
    _recorder = TraceRecorder(sink=sink, **kw)
    from .sentinel import get_flight_recorder

    get_flight_recorder().add_section(
        "trace_exemplars",
        lambda: {"stats": _recorder.stats() if _recorder else None,
                 "exemplars": _recorder.exemplars() if _recorder else []})
    return _recorder


def disable_tracing() -> None:
    """Disarm: ``start_request`` returns None again and every plumbing
    site reverts to its zero-cost ``trace is None`` branch."""
    global _recorder
    _recorder = None
    from .sentinel import get_flight_recorder

    get_flight_recorder().remove_section("trace_exemplars")


def tracing_enabled() -> bool:
    return _recorder is not None and _recorder.enabled


def get_trace_recorder() -> TraceRecorder | None:
    return _recorder


def start_request(**fields) -> TraceContext | None:
    """The serving layers' creation point: a live TraceContext when
    tracing is armed, None (the zero-overhead path) otherwise."""
    rec = _recorder
    if rec is None or not rec.enabled:
        return None
    return rec.start(**fields)


# ---------------------------------------------------------------------------
# offline reconstruction: `cli trace RUN_DIR ID`

def load_trace_events(run_dir: str) -> dict:
    """Everything `cli trace` joins: ``trace_request`` records from the
    run's trace stream plus ``lineage_*`` events from the loop/metrics
    streams (rotation-aware, torn lines skipped)."""
    import os

    from .report import read_events

    requests: list[dict] = []
    lineage: list[dict] = []
    searches: list[dict] = []
    for name in ("trace.jsonl", "metrics.jsonl", "loop.jsonl"):
        for r in read_events(os.path.join(run_dir, name)):
            kind = r.get("kind")
            if kind == "trace_request":
                requests.append(r)
            elif isinstance(kind, str) and kind.startswith("lineage_"):
                lineage.append(r)
            elif isinstance(kind, str) and kind.startswith("search_"):
                searches.append(r)
    return {"requests": requests, "lineage": lineage,
            "searches": searches}


def find_request(events: dict, ident: str) -> dict | None:
    """The trace_request record whose id starts with ``ident`` (newest
    wins when a short prefix is ambiguous)."""
    hits = [r for r in events["requests"]
            if str(r.get("trace_id", "")).startswith(ident)]
    return hits[-1] if hits else None


def format_waterfall(record: dict) -> str:
    """The human rendering of one request timeline: one line per event,
    ms offsets from submit, hops merged in chronological order."""
    head = [f"trace {record.get('trace_id')}  status={record.get('status')}"]
    for k in ("tier", "replica", "bucket", "error"):
        if record.get(k) is not None:
            head.append(f"{k}={record[k]}")
    dur = record.get("duration_s")
    if dur is not None:
        head.append(f"duration={float(dur) * 1000:.3f}ms")
    if record.get("hops"):
        head.append(f"hops={len(record['hops'])}")
    lines = ["  ".join(head)]
    if record.get("parent_span"):
        lines.append(f"  parent span: {record['parent_span']}")
    events = sorted(record.get("events", []),
                    key=lambda e: float(e.get("t_ms", 0.0)))
    width = max((len(e.get("name", "")) for e in events), default=0)
    for e in events:
        detail = "  ".join(f"{k}={v}" for k, v in e.items()
                           if k not in ("name", "t_ms"))
        lines.append(f"  +{float(e.get('t_ms', 0.0)):9.3f}ms  "
                     f"{e.get('name', '?'):<{width}}  {detail}".rstrip())
    return "\n".join(lines)


def find_search(events: dict, ident: str) -> dict | None:
    """The search_request record whose search id starts with ``ident``
    (newest wins when a short prefix is ambiguous)."""
    hits = [r for r in events.get("searches", [])
            if str(r.get("search_id", "")).startswith(ident)]
    return hits[-1] if hits else None


def _point(move) -> str:
    if move is None or int(move) < 0:
        return "pass"
    x, y = divmod(int(move), 19)
    return f"({x},{y})"


def format_search(record: dict) -> str:
    """The human rendering of one search verdict: the move, the anytime
    accounting (simulations done vs lost, deadline compliance), wave
    occupancy, and the principal variation reconstructed from the
    tree's visit counts."""
    head = [f"search {record.get('search_id')}  "
            f"move={_point(record.get('move'))}"]
    if record.get("value") is not None:
        head.append(f"value={float(record['value']):+.4f}")
    if record.get("tier") is not None:
        head.append(f"tier={record['tier']}")
    if record.get("fallback"):
        head.append("FALLBACK")
    lines = ["  ".join(head)]
    lines.append(
        f"  simulations {record.get('simulations')}  "
        f"lost {record.get('lost', 0)}  waves {record.get('waves')}  "
        f"occupancy {record.get('wave_occupancy')}")
    deadline = record.get("deadline_s")
    lines.append(
        f"  duration {float(record.get('duration_s', 0)) * 1000:.1f}ms"
        + (f"  deadline {float(deadline) * 1000:.0f}ms"
           f"  met={record.get('deadline_met')}"
           if deadline is not None else ""))
    if record.get("digest"):
        lines.append(f"  root digest {str(record['digest'])[:16]}")
    pv = record.get("pv") or []
    if pv:
        lines.append("  pv: " + " ".join(_point(m) for m in pv))
    return "\n".join(lines)


def _latest(records: list[dict]) -> dict | None:
    return records[-1] if records else None


def build_lineage(events: dict, ident: str) -> dict | None:
    """Walk the lineage chain backwards from a champion (or a window
    digest / window number): champion → gate verdict → window → extent →
    segments → games. Returns the joined chain, or None when ``ident``
    matches nothing."""
    lineage = events["lineage"]
    champions = [r for r in lineage if r["kind"] == "lineage_champion"]
    gates = [r for r in lineage if r["kind"] == "lineage_gate"]
    windows = [r for r in lineage if r["kind"] == "lineage_window"]
    segments = [r for r in lineage if r["kind"] == "lineage_segment"]
    games = [r for r in lineage if r["kind"] == "lineage_game"]

    champion = gate = window = None
    if ident in ("champion", "latest"):
        champion = _latest(champions)
        if champion is not None:
            digest = champion.get("digest")
            gate = _latest([g for g in gates
                            if g.get("digest") == digest]) or _latest(gates)
        window = _latest([w for w in windows
                          if champion is not None
                          and w.get("digest") == champion.get("digest")])
        if window is None and gate is not None:
            window = _latest([w for w in windows
                              if w.get("digest") == gate.get("digest")])
    elif ident.startswith("window:") or ident.isdigit():
        num = int(ident.split(":", 1)[-1])
        window = _latest([w for w in windows if w.get("window") == num])
    else:
        window = _latest([w for w in windows
                          if str(w.get("digest", "")).startswith(ident)])
        if window is None:
            champion = _latest([c for c in champions
                                if str(c.get("digest", ""))
                                .startswith(ident)])
            if champion is not None:
                window = _latest([w for w in windows
                                  if w.get("digest")
                                  == champion.get("digest")])
    if window is None and champion is None:
        return None
    if gate is None and window is not None:
        gate = _latest([g for g in gates
                        if g.get("digest") == window.get("digest")])
    lo = hi = None
    if window is not None and window.get("extent"):
        lo, hi = int(window["extent"][0]), int(window["extent"][1])
    segs = [s for s in segments
            if lo is not None and int(s.get("hi", 0)) > lo
            and int(s.get("lo", 0)) < hi]
    gids = set()
    for s in segs:
        gids.update(range(int(s.get("first_gid", 0)),
                          int(s.get("last_gid", -1)) + 1))
    chain_games = [g for g in games if g.get("gid") in gids]
    return {"champion": champion, "gate": gate, "window": window,
            "segments": segs, "games": chain_games}


def format_lineage(chain: dict) -> str:
    """The provenance rendering: champion → gate → window → segments →
    games, one level per block."""
    lines = []
    champ = chain.get("champion")
    if champ is not None:
        lines.append(
            f"champion  step={champ.get('step')}  "
            f"digest={str(champ.get('digest', ''))[:16]}  "
            f"source={champ.get('source', 'gate')}")
    gate = chain.get("gate")
    if gate is not None:
        lines.append(
            f"  gate    {gate.get('outcome')}  "
            f"win_rate={gate.get('win_rate')}  "
            f"games={gate.get('games')}  "
            f"digest={str(gate.get('digest', ''))[:16]}")
    window = chain.get("window")
    if window is not None:
        lines.append(
            f"  window  {window.get('window')}  "
            f"steps {window.get('step0')}->{window.get('step1')}  "
            f"extent={window.get('extent')}  "
            f"version={window.get('version')}  "
            f"digest={str(window.get('digest', ''))[:16]}")
    segs = chain.get("segments") or []
    for s in segs:
        lines.append(
            f"    segment {s.get('segment')}  "
            f"[{s.get('lo')},{s.get('hi')})  "
            f"gids {s.get('first_gid')}..{s.get('last_gid')}  "
            f"games={s.get('games')}")
    games = chain.get("games") or []
    if games:
        by_source: dict[str, int] = {}
        for g in games:
            by_source[g.get("source", "?")] = \
                by_source.get(g.get("source", "?"), 0) + 1
        summary = ", ".join(f"{src} ({n})"
                            for src, n in sorted(by_source.items()))
        lines.append(f"    games   {len(games)} ingested by {summary}")
    if not lines:
        lines.append("(empty chain)")
    return "\n".join(lines)


def trace_report(run_dir: str, ident: str) -> str:
    """The `cli trace` body: a request waterfall when ``ident`` matches
    a sampled trace id, else the lineage chain, else a listing of what
    IS available (so a typo'd id still tells the operator where to
    look)."""
    events = load_trace_events(run_dir)
    if ident:
        record = find_request(events, ident)
        if record is not None:
            return format_waterfall(record)
        search = find_search(events, ident)
        if search is not None:
            return format_search(search)
        chain = build_lineage(events, ident)
        if chain is not None:
            return format_lineage(chain)
        lines = [f"no trace or lineage matches {ident!r} in {run_dir}"]
    else:
        lines = [f"traces available in {run_dir}:"]
    if events["requests"]:
        lines.append("sampled request exemplars:")
        for r in sorted(events["requests"],
                        key=lambda r: -float(r.get("duration_s", 0)))[:10]:
            lines.append(
                f"  {r.get('trace_id')}  "
                f"{float(r.get('duration_s', 0)) * 1000:9.3f}ms  "
                f"status={r.get('status')}  hops={len(r.get('hops', []))}")
    if events.get("searches"):
        lines.append("search verdicts:")
        for r in events["searches"][-10:]:
            lines.append(
                f"  {r.get('search_id')}  move={_point(r.get('move'))}  "
                f"sims={r.get('simulations')}  "
                f"{float(r.get('duration_s', 0)) * 1000:8.1f}ms")
    if events["lineage"]:
        windows = [r for r in events["lineage"]
                   if r["kind"] == "lineage_window"]
        if windows:
            lines.append("lineage windows:")
            for w in windows[-10:]:
                lines.append(f"  window {w.get('window')}  "
                             f"digest={str(w.get('digest', ''))[:16]}")
        lines.append("(try `champion`, a window number, or a digest "
                     "prefix)")
    if not events["requests"] and not events["lineage"]:
        lines.append("(no trace_request or lineage events found — was "
                     "tracing armed? obs/tracing.configure_tracing, "
                     "`cli loop --trace`, or bench --mode serving)")
    return "\n".join(lines)
