"""Unified observability: metrics registry, span tracing, live exporter.

The reference's entire observability story was wall-clock prints and a
Google-Forms POST (SURVEY.md §5.1/§5.5); PRs 1-4 replaced the prints with
JSONL *event* streams but left no way to aggregate, correlate, or scrape
them. This package closes that gap with three coordinated pieces:

  * ``registry``  — process-wide labeled Counter/Gauge/Histogram
    aggregates (thread-safe, injectable clock, snapshot-as-dict);
  * ``spans``     — context-manager span tracing with parent/child ids
    and exception capture, streamed to a JSONL trace file and bridged
    onto ``jax.profiler.TraceAnnotation`` so host stages line up with
    device traces in TensorBoard;
  * ``exporter``  — a daemon-thread HTTP endpoint serving ``/metrics``
    (Prometheus text) and ``/healthz`` (composed component health), plus
    the rotating ``JsonlSink`` every event stream now writes through;
  * ``report``    — the offline summarizer joining a run's metrics /
    trace / elastic streams into one per-stage table (``cli obs``);

plus the analysis-and-enforcement layer on top (ISSUE 6):

  * ``attribution`` — per-step wall-clock decomposed into loader-wait /
    h2d / compile / dispatch / compute / collective / checkpoint buckets
    with the residual called out, joined across elastic hosts;
  * ``slo``       — declarative objectives with multi-window error-budget
    burn rates, ``slo_burn`` events, and a degraded-but-200 /healthz
    component;
  * ``sentinel``  — the noise-aware bench regression gate
    (``bench.py --gate`` vs BENCH_LAST_GOOD.json) and the ring-buffer
    crash flight recorder dumped on restart/HostLost/fast-burn/watchdog;
  * ``costmodel`` — the AOT device cost ledger: every jitted entrypoint
    lowered + compiled ahead of time, XLA ``cost_analysis()`` FLOPs /
    bytes and ``memory_analysis()`` HBM folded into ``deepgo_cost_*``
    gauges, ``cost_ledger`` events, the exporter's ``/cost`` route, and
    the per-entrypoint roofline/MFU join ``bench --gate`` enforces;
  * ``tracing``   — request-scoped end-to-end timelines through the
    serving path (queued/routed/coalesced/dispatched/resolved + failover
    hops, one trace id surviving restarts), bounded-memory tail-exemplar
    sampling folded into flight-recorder dumps, and the loop's
    ``lineage_*`` provenance chain (``cli trace RUN_DIR ID``);
  * ``timeseries`` / ``federate`` / ``anomaly`` / ``dash`` — the fleet
    telemetry plane (ISSUE 14): a background sampler appending the
    registry to a retention-bounded, power-of-two-downsampled on-disk
    time-series store (``ts-NNNN.jsonl``), cross-host federation of
    live scrapes and offline stores into one host-labeled view (a dead
    endpoint is a ``ts_scrape_failed`` event, never a crash), streaming
    robust anomaly detection (EWMA+MAD z-score, drift, rate) over a
    declared watchlist feeding the flight recorder, and the
    ``cli dash`` / ``cli trend`` operator surfaces.

Finding scaling bottlenecks is a measurement problem first (FireCaffe,
arXiv:1511.00175; arXiv:1711.00705): every future perf claim in this
repo starts from these numbers. See docs/observability.md.
"""

from .registry import (DEFAULT_BUCKETS_S, Counter, Gauge,  # noqa: F401
                       Histogram, MetricsRegistry, get_registry)
from .spans import (add_span_listener, attach_context,  # noqa: F401
                    capture_context, current_span_id, get_trace_sink,
                    remove_span_listener, set_trace_sink, span, trace_to)
from .tracing import (TraceContext, TraceRecorder,  # noqa: F401
                      configure_tracing, disable_tracing,
                      get_trace_recorder, start_request, trace_report,
                      tracing_enabled)
from .exporter import (JsonlSink, ObsExporter,  # noqa: F401
                       health_from_engine, health_from_ledger,
                       render_prometheus, sink_files, start_exporter)
from .sentinel import (FlightRecorder, GateConfig,  # noqa: F401
                       configure_flight, evaluate_gate, flight_dump,
                       get_flight_recorder, install_signal_dump)
from .slo import (GaugeFloorObjective, HealthObjective,  # noqa: F401
                  HistogramLatencyObjective, SLOConfig, SloTracker,
                  parse_slo_spec)
from .attribution import (attribute_run, attribute_snapshot,  # noqa: F401
                          format_attribution)
from .costmodel import (CostEntry, CostLedger, PlatformPeak,  # noqa: F401
                        analytic_flops, analytic_train_flops, detect_peak,
                        dispatch_seconds_by_bucket, evaluate_mfu_floor,
                        format_ledger, get_cost_ledger, set_cost_ledger,
                        standard_ledger)
from .timeseries import (TelemetrySampler, TimeSeriesStore,  # noqa: F401
                         flatten_snapshot, get_live_store, load_samples,
                         series_from_samples, set_live_store)
from .anomaly import (DEFAULT_WATCHLIST, Anomaly,  # noqa: F401
                      AnomalyDetector, WatchSpec)
from .workload import (WorkloadCaptureError, WorkloadRecorder,  # noqa: F401
                       WorkloadToken, analyze_capture, canonical_digest,
                       characterize, configure_workload, disable_workload,
                       exact_digest, format_workload, get_workload_recorder,
                       load_capture, note_request, workload_enabled)
from .federate import (FederatedView, parse_prometheus,  # noqa: F401
                       scrape_series, store_series, with_labels)
