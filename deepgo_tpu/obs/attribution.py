"""Step-time attribution: where each training step's wall-clock went.

PR 5 left the raw material — histograms, spans, per-host elastic streams —
but no *answer* to the question every scaling PR argues about: of the
seconds a training run spent, how many fed the device and how many leaked
into the input pipeline, dispatch overhead, liveness bookkeeping, or
checkpoint I/O? FireCaffe (arXiv:1511.00175) and arXiv:1711.00705 frame
scaling losses exactly this way — attribute the gap to communication or
you will optimize the wrong thing. This module decomposes the measured
train-loop wall-clock (``deepgo_train_wall_seconds_total``) into named
buckets, each read from a hot-path histogram or span the loop already
feeds:

  bucket      source metric                                   meaning
  ------      -------------                                   -------
  loader_wait deepgo_loader_wait_seconds (minus inline h2d)   consumer blocked in AsyncLoader.get(): sampling + queueing
  h2d         deepgo_h2d_seconds{path=inline}                 host->device transfer paid on the consumer's clock
  compile     deepgo_train_dispatch_seconds{phase=first}      first step-call per program: trace + XLA compile
  dispatch    deepgo_train_dispatch_seconds{phase=steady}     host time inside warm step calls (dispatch overhead)
  compute     deepgo_train_fetch_seconds                      blocked on the window's loss fetch — the device fence
  collective  deepgo_collective_seconds                       host-side cross-host array assembly (multi-process runs)
  checkpoint  deepgo_span_seconds{name=checkpoint_save}       periodic checkpoint writes
  validate    deepgo_span_seconds{name=validate}              validation passes
  liveness    deepgo_train_hook_seconds                       window hook: heartbeat write + ledger poll + liveness check

Everything not covered is the **residual**, reported explicitly (the
acceptance bar: >= 95 % of wall-clock attributed on a dryrun train, the
rest named, never hidden). ``useful_compute_fraction`` is the compute
bucket's share — a *lower bound* on device utilization, since device work
overlapped with host-side stages (async dispatch) is invisible to a
host-clock decomposition.

Cross-host: each elastic host snapshots its registry into its own
``elastic-NNNN.jsonl`` stream at shutdown, so ``attribute_run`` joins the
per-host decompositions and reports the FireCaffe-style scaling view:
per-host samples/sec, fleet aggregate, and the per-host non-compute
fractions that bound scaling efficiency.

When the snapshot carries the AOT cost-ledger gauges (obs/costmodel.py —
the train loop measures its own step program at start), the decomposition
gains a ``roofline`` block: achieved FLOP/s and MFU against the recorded
platform peak, so "the compute bucket is 60% of wall" and "that compute
ran at 4% MFU" finally live in one table.

Consumers: ``cli obs`` (the per-stage report grows an attribution table)
and ``bench.py --mode distributed`` (the BENCH json gains an
``attribution`` field).
"""

from __future__ import annotations

import glob
import os

# (bucket, metric, label filter or None) — the decomposition table above,
# in display order. Label filters match series whose labels are a superset.
_BUCKETS = (
    ("loader_wait", "deepgo_loader_wait_seconds", None),
    ("h2d", "deepgo_h2d_seconds", {"path": "inline"}),
    ("compile", "deepgo_train_dispatch_seconds", {"phase": "first"}),
    ("dispatch", "deepgo_train_dispatch_seconds", {"phase": "steady"}),
    ("compute", "deepgo_train_fetch_seconds", None),
    ("collective", "deepgo_collective_seconds", None),
    ("checkpoint", "deepgo_span_seconds", {"name": "checkpoint_save"}),
    ("validate", "deepgo_span_seconds", {"name": "validate"}),
    ("liveness", "deepgo_train_hook_seconds", None),
)


def _parse_label(label: str) -> dict:
    """The snapshot's ``"k=v,k2=v2"`` series key back into a dict."""
    if not label:
        return {}
    out = {}
    for part in label.split(","):
        k, _, v = part.partition("=")
        out[k] = v
    return out


def _series_sum(metrics: dict, name: str, where: dict | None = None,
                field: str = "sum") -> float:
    """Sum one field over a metric's matching series in a registry
    snapshot (the ``{name: {kind, series: {label: snap}}}`` shape that
    ``obs_snapshot`` events and ``MetricsRegistry.snapshot()`` carry)."""
    m = metrics.get(name)
    if not m:
        return 0.0
    total = 0.0
    for label, snap in m.get("series", {}).items():
        if where is not None:
            labels = _parse_label(label)
            if any(labels.get(k) != str(v) for k, v in where.items()):
                continue
        if isinstance(snap, dict):
            total += float(snap.get(field) or 0.0)
        elif snap is not None:
            total += float(snap)  # counter/gauge series are bare numbers
    return total


def _series_one(metrics: dict, name: str, where: dict | None = None):
    """First matching series value (gauges/counters are bare numbers in a
    snapshot) or None — for metrics that carry exactly one relevant
    series, where summing label sets would double count."""
    m = metrics.get(name)
    if not m:
        return None
    for label, value in m.get("series", {}).items():
        if where is not None:
            labels = _parse_label(label)
            if any(labels.get(k) != str(v) for k, v in where.items()):
                continue
        if value is not None and not isinstance(value, dict):
            return float(value)
    return None


def _roofline_from_snapshot(metrics: dict, wall: float,
                            steps: float) -> dict | None:
    """The MFU join: the train step's AOT cost-ledger gauges (written by
    Experiment at train start, obs/costmodel.py) against the measured
    wall-clock and step count of the same snapshot. The ledger rides in
    the snapshot itself — including the detected platform peak — so the
    join works offline on another machine (`cli obs` over a copied run
    dir) without re-detecting hardware it cannot see. MFU is against ONE
    chip's peak — on a data-parallel host the ratio reads as host-level
    utilization only when the whole batch fit one chip's program."""
    flops = _series_one(metrics, "deepgo_cost_flops", {"fn": "train_step"})
    if not flops or not steps or not wall:
        return None
    achieved = flops * steps / wall
    out = {
        "flops_per_step": flops,
        "achieved_flops_per_s": round(achieved),
    }
    peak = _series_one(metrics, "deepgo_cost_peak_flops_per_sec")
    bw = _series_one(metrics, "deepgo_cost_peak_hbm_bytes_per_sec")
    bytes_ = _series_one(metrics, "deepgo_cost_bytes", {"fn": "train_step"})
    out["mfu"] = round(achieved / peak, 6) if peak else None
    if bytes_:
        ai = flops / bytes_
        out["arithmetic_intensity"] = round(ai, 3)
        if peak and bw:
            out["bound"] = "compute" if ai >= peak / bw else "memory"
    hbm = _series_one(metrics, "deepgo_cost_hbm_peak_bytes",
                      {"fn": "train_step"})
    if hbm is not None:
        out["hbm_peak_bytes"] = hbm
    return out


def attribute_snapshot(metrics: dict) -> dict | None:
    """Decompose one registry snapshot's train wall-clock into buckets.

    Returns None when the snapshot carries no
    ``deepgo_train_wall_seconds_total`` (nothing trained in that process,
    so there is no denominator to attribute against)."""
    wall = _series_sum(metrics, "deepgo_train_wall_seconds_total")
    if wall <= 0:
        return None
    buckets: dict[str, dict] = {}
    attributed = 0.0
    for bucket, metric, where in _BUCKETS:
        seconds = _series_sum(metrics, metric, where)
        if bucket == "loader_wait":
            # inline h2d happens *inside* get(): carve it out so the two
            # buckets partition the loader time instead of double counting
            seconds = max(0.0, seconds - _series_sum(
                metrics, "deepgo_h2d_seconds", {"path": "inline"}))
        if seconds <= 0:
            continue
        buckets[bucket] = {
            "seconds": round(seconds, 6),
            "fraction": round(seconds / wall, 4),
        }
        attributed += seconds
    residual = wall - attributed
    steps = _series_sum(metrics, "deepgo_train_steps_total")
    samples = _series_sum(metrics, "deepgo_train_samples_total")
    out = {
        "wall_s": round(wall, 6),
        "buckets": buckets,
        "attributed_fraction": round(attributed / wall, 4),
        # residual may legitimately go slightly negative when a bucketed
        # stage ran outside the measured loop (e.g. warmup before the
        # clock started); report it signed — honesty over cosmetics
        "residual_s": round(residual, 6),
        "residual_fraction": round(residual / wall, 4),
        "useful_compute_fraction": round(
            buckets.get("compute", {}).get("seconds", 0.0) / wall, 4),
        "steps": int(steps),
    }
    if samples and wall:
        out["samples_per_sec"] = round(samples / wall, 1)
    roofline = _roofline_from_snapshot(metrics, wall, steps)
    if roofline is not None:
        out["roofline"] = roofline
    # h2d paid off the consumer's clock (uploader thread) overlaps with
    # compute — outside the decomposition, reported for completeness
    overlapped = _series_sum(metrics, "deepgo_h2d_seconds",
                             {"path": "uploader"})
    if overlapped:
        out["overlapped_h2d_s"] = round(overlapped, 6)
    return out


def attribute_run(run_dir: str) -> dict | None:
    """The per-run attribution: per-host decompositions joined across the
    elastic streams when present, else the single-host ``metrics.jsonl``
    close-time snapshot. Returns None when no snapshot exists (a run that
    never trained, or predates this instrumentation)."""
    from .report import read_events

    hosts: dict[str, dict] = {}
    for p in sorted(glob.glob(os.path.join(run_dir, "elastic-*.jsonl"))):
        snaps = [r for r in read_events(p) if r.get("kind") == "obs_snapshot"]
        if not snaps:
            continue
        att = attribute_snapshot(snaps[-1].get("metrics", {}))
        if att is not None:
            host = snaps[-1].get("host")
            if host is None:  # fall back to the stream's file id
                host = os.path.basename(p).split("-")[1].split(".")[0]
            hosts[str(host)] = att
    if not hosts:
        snaps = [r for r in
                 read_events(os.path.join(run_dir, "metrics.jsonl"))
                 if r.get("kind") == "obs_snapshot"]
        if snaps:
            att = attribute_snapshot(snaps[-1].get("metrics", {}))
            if att is not None:
                hosts["0"] = att
    if not hosts:
        return None
    out: dict = {"hosts": hosts, "num_hosts": len(hosts)}
    if len(hosts) > 1:
        # the FireCaffe-style scaling view: each host's useful-compute
        # fraction bounds how efficiently added hosts can possibly pay off
        # (time not spent computing does not scale down with more hosts)
        sps = {h: a.get("samples_per_sec") for h, a in hosts.items()}
        known = [v for v in sps.values() if v]
        fracs = [a["useful_compute_fraction"] for a in hosts.values()]
        out["scaling"] = {
            "per_host_samples_per_sec": sps,
            "aggregate_samples_per_sec": round(sum(known), 1),
            "useful_compute_fraction_min": round(min(fracs), 4),
            "useful_compute_fraction_mean": round(
                sum(fracs) / len(fracs), 4),
            "non_compute_fraction_mean": round(
                1.0 - sum(fracs) / len(fracs), 4),
        }
    return out


def format_attribution(att: dict) -> str:
    """Fixed-width rendering of ``attribute_run``'s output, one column
    per host — the table ``cli obs`` appends and a perf PR quotes."""
    hosts = att["hosts"]
    ids = sorted(hosts)
    names = [b for b, _, _ in _BUCKETS]
    lines = [f"step-time attribution ({len(ids)} host"
             f"{'s' if len(ids) != 1 else ''}):"]
    header = ["bucket"] + [f"host{h}_s (frac)" for h in ids]
    rows = []
    for bucket in names:
        if not any(bucket in hosts[h]["buckets"] for h in ids):
            continue
        row = [bucket]
        for h in ids:
            b = hosts[h]["buckets"].get(bucket)
            row.append(f"{b['seconds']:.3f} ({b['fraction']:.1%})"
                       if b else "-")
        rows.append(row)
    for label, key in (("(residual)", "residual_s"), ("wall", "wall_s")):
        row = [label]
        for h in ids:
            v = hosts[h][key]
            if key == "residual_s":
                row.append(f"{v:.3f} ({hosts[h]['residual_fraction']:.1%})")
            else:
                row.append(f"{v:.3f}")
        rows.append(row)
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    lines.append("  ".join(c.ljust(w) for c, w in zip(header, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    for h in ids:
        a = hosts[h]
        extra = (f"  host{h}: attributed {a['attributed_fraction']:.1%}, "
                 f"useful compute {a['useful_compute_fraction']:.1%}")
        if a.get("samples_per_sec"):
            extra += f", {a['samples_per_sec']:.0f} samples/sec"
        lines.append(extra)
        roof = a.get("roofline")
        if roof:
            mfu = (f"MFU {roof['mfu']:.2%}" if roof.get("mfu") is not None
                   else "MFU unknown (no platform peak)")
            line = (f"  host{h} roofline: {mfu}, "
                    f"{roof['achieved_flops_per_s'] / 1e9:.1f} GFLOP/s "
                    "achieved")
            if roof.get("bound"):
                line += f", {roof['bound']}-bound"
            lines.append(line)
    scaling = att.get("scaling")
    if scaling:
        lines.append(
            f"  fleet: {scaling['aggregate_samples_per_sec']:.0f} "
            f"samples/sec aggregate; mean useful-compute "
            f"{scaling['useful_compute_fraction_mean']:.1%} (bounds "
            f"scaling efficiency; the "
            f"{scaling['non_compute_fraction_mean']:.1%} non-compute "
            f"share does not shrink with more hosts)")
    return "\n".join(lines)
