"""The operator surface: `cli dash` (live fleet view) and `cli trend`.

``dash`` renders one screenful an operator can actually steer by:
sparklines over the declared watchlist (obs/anomaly.DEFAULT_WATCHLIST),
a per-host/per-replica fleet health grid, the active anomaly tail, and
SLO burn state — from a run directory's on-disk time-series store
(offline / tailing a live run's directory) or from live ``/metrics``
endpoints federated client-side (obs/federate.py; series history
accumulates across refreshes in a ``DashHistory``). ``--once`` renders
a single frame and ``--json`` emits the underlying dict — the CI
contract, schema documented in docs/observability.md.

``trend`` answers "what has the bench been saying all along": it joins
every committed ``BENCH_r*.json`` round (both artifact shapes — the
r01–r05 driver capture ``{n, parsed}`` and the r06+ ``{round,
captures}``) with ``BENCH_LAST_GOOD.json`` into a per-metric trajectory
table, stale captures marked, so the regression gate's verdicts finally
have a visible history.

Rendering is stdlib-only and terminal-greppable (the report.py
discipline): fixed-width tables, unicode block sparklines.
"""

from __future__ import annotations

import glob
import json
import os
import time
from collections import deque

from .anomaly import DEFAULT_WATCHLIST
from .federate import FederatedView
from .timeseries import (chunk_paths, key_field, load_samples,
                         series_from_samples, split_key)

SPARK_CHARS = "▁▂▃▄▅▆▇█"

# watch families whose value reads better in ms on the dash
_MS_FIELDS = (":p50", ":p99")


def sparkline(points: list[tuple[float, float]], width: int = 40) -> str:
    """(t, value) points -> one unicode sparkline, newest right."""
    values = [v for _, v in points][-width:]
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return SPARK_CHARS[0] * len(values)
    return "".join(
        SPARK_CHARS[min(len(SPARK_CHARS) - 1,
                        int((v - lo) / span * len(SPARK_CHARS)))]
        for v in values)


def find_store_dir(run_dir: str) -> str:
    """Where a run keeps its chunks: the run dir itself (loop/train
    runs) or a ``ts/`` subdirectory (bench runs)."""
    if chunk_paths(run_dir):
        return run_dir
    sub = os.path.join(run_dir, "ts")
    return sub if chunk_paths(sub) else run_dir


class DashHistory:
    """Client-side sample accumulation for live scrape mode: each
    refresh's federated sample appends here, so sparklines grow across
    refreshes without any server-side store."""

    def __init__(self, window: int = 240):
        self._samples: deque = deque(maxlen=window)

    def add(self, collected: dict) -> None:
        self._samples.append({"t": collected["time"],
                              "values": collected["values"]})

    def samples(self) -> list[dict]:
        return list(self._samples)


def _rate_points(points: list[tuple[float, float]]
                 ) -> list[tuple[float, float]]:
    """Cumulative counter samples -> per-second rate points (successive
    differences over the sample gap; a counter reset clamps at 0)."""
    out = []
    for (t0, v0), (t1, v1) in zip(points, points[1:]):
        dt = t1 - t0
        if dt > 0:
            out.append((t1, max(v1 - v0, 0.0) / dt))
    return out


def _watch_section(samples: list[dict], window: int) -> dict:
    tail = samples[-window:]
    out: dict = {}
    for spec in DEFAULT_WATCHLIST:
        metric = spec.metric if spec.field is None \
            else f"{spec.metric}:{spec.field}"
        # counter families sparkline as RATES (boards/sec, requests/sec
        # per tier) — a monotone cumulative count hides exactly the
        # "when did it change" signal a sparkline exists to show
        rate = spec.mode == "counter_rate"
        if rate:
            metric = f"{spec.metric}:rate"
        per_key = {k: v for k, v in series_from_samples(
            tail, spec.metric).items() if key_field(k) == spec.field}
        if not per_key:
            continue
        rows = {}
        for key, points in sorted(per_key.items()):
            if rate:
                points = _rate_points(points)
                if not points:
                    continue
            values = [v for _, v in points]
            rows[key] = {
                "points": points,
                "last": values[-1],
                "min": min(values),
                "max": max(values),
            }
        if rows:
            out[metric] = rows
    return out


def _latest_values(samples: list[dict]) -> dict:
    return dict(samples[-1]["values"]) if samples else {}


def _fleet_section(latest: dict) -> dict:
    """Per-host fleet rows from the newest sample: replica count, the
    per-replica state gauge, and the failure counters."""
    hosts: dict[str, dict] = {}

    def row(host: str) -> dict:
        return hosts.setdefault(host, {"replica_state": {},
                                       "restarts": {}})

    for key, value in latest.items():
        name, labelstr, field = split_key(key)
        if field is not None:
            continue
        labels = dict(kv.split("=", 1)
                      for kv in labelstr.split(",") if "=" in kv)
        host = labels.get("host", "local")
        if name == "deepgo_fleet_replicas_serving":
            row(host)["replicas_serving"] = value
        elif name == "deepgo_fleet_replica_state":
            row(host)["replica_state"][labels.get("replica", "?")] = value
        elif name == "deepgo_fleet_failovers_total":
            row(host)["failovers"] = row(host).get("failovers", 0) + value
        elif name == "deepgo_fleet_respawns_total":
            row(host)["respawns"] = row(host).get("respawns", 0) + value
        elif name == "deepgo_serving_restarts_total":
            row(host)["restarts"][labels.get("engine", "?")] = value
        elif name == "deepgo_loop_learner_step":
            row(host)["learner_step"] = value
    return {h: r for h, r in sorted(hosts.items())
            if r.get("replicas_serving") is not None
            or r["replica_state"] or r["restarts"]
            or r.get("learner_step") is not None}


def _slo_section(latest: dict) -> dict:
    return {key: value for key, value in sorted(latest.items())
            if split_key(key)[0] == "deepgo_slo_burn_ratio"}


def _anomaly_totals(latest: dict) -> dict:
    return {key: value for key, value in sorted(latest.items())
            if key.startswith("deepgo_anomaly_total") and value > 0}


def _store_anomalies(run_dir: str, limit: int = 20) -> list[dict]:
    from .report import read_events

    events: list[dict] = []
    for stream in ("metrics.jsonl", "loop.jsonl", "trace.jsonl"):
        events.extend(r for r in read_events(
            os.path.join(run_dir, stream)) if r.get("kind") == "anomaly")
    events.sort(key=lambda r: r.get("t") or r.get("time") or 0.0)
    return [{k: r.get(k) for k in ("metric", "series", "detector",
                                   "value", "baseline", "score", "t")}
            for r in events[-limit:]]


def collect_dash(run_dir: str | None = None, urls: dict | None = None,
                 history: DashHistory | None = None, window: int = 240,
                 view: FederatedView | None = None,
                 clock=time.time) -> dict:
    """One dash frame as data. Exactly one of ``run_dir`` (store mode)
    or ``urls`` (``{host: url}`` scrape mode) drives it; scrape mode
    needs a ``DashHistory`` to grow sparklines across calls and accepts
    a pre-built ``FederatedView`` (tests inject getters)."""
    if run_dir is not None:
        samples = load_samples(find_store_dir(run_dir))[-window:]
        data: dict = {"mode": "store", "run_dir": run_dir,
                      "hosts": {"local": {"ok": bool(samples),
                                          "kind": "store",
                                          "series": len(_latest_values(
                                              samples))}},
                      "anomalies": _store_anomalies(run_dir)}
    elif urls or view is not None:
        if view is None:
            view = FederatedView()
            for host, url in sorted((urls or {}).items()):
                view.add_scrape(host, url)
        collected = view.collect()
        if history is not None:
            history.add(collected)
            samples = history.samples()[-window:]
        else:
            samples = [{"t": collected["time"],
                        "values": collected["values"]}]
        data = {"mode": "scrape", "hosts": collected["hosts"],
                "anomalies": []}
    else:
        raise ValueError("collect_dash needs run_dir or scrape urls")
    latest = _latest_values(samples)
    data.update(
        time=clock(),
        samples=len(samples),
        watchlist=_watch_section(samples, window),
        fleet=_fleet_section(latest),
        slo=_slo_section(latest),
        anomaly_totals=_anomaly_totals(latest),
    )
    return data


# -- rendering ---------------------------------------------------------------


def _fmt(value: float, key: str = "") -> str:
    if any(key.endswith(f) for f in _MS_FIELDS):
        return f"{value * 1000:.2f}ms"
    if value == int(value) and abs(value) < 1e12:
        return str(int(value))
    return f"{value:.4g}"


def render_dash(data: dict, width: int = 40) -> str:
    lines: list[str] = []
    src = data.get("run_dir") or ",".join(
        f"{h}{'' if v.get('ok') else '(DEAD)'}"
        for h, v in sorted(data.get("hosts", {}).items()))
    lines.append(f"deepgo dash · {data['mode']} · {src} · "
                 f"{data['samples']} samples · "
                 f"{time.strftime('%H:%M:%S', time.localtime(data['time']))}")
    dead = [h for h, v in sorted(data.get("hosts", {}).items())
            if not v.get("ok")]
    if dead:
        lines.append(f"  !! unreachable: {', '.join(dead)} "
                     "(ts_scrape_failed — serving the survivors)")
    watch = data.get("watchlist", {})
    if watch:
        lines.append("")
        lines.append("watchlist:")
        label_w = max((len(k) for rows in watch.values() for k in rows),
                      default=0)
        label_w = min(label_w, 72)
        for metric, rows in watch.items():
            # rate-derived families (":rate") show per-second values
            unit = "/s" if metric.endswith(":rate") else ""
            for key, row in rows.items():
                lines.append(
                    f"  {key[:72].ljust(label_w)}  "
                    f"{sparkline(row['points'], width).ljust(width)}  "
                    f"last {_fmt(row['last'], key)}{unit}  "
                    f"[{_fmt(row['min'], key)} .. "
                    f"{_fmt(row['max'], key)}{unit}]")
    fleet = data.get("fleet", {})
    if fleet:
        lines.append("")
        lines.append("fleet health:")
        for host, row in fleet.items():
            states = row.get("replica_state", {})
            grid = " ".join(
                f"r{rid}:{'UP' if v >= 1.0 else 'DRAIN' if v > 0 else 'DOWN'}"
                for rid, v in sorted(states.items())) or "-"
            extras = []
            for k in ("replicas_serving", "failovers", "respawns",
                      "learner_step"):
                if row.get(k) is not None:
                    extras.append(f"{k}={_fmt(row[k])}")
            restarts = row.get("restarts", {})
            if restarts and sum(restarts.values()):
                extras.append("restarts=" + ",".join(
                    f"{e}:{_fmt(v)}" for e, v in sorted(restarts.items())
                    if v))
            lines.append(f"  {host}: {grid}  {' '.join(extras)}")
    anomalies = data.get("anomalies") or []
    totals = data.get("anomaly_totals") or {}
    lines.append("")
    if anomalies:
        lines.append(f"anomalies (last {len(anomalies)}):")
        for a in anomalies:
            t = a.get("t")
            stamp = time.strftime("%H:%M:%S", time.localtime(t)) \
                if t else "?"
            lines.append(
                f"  {stamp}  {a.get('detector', '?'):5s}  "
                f"{a.get('series') or a.get('metric')}  "
                f"value {_fmt(float(a.get('value') or 0.0))} vs baseline "
                f"{_fmt(float(a.get('baseline') or 0.0))} "
                f"(score {a.get('score')})")
    elif totals:
        lines.append("anomalies (counters — events live in the run dir):")
        for key, value in totals.items():
            lines.append(f"  {key}: {_fmt(value)}")
    else:
        lines.append("anomalies: none")
    slo = data.get("slo", {})
    if slo:
        lines.append("")
        lines.append("slo burn:")
        for key, value in slo.items():
            state = "BURNING" if value >= 1.0 else "ok"
            lines.append(f"  {key}: {value:.3g} ({state})")
    return "\n".join(lines)


# -- trend -------------------------------------------------------------------


def _round_captures(payload: dict) -> tuple[int | None, list[dict]]:
    """Both committed artifact shapes -> (round number, result dicts)."""
    if "captures" in payload:
        return payload.get("round"), [r for r in payload["captures"]
                                      .values() if isinstance(r, dict)]
    if "parsed" in payload:
        parsed = payload["parsed"]
        return payload.get("n"), [parsed] if isinstance(parsed, dict) else []
    return None, []


def collect_trend(root: str = ".") -> dict:
    """Every ``BENCH_r*.json`` round + the last-good table, joined into
    ``{metrics: {metric: {round: {value, stale}}}}``. Unreadable files
    are skipped with a note (history outlives format churn)."""
    rounds: list[int] = []
    metrics: dict[str, dict] = {}
    skipped: list[str] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            skipped.append(os.path.basename(path))
            continue
        rnd, captures = _round_captures(payload)
        if rnd is None or not captures:
            skipped.append(os.path.basename(path))
            continue
        rounds.append(int(rnd))
        for res in captures:
            metric = res.get("metric")
            if not metric or res.get("value") is None:
                continue
            metrics.setdefault(metric, {})[int(rnd)] = {
                "value": res["value"],
                "stale": bool(res.get("stale")),
                "unit": res.get("unit"),
                "device": res.get("device")
                or (res.get("last_good") or {}).get("device"),
            }
    last_good: dict[str, dict] = {}
    try:
        with open(os.path.join(root, "BENCH_LAST_GOOD.json")) as f:
            table = json.load(f)
        for metric, entry in table.items():
            if isinstance(entry, dict) and entry.get("value") is not None:
                last_good[metric] = {
                    "value": entry["value"],
                    "device": entry.get("device"),
                    "timestamp": entry.get("timestamp"),
                }
    except (OSError, ValueError):
        pass
    return {"rounds": sorted(set(rounds)), "metrics": metrics,
            "last_good": last_good, "skipped": skipped}


def render_trend(data: dict) -> str:
    rounds = data["rounds"]
    if not rounds and not data["last_good"]:
        return "no BENCH_r*.json rounds found"
    cols = ["metric"] + [f"r{r:02d}" for r in rounds] + ["last-good"]
    names = sorted(set(data["metrics"]) | set(data["last_good"]))
    rows = []
    for metric in names:
        per_round = data["metrics"].get(metric, {})
        row = [metric]
        for r in rounds:
            cell = per_round.get(r)
            if cell is None:
                row.append("-")
            else:
                row.append(f"{cell['value']:g}"
                           + ("*" if cell["stale"] else ""))
        lg = data["last_good"].get(metric)
        row.append(f"{lg['value']:g}" if lg else "-")
        rows.append(row)
    widths = [max(len(c), *(len(r[i]) for r in rows))
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths)),
             "  ".join("-" * w for w in widths)]
    lines.extend("  ".join(v.ljust(w) for v, w in zip(r, widths))
                 for r in rows)
    lines.append("")
    lines.append("* = stale capture (the committed last-good value, "
                 "re-quoted because that round measured nothing live)")
    if data["skipped"]:
        lines.append(f"skipped unreadable: {', '.join(data['skipped'])}")
    return "\n".join(lines)
