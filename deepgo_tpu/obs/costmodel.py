"""Device cost-model ledger: AOT roofline / MFU attribution per entrypoint.

The host-side observability stack (attribution, SLOs, tracing) says where
*wall-clock* went, but nothing in the repo could say whether the compute
bucket was anywhere near the hardware roofline, whether a bucket-ladder
rung is compute- or memory-bound, or how much HBM a config needs before
it OOMs a chip — exactly the per-resource attribution FireCaffe
(arXiv:1511.00175) argues you need before optimizing anything. This
module closes that gap with the compiler's own numbers:

  * every jitted entrypoint (each ladder rung of the serving forward,
    the train/eval steps, the sym-ensemble forward) is lowered and
    compiled **ahead of time** — ``jax.jit(...).lower(...).compile()``
    over ``jax.eval_shape`` avals, so no device buffers are allocated
    and nothing runs — and XLA's ``cost_analysis()`` FLOPs +
    bytes-accessed and ``memory_analysis()`` argument/output/temp HBM
    land in a typed :class:`CostEntry`;
  * entries publish ``deepgo_cost_*`` gauges into the PR 5 registry and
    stream versioned ``cost_ledger`` JSONL events, so the offline report
    and the live ``/cost`` exporter route both see them;
  * :meth:`CostLedger.roofline` joins the AOT ledger with *measured*
    timings (bench medians, the engine's per-bucket dispatch histogram,
    the train loop's step counters) into achieved FLOP/s, **MFU**
    against a detected per-platform peak, arithmetic intensity, and a
    compute-vs-memory-bound verdict per entrypoint;
  * ``bench.py`` folds that join into every mode's JSON as a
    ``roofline`` block, and ``bench --gate`` runs
    :func:`evaluate_mfu_floor` so a perf PR that "wins" its throughput
    gate by silently dropping MFU still fails.

Discipline (the lockcheck/xlacheck pattern): ALL analysis is AOT at
warmup/bench/train-start time — the dispatch hot path never sees this
module. Backends with no cost model (or where lowering itself fails)
degrade gracefully: the row is marked ``source="estimated"`` and carries
the analytic FLOPs estimator's number instead of crashing (CPU CI runs
the same code paths as a TPU capture).

Caveat worth stating once: XLA's ``bytes accessed`` is per-op traffic,
not a cache-aware HBM model, so arithmetic intensity is an upper bound
on memory pressure; and the analytic estimator counts SAME-padding
border taps exactly the way XLA does (a dense ``k²·cin·cout·361``
count overstates a 19x19 board's conv FLOPs by ~10%).
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from ..analysis.lockcheck import make_lock
from .registry import MetricsRegistry, get_registry

# bumped when CostEntry/event fields change shape; rides in every
# cost_ledger event and roofline block so offline joins can dispatch
VERSION = 1

# bf16 peak FLOP/s, HBM bandwidth (bytes/s), HBM capacity (bytes) per
# chip, matched by substring against jax's device_kind (public Google
# specs; v5e is what BASELINE.md targets). First match wins, so the
# more specific kinds sort first.
_TPU_PEAKS = (
    ("v6e", 918e12, 1640e9, 32 * 2**30),
    ("v6 lite", 918e12, 1640e9, 32 * 2**30),
    ("v5p", 459e12, 2765e9, 95 * 2**30),
    ("v5e", 197e12, 819e9, 16 * 2**30),
    ("v5 lite", 197e12, 819e9, 16 * 2**30),
    ("v4", 275e12, 1228e9, 32 * 2**30),
    ("v3", 123e12, 900e9, 32 * 2**30),
    ("v2", 45e12, 700e9, 16 * 2**30),
)


@dataclasses.dataclass(frozen=True)
class PlatformPeak:
    """The roofline's ceiling for one device. ``source`` says how much to
    trust it: "table" (a known TPU generation), "estimated" (the CPU
    fallback: core count x a nominal per-core FMA rate, so CI exercises
    the full join with honest quotation marks), or "unknown" (an
    unrecognized accelerator — MFU reads None rather than lying)."""

    platform: str
    device_kind: str
    flops_per_s: float | None
    hbm_bytes_per_s: float | None
    hbm_capacity_bytes: float | None
    source: str

    @property
    def ridge_flops_per_byte(self) -> float | None:
        """The roofline ridge point: arithmetic intensity above which the
        ceiling is compute, below which it is memory bandwidth."""
        if self.flops_per_s and self.hbm_bytes_per_s:
            return self.flops_per_s / self.hbm_bytes_per_s
        return None

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        ridge = self.ridge_flops_per_byte
        out["ridge_flops_per_byte"] = round(ridge, 3) if ridge else None
        return out


def detect_peak(device=None) -> PlatformPeak:
    """The per-platform peak for ``device`` (default: the first local
    device). TPU generations come from the table above; CPU gets an
    estimated peak so the MFU plumbing runs everywhere; anything else is
    "unknown" with None ceilings."""
    if device is None:
        import jax

        device = jax.local_devices()[0]
    platform = getattr(device, "platform", "unknown")
    kind = str(getattr(device, "device_kind", "") or "")
    low = kind.lower()
    for sub, flops, bw, cap in _TPU_PEAKS:
        if sub in low:
            return PlatformPeak(platform, kind, flops, bw, cap, "table")
    if platform == "cpu":
        # nominal modern x86 core: 2 FMA ports x 8 f32 lanes x 2 flops x
        # ~2 GHz = 64 GFLOP/s/core; ~3 GB/s/core sustained memory BW.
        # Deliberately coarse — the point is exercising the join, and the
        # "estimated" source tag rides every derived MFU.
        cores = os.cpu_count() or 1
        try:
            capacity = float(os.sysconf("SC_PHYS_PAGES")
                             * os.sysconf("SC_PAGE_SIZE"))
        except (ValueError, OSError, AttributeError):
            capacity = None
        return PlatformPeak(platform, kind or "cpu", cores * 64e9,
                            cores * 3e9, capacity, "estimated")
    return PlatformPeak(platform, kind, None, None, None, "unknown")


# ---------------------------------------------------------------------------
# the analytic estimator (the degraded-mode fallback and the cross-check)


def _same_taps(size: int, k: int) -> int:
    """Sum over one spatial dim's output positions of the kernel taps that
    land inside a SAME-padded input of ``size`` — the count XLA actually
    charges for border outputs (a dense k·size count overcharges them)."""
    half = k // 2
    return sum(min(i + half, size - 1) - max(i - half, 0) + 1
               for i in range(size))


def analytic_flops(cfg, batch: int = 1) -> float:
    """Forward-pass conv FLOPs (MAC x 2) of one ``policy_cnn.ModelConfig``
    for ``batch`` 19x19 boards, counting SAME-padding border taps exactly
    as XLA's cost model does. Replaces bench.py's hand-rolled
    ``_conv_flops_per_sample``, whose dense ``k²·cin·cout·361`` count
    overstated the 19x19 stack by ~10% (tests/test_costmodel.py pins this
    formula against ``cost_analysis()`` to a tolerance band). Bias adds,
    ReLUs, and the plane expansion are excluded — sub-1% at these widths.
    """
    from .. import BOARD_SIZE

    total = 0.0
    for k, c_in, c_out in cfg.layer_shapes():
        taps = _same_taps(BOARD_SIZE, k)
        total += 2.0 * c_in * c_out * taps * taps
    return batch * total


def analytic_train_flops(cfg, batch: int = 1) -> float:
    """Fused train-step estimate: forward + backward ~= 3x forward (the
    standard estimate bench.py has always quoted for ``tflops_est``)."""
    return 3.0 * analytic_flops(cfg, batch)


# ---------------------------------------------------------------------------
# the ledger


@dataclasses.dataclass(frozen=True)
class CostEntry:
    """One AOT-compiled entrypoint's resource bill. ``source="xla"`` rows
    carry the compiler's own numbers; ``"estimated"`` rows mean the
    backend returned no cost model (or lowering failed) and ``flops`` is
    the analytic estimator's count with byte/HBM fields None."""

    fn: str
    bucket: int | None
    flops: float
    bytes_accessed: float | None
    hbm_peak_bytes: float | None
    hbm_argument_bytes: float | None
    hbm_output_bytes: float | None
    hbm_temp_bytes: float | None
    compile_seconds: float
    source: str
    platform: str

    @property
    def key(self) -> str:
        return self.fn if self.bucket is None else f"{self.fn}/b{self.bucket}"

    @property
    def arithmetic_intensity(self) -> float | None:
        if self.flops and self.bytes_accessed:
            return self.flops / self.bytes_accessed
        return None

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        ai = self.arithmetic_intensity
        out["arithmetic_intensity"] = round(ai, 3) if ai else None
        return out


def _normalize_cost(cost) -> dict:
    """``Compiled.cost_analysis()`` returns a dict on new jax, a
    one-element list of dicts on older — normalize to one dict."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


class CostLedger:
    """The process ledger: measured entries + gauges + events + roofline.

    Thread-safe the repo's way (one lock via make_lock), but the intended
    use is single-threaded AOT passes at warmup/bench/train-start — the
    lock is for the exporter's ``/cost`` reads racing a slow build.
    """

    def __init__(self, registry: MetricsRegistry | None = None, sink=None,
                 device=None, clock=time.monotonic):
        self._registry = registry or get_registry()
        self._sink = sink  # anything with .write(kind, **fields), or None
        self._clock = clock
        self._lock = make_lock("obs.costmodel")
        self._entries: list[CostEntry] = []
        self._aot_seconds = 0.0
        self.peak = detect_peak(device)
        reg = self._registry
        self._g_flops = reg.gauge(
            "deepgo_cost_flops",
            "AOT cost-model FLOPs of one jitted entrypoint dispatch")
        self._g_bytes = reg.gauge(
            "deepgo_cost_bytes",
            "AOT cost-model bytes accessed per dispatch")
        self._g_hbm = reg.gauge(
            "deepgo_cost_hbm_peak_bytes",
            "AOT device-memory bill (argument+output+temp) per entrypoint")
        self._g_compile = reg.gauge(
            "deepgo_cost_compile_seconds",
            "wall time of the AOT lower+compile per entrypoint")
        self._g_peak_flops = reg.gauge(
            "deepgo_cost_peak_flops_per_sec",
            "detected per-platform peak FLOP/s (the MFU denominator)")
        self._g_peak_bw = reg.gauge(
            "deepgo_cost_peak_hbm_bytes_per_sec",
            "detected per-platform HBM bandwidth (the roofline slope)")
        if self.peak.flops_per_s:
            self._g_peak_flops.set(self.peak.flops_per_s,
                                   platform=self.peak.platform,
                                   source=self.peak.source)
        if self.peak.hbm_bytes_per_s:
            self._g_peak_bw.set(self.peak.hbm_bytes_per_s,
                                platform=self.peak.platform,
                                source=self.peak.source)

    # -- building ----------------------------------------------------------

    def measure(self, fn: str, jitted, args: tuple, kwargs: dict | None = None,
                *, bucket: int | None = None,
                analytic: float | None = None) -> CostEntry:
        """Lower + compile ``jitted`` at ``args``' avals and record its
        bill. ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct``
        pytrees (``jax.eval_shape`` output) — AOT either way: nothing
        executes, no device buffers are written.

        Never raises for backend reasons: a backend with no cost model,
        or a ``lower()``/``compile()`` failure, degrades the row to
        ``source="estimated"`` with ``analytic`` FLOPs (0.0 when no
        estimator was given — still a row, still honest)."""
        t0 = self._clock()
        flops = bytes_accessed = None
        hbm_arg = hbm_out = hbm_tmp = hbm_peak = None
        try:
            compiled = jitted.lower(*args, **(kwargs or {})).compile()
            cost = _normalize_cost(compiled.cost_analysis())
            flops = float(cost.get("flops") or 0.0) or None
            bytes_accessed = float(cost.get("bytes accessed") or 0.0) or None
            try:
                mem = compiled.memory_analysis()
            except Exception:  # noqa: BLE001 — per-backend, optional
                mem = None
            if mem is not None:
                hbm_arg = float(getattr(mem, "argument_size_in_bytes", 0.0))
                hbm_out = float(getattr(mem, "output_size_in_bytes", 0.0))
                hbm_tmp = float(getattr(mem, "temp_size_in_bytes", 0.0))
                alias = float(getattr(mem, "alias_size_in_bytes", 0.0))
                code = float(getattr(mem, "generated_code_size_in_bytes",
                                     0.0))
                # donated buffers alias outputs — they are not billed twice
                hbm_peak = max(0.0, hbm_arg + hbm_out + hbm_tmp + code
                               - alias)
        except Exception:  # noqa: BLE001 — degraded mode, never crash
            pass
        compile_seconds = self._clock() - t0
        source = "xla"
        if flops is None:
            source = "estimated"
            flops = float(analytic or 0.0)
        entry = CostEntry(
            fn=fn, bucket=bucket, flops=flops,
            bytes_accessed=bytes_accessed, hbm_peak_bytes=hbm_peak,
            hbm_argument_bytes=hbm_arg, hbm_output_bytes=hbm_out,
            hbm_temp_bytes=hbm_tmp,
            compile_seconds=round(compile_seconds, 4), source=source,
            platform=self.peak.platform)
        self.add(entry)
        return entry

    def add(self, entry: CostEntry) -> None:
        """Record one entry: ledger row + gauges + the JSONL event."""
        with self._lock:
            self._entries.append(entry)
            self._aot_seconds += entry.compile_seconds
        labels = {"fn": entry.fn}
        if entry.bucket is not None:
            labels["bucket"] = entry.bucket
        self._g_flops.set(entry.flops, **labels)
        if entry.bytes_accessed is not None:
            self._g_bytes.set(entry.bytes_accessed, **labels)
        if entry.hbm_peak_bytes is not None:
            self._g_hbm.set(entry.hbm_peak_bytes, **labels)
        self._g_compile.set(entry.compile_seconds, **labels)
        if self._sink is not None:
            try:
                # entry.to_dict() already carries the platform
                self._sink.write("cost_ledger", version=VERSION,
                                 device_kind=self.peak.device_kind,
                                 **entry.to_dict())
            except Exception:  # noqa: BLE001 — bookkeeping never fatal
                pass

    # -- reading -----------------------------------------------------------

    @property
    def entries(self) -> list[CostEntry]:
        with self._lock:
            return list(self._entries)

    def get(self, fn: str, bucket: int | None = None) -> CostEntry | None:
        with self._lock:
            for e in self._entries:
                if e.fn == fn and e.bucket == bucket:
                    return e
        return None

    @property
    def aot_seconds(self) -> float:
        with self._lock:
            return round(self._aot_seconds, 3)

    def to_dict(self) -> dict:
        return {
            "version": VERSION,
            "platform": self.peak.platform,
            "device_kind": self.peak.device_kind,
            "peak": self.peak.to_dict(),
            "aot_seconds": self.aot_seconds,
            "entries": [e.to_dict() for e in self.entries],
        }

    def roofline(self, timings: dict | None = None) -> dict:
        """The ledger joined with measured timings.

        ``timings`` maps ``(fn, bucket) -> seconds per dispatch`` (bench
        medians, per-bucket dispatch means, per-step wall). Entries with
        a timing gain ``achieved_flops_per_s`` + ``mfu``; the rest stay
        AOT-only (``mfu: None``) — the block shape is identical either
        way so gates and dashboards need no special cases."""
        timings = timings or {}
        entries = {}
        for e in self.entries:
            entries[e.key] = roofline_entry(
                e, self.peak, seconds_per_call=timings.get((e.fn, e.bucket)))
        return {
            "version": VERSION,
            "platform": self.peak.platform,
            "device_kind": self.peak.device_kind,
            "peak": self.peak.to_dict(),
            "aot_seconds": self.aot_seconds,
            "entries": entries,
        }


def roofline_entry(entry: CostEntry, peak: PlatformPeak,
                   seconds_per_call: float | None = None) -> dict:
    """One entrypoint's roofline verdict: the acceptance shape
    ``{flops, bytes, hbm_peak, achieved_flops_per_s, mfu, bound}`` plus
    the arithmetic the verdict came from."""
    ai = entry.arithmetic_intensity
    ridge = peak.ridge_flops_per_byte
    bound = None
    if ai is not None and ridge is not None:
        bound = "compute" if ai >= ridge else "memory"
    out = {
        "flops": entry.flops,
        "bytes": entry.bytes_accessed,
        "hbm_peak": entry.hbm_peak_bytes,
        "achieved_flops_per_s": None,
        "mfu": None,
        "bound": bound,
        "arithmetic_intensity": round(ai, 3) if ai else None,
        "compile_seconds": entry.compile_seconds,
        "source": entry.source,
    }
    if peak.hbm_capacity_bytes and entry.hbm_peak_bytes is not None:
        out["hbm_headroom_bytes"] = round(
            peak.hbm_capacity_bytes - entry.hbm_peak_bytes)
    if seconds_per_call and seconds_per_call > 0 and entry.flops:
        achieved = entry.flops / seconds_per_call
        out["achieved_flops_per_s"] = round(achieved)
        out["seconds_per_call"] = round(seconds_per_call, 6)
        if peak.flops_per_s:
            out["mfu"] = round(achieved / peak.flops_per_s, 4)
            # the entry's own ceiling: memory-bound entries cap below
            # peak FLOP/s at ai x bandwidth
            ceiling = peak.flops_per_s
            if ai is not None and peak.hbm_bytes_per_s:
                ceiling = min(ceiling, ai * peak.hbm_bytes_per_s)
            out["roofline_frac"] = round(achieved / ceiling, 4)
    return out


# ---------------------------------------------------------------------------
# entrypoint builders (every jitted program the repo serves or trains)


def _board_avals(batch: int, wire: str = "packed"):
    """ShapeDtypeStruct avals of one packed-record batch (no data, no
    device buffers — the whole point of the AOT pass)."""
    import jax

    if wire == "nibble":
        packed = jax.ShapeDtypeStruct((batch, 1625), np.uint8)
    else:
        packed = jax.ShapeDtypeStruct((batch, 9, 19, 19), np.uint8)
    ints = jax.ShapeDtypeStruct((batch,), np.int32)
    return packed, ints


def _params_avals(cfg):
    import functools

    import jax

    from ..models import policy_cnn

    return jax.eval_shape(functools.partial(policy_cnn.init, cfg=cfg),
                          jax.random.key(0))


def ladder_entries(ledger: CostLedger, cfg, buckets=None, forward=None,
                   fn_name: str = "policy_forward") -> list[CostEntry]:
    """One entry per bucket-ladder rung of the serving forward
    (``make_log_prob_fn`` unless ``forward`` is the engine's own jit) —
    the AOT twin of ``InferenceEngine.warmup()``'s compile sweep."""
    from ..models.serving import make_log_prob_fn
    from ..serving.buckets import DEFAULT_BUCKETS

    fn = forward if forward is not None else make_log_prob_fn(cfg)
    params = _params_avals(cfg)
    out = []
    for b in sorted(set(int(x) for x in (buckets or DEFAULT_BUCKETS))):
        packed, ints = _board_avals(b)
        out.append(ledger.measure(
            fn_name, fn, (params, packed, ints, ints), bucket=b,
            analytic=analytic_flops(cfg, b)))
    return out


def sym_entry(ledger: CostLedger, cfg, bucket: int = 8,
              fn_name: str = "sym_policy_forward") -> CostEntry:
    """The 8-fold dihedral ensemble forward (``make_sym_policy_fn``) —
    the ~8x-cost entrypoint ROADMAP item 1 wants fused; its ledger row is
    the before picture that fusion PR will be gated against."""
    from ..models.serving import make_sym_policy_fn

    fn = make_sym_policy_fn(cfg)
    packed, ints = _board_avals(bucket)
    return ledger.measure(fn_name, fn, (_params_avals(cfg), packed, ints,
                                        ints), bucket=bucket,
                          analytic=8.0 * analytic_flops(cfg, bucket))


def _quant_params_avals(cfg):
    """ShapeDtypeStruct avals of the int8 serving pytree — derived by
    tracing ``quantize_params`` over the f32 avals, so the AOT pass can
    price the quantized program without any real weights existing."""
    import jax

    from ..models.quant import quantize_params

    return jax.eval_shape(quantize_params, _params_avals(cfg))


def quant_entries(ledger: CostLedger, cfg, buckets=None, forward=None,
                  fn_name: str = "quant_forward") -> list[CostEntry]:
    """One entry per bucket-ladder rung of the int8 serving forward
    (``make_quant_log_prob_fn`` — per-output-channel symmetric int8
    weights, po2 dequant folded into the conv epilogue). Conv FLOPs are
    unchanged vs f32 (quantization moves BYTES, not multiplies), so the
    analytic fallback reuses the f32 estimator; the interesting columns
    are bytes-accessed and HBM, where the int8 weight tree is ~4x
    lighter — the ``bench --gate`` MFU floor covers these rows exactly
    like the f32 ladder's."""
    from ..models.quant import make_quant_log_prob_fn
    from ..serving.buckets import DEFAULT_BUCKETS

    fn = forward if forward is not None else make_quant_log_prob_fn(cfg)
    qparams = _quant_params_avals(cfg)
    out = []
    for b in sorted(set(int(x) for x in (buckets or DEFAULT_BUCKETS))):
        packed, ints = _board_avals(b)
        out.append(ledger.measure(
            fn_name, fn, (qparams, packed, ints, ints), bucket=b,
            analytic=analytic_flops(cfg, b)))
    return out


def fused_sym_entry(ledger: CostLedger, cfg, bucket: int = 8,
                    quant: bool = False,
                    fn_name: str | None = None) -> CostEntry:
    """The FUSED batch-stacked dihedral ensemble
    (``make_fused_sym_policy_fn``): one jitted program for all eight
    views — transform, forward, inverse map, log-sum-exp average. FLOPs
    are honestly ~8x a single forward of the same rung (the ensemble
    computes eight forwards; fusion buys dispatch economics, not
    arithmetic) — the acceptance A/B compares MEASURED per-request cost,
    and this row plus the ladder row is the denominator pair. With
    ``quant=True`` the stack runs over int8 weights (the ``int8+sym``
    serving variant)."""
    from ..models.quant import make_fused_sym_policy_fn

    if fn_name is None:
        fn_name = ("fused_sym_int8_forward" if quant
                   else "fused_sym_forward")
    fn = make_fused_sym_policy_fn(cfg, quant=quant)
    params = _quant_params_avals(cfg) if quant else _params_avals(cfg)
    packed, ints = _board_avals(bucket)
    return ledger.measure(fn_name, fn, (params, packed, ints, ints),
                          bucket=bucket,
                          analytic=8.0 * analytic_flops(cfg, bucket))


def variant_entries(ledger: CostLedger, cfg, variant: str, buckets=None,
                    forward=None) -> list[CostEntry]:
    """Price one named serving variant's forward over the ladder rungs
    (serving/variants.py): the per-rung AOT rows ``bench --mode serving
    --variant`` joins with the variant engine's dispatch histogram for
    per-rung MFU. Delegates to the f32/int8 ladder helpers; sym variants
    price the fused batch-stacked program at every rung."""
    from ..serving.buckets import DEFAULT_BUCKETS
    from ..serving.variants import variant_fn_name, variant_spec

    if variant == "f32":
        return ladder_entries(ledger, cfg, buckets=buckets, forward=forward)
    if variant == "int8":
        return quant_entries(ledger, cfg, buckets=buckets, forward=forward)
    spec = None if forward is not None else variant_spec(cfg, variant)
    fn = forward if forward is not None else spec.forward
    params = (_quant_params_avals(cfg) if "int8" in variant
              else _params_avals(cfg))
    out = []
    for b in sorted(set(int(x) for x in (buckets or DEFAULT_BUCKETS))):
        packed, ints = _board_avals(b)
        out.append(ledger.measure(
            variant_fn_name(variant), fn, (params, packed, ints, ints),
            bucket=b, analytic=8.0 * analytic_flops(cfg, b)))
    return out


# identical train-step programs are priced once per process: the
# expert-iteration tests and loops build many short Experiments over the
# same config, and re-lowering the same program would multiply the AOT
# compile cost for bit-identical numbers
_train_memo: dict[tuple, CostEntry] = {}


def train_entry(ledger: CostLedger, cfg, batch: int, optimizer=None,
                wire: str = "packed", augment: bool = False,
                fn_name: str = "train_step") -> CostEntry:
    """The fused single-step train program (``make_train_step``): one
    optimizer step at ``batch`` — FLOPs per step are identical under the
    K-step scan, so this one row prices both dispatch shapes."""
    import jax

    from ..training import make_train_step
    from ..training.optimizers import OPTIMIZERS

    memo_key = (fn_name, cfg, int(batch), wire, bool(augment),
                type(optimizer).__name__, ledger.peak.platform)
    cached = _train_memo.get(memo_key)
    if cached is not None:
        ledger.add(cached)
        return cached
    optimizer = optimizer or OPTIMIZERS["sgd"](0.01, 1e-7, 0.0)
    step = make_train_step(cfg, optimizer, augment=augment, wire=wire)
    params = _params_avals(cfg)
    opt_state = jax.eval_shape(optimizer.init, params)
    packed, ints = _board_avals(batch, wire)
    batch_avals = {"packed": packed, "player": ints, "rank": ints,
                   "target": ints}
    if augment:
        batch_avals["sym"] = ints
    entry = ledger.measure(fn_name, step, (params, opt_state, batch_avals),
                           bucket=batch,
                           analytic=analytic_train_flops(cfg, batch))
    _train_memo[memo_key] = entry
    return entry


def eval_entry(ledger: CostLedger, cfg, batch: int, wire: str = "packed",
               fn_name: str = "eval_step") -> CostEntry:
    """The validation program (``make_eval_step``)."""
    from ..training import make_eval_step

    step = make_eval_step(cfg, wire=wire)
    packed, ints = _board_avals(batch, wire)
    batch_avals = {"packed": packed, "player": ints, "rank": ints,
                   "target": ints}
    return ledger.measure(fn_name, step, (_params_avals(cfg), batch_avals),
                          bucket=batch, analytic=analytic_flops(cfg, batch))


def standard_ledger(model: str = "full", buckets=None,
                    train_batch: int = 256, sym_bucket: int = 8,
                    registry: MetricsRegistry | None = None,
                    sink=None, variants: bool = True) -> CostLedger:
    """The ``cli cost`` sweep: the serving ladder (f32 AND int8), the
    sym ensembles (legacy unfused + fused f32 + fused int8), and the
    train/eval steps of one named model config, in one ledger — so the
    MFU floor and ``cli cost`` price every program the fleet can
    actually serve, not just the f32 ladder. ``train_batch=0`` skips
    the train/eval programs (their backward-pass compile dominates the
    sweep on CPU); ``variants=False`` skips the int8/fused rows."""
    from ..models import policy_cnn

    cfg = policy_cnn.CONFIGS[model]
    ledger = CostLedger(registry=registry, sink=sink)
    ladder_entries(ledger, cfg, buckets=buckets)
    if variants:
        quant_entries(ledger, cfg, buckets=buckets)
    if sym_bucket:
        sym_entry(ledger, cfg, bucket=sym_bucket)
        if variants:
            fused_sym_entry(ledger, cfg, bucket=sym_bucket)
            fused_sym_entry(ledger, cfg, bucket=sym_bucket, quant=True)
    if train_batch:
        train_entry(ledger, cfg, train_batch)
        eval_entry(ledger, cfg, train_batch)
    return ledger


# ---------------------------------------------------------------------------
# joins against measured timings


def _parse_label(label: str) -> dict:
    if not label:
        return {}
    out = {}
    for part in label.split(","):
        k, _, v = part.partition("=")
        out[k] = v
    return out


def dispatch_seconds_by_bucket(metrics: dict,
                               engine: str | None = None) -> dict[int, float]:
    """Mean coalesced-dispatch seconds per ladder rung, from the
    ``deepgo_serving_dispatch_seconds{engine,bucket}`` histogram in a
    registry snapshot (summed across engines — a fleet's replicas share
    one jitted program, so their rungs price identically). ``engine``
    restricts the join to one engine's series — the variant bench runs
    an f32 engine and an int8 engine in one process, and each variant's
    MFU must divide ITS OWN dispatch times, not a blend."""
    m = (metrics or {}).get("deepgo_serving_dispatch_seconds") or {}
    sums: dict[int, float] = {}
    counts: dict[int, int] = {}
    for label, snap in (m.get("series") or {}).items():
        if not isinstance(snap, dict):
            continue
        labels = _parse_label(label)
        if engine is not None and labels.get("engine") != engine:
            continue
        bucket = labels.get("bucket")
        if bucket is None:
            continue
        try:
            b = int(bucket)
        except ValueError:
            continue
        sums[b] = sums.get(b, 0.0) + float(snap.get("sum") or 0.0)
        counts[b] = counts.get(b, 0) + int(snap.get("count") or 0)
    return {b: sums[b] / counts[b] for b in sums if counts.get(b)}


def evaluate_mfu_floor(fresh: dict | None, baseline: dict | None,
                       floor: float = 0.10) -> dict:
    """The MFU-floor gate: compare a fresh ``roofline`` block against the
    last-good capture's, entry by entry. An entrypoint whose MFU dropped
    by ``floor`` (relative) or more is a failure even when raw
    throughput passed — a "win" that spends hardware efficiency is a
    latent regression. Entries without MFU on either side (AOT-only
    rows, unknown platforms) are skipped, never failed: the gate
    enforces what it can measure (the ``evaluate_gate`` discipline)."""
    out: dict = {"floor": floor, "checked": 0, "failures": []}
    fresh_entries = (fresh or {}).get("entries") or {}
    base_entries = (baseline or {}).get("entries") or {}
    if not fresh_entries or not base_entries:
        out.update(verdict="skip",
                   reason="no roofline block on one side — nothing to "
                          "compare")
        return out
    for key in sorted(set(fresh_entries) & set(base_entries)):
        f_mfu = (fresh_entries[key] or {}).get("mfu")
        b_mfu = (base_entries[key] or {}).get("mfu")
        if not f_mfu or not b_mfu:
            continue
        out["checked"] += 1
        drop = (b_mfu - f_mfu) / b_mfu
        if drop >= floor:
            out["failures"].append({
                "entry": key, "mfu": f_mfu, "baseline_mfu": b_mfu,
                "drop": round(drop, 4)})
    if not out["checked"]:
        out.update(verdict="skip",
                   reason="no entrypoint carries MFU on both sides")
    elif out["failures"]:
        worst = max(out["failures"], key=lambda f: f["drop"])
        out.update(verdict="fail",
                   reason=f"{worst['entry']} MFU dropped {worst['drop']:.1%} "
                          f"({worst['baseline_mfu']:.4f} -> "
                          f"{worst['mfu']:.4f}), floor {floor:.0%} — "
                          "throughput may have passed, hardware efficiency "
                          "did not")
    else:
        out.update(verdict="pass",
                   reason=f"MFU within floor on {out['checked']} "
                          "entrypoint(s)")
    return out


# ---------------------------------------------------------------------------
# the process-wide ledger (what the exporter's /cost route serves)

_ledger_lock = make_lock("obs.costmodel.global")
_process_ledger: CostLedger | None = None


def set_cost_ledger(ledger: CostLedger | None) -> None:
    """Install (or clear) the process ledger. bench / `cli cost` /
    Experiment install theirs so a live ``--obs-port`` serves it at
    ``/cost`` next to ``/metrics``."""
    global _process_ledger
    with _ledger_lock:
        _process_ledger = ledger


def get_cost_ledger() -> CostLedger | None:
    with _ledger_lock:
        return _process_ledger


# ---------------------------------------------------------------------------
# rendering (cli cost / cli obs)


def _fmt_num(v, scale=1.0, suffix="") -> str:
    if v is None:
        return "-"
    return f"{v / scale:,.1f}{suffix}"


def format_ledger(ledger: CostLedger, timings: dict | None = None) -> str:
    """Fixed-width table of the ledger (+ roofline columns when timings
    are supplied) — what ``cli cost`` prints."""
    block = ledger.roofline(timings)
    peak = block["peak"]
    lines = [
        f"device cost ledger v{block['version']} — {block['platform']} "
        f"({block['device_kind']}), peak "
        f"{_fmt_num(peak['flops_per_s'], 1e12)} TFLOP/s @ "
        f"{_fmt_num(peak['hbm_bytes_per_s'], 1e9)} GB/s "
        f"(ridge {peak['ridge_flops_per_byte'] or '-'} FLOP/byte, "
        f"source: {peak['source']}); AOT {block['aot_seconds']}s",
    ]
    header = ["entrypoint", "GFLOPs", "MB moved", "AI", "HBM MB",
              "compile_s", "bound", "MFU", "src"]
    rows = []
    for key, e in block["entries"].items():
        rows.append([
            key,
            _fmt_num(e["flops"], 1e9),
            _fmt_num(e["bytes"], 2**20),
            f"{e['arithmetic_intensity']:.1f}"
            if e["arithmetic_intensity"] else "-",
            _fmt_num(e["hbm_peak"], 2**20),
            f"{e['compile_seconds']:.2f}",
            e["bound"] or "-",
            f"{e['mfu']:.2%}" if e["mfu"] else "-",
            e["source"],
        ])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              if rows else len(header[i]) for i in range(len(header))]
    lines.append("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)
