"""On-disk time-series store + background registry sampler.

Everything observability had until now was either *live* (the registry a
scrape sees right now) or *terminal* (the close-time ``obs_snapshot``
event). Nobody could ask "when did boards/sec start degrading" about an
always-on loop run, because no one was writing the registry down over
time. This module closes that gap with two pieces:

  * ``TimeSeriesStore`` — an append-only, chunked, on-disk history of
    flattened registry snapshots: ``ts-NNNN.jsonl`` chunk files (each a
    ``JsonlSink``), one ``ts_sample`` record per sampling tick. Disk is
    bounded two ways: chunks roll at a fixed sample count, and once the
    chunk count exceeds the retention budget the two *oldest* chunks are
    merged with power-of-two decimation (every other unpinned sample is
    dropped, survivors carry a ``ds`` generation counter) — so a
    multi-hour run keeps its full recent resolution while older history
    degrades gracefully instead of being truncated. Samples *pinned* by
    the anomaly detector (the series window around an incident) are
    never decimated. Reads are torn-line tolerant like ``report.py``:
    a store being written by a SIGKILLed process stays queryable.
  * ``TelemetrySampler`` — the background thread that snapshots the
    registry into the store on a fixed cadence (injectable clock, the
    liveness/supervisor discipline — cadence is unit-testable without
    sleeping) and fans each flattened sample out to listeners (the
    anomaly detector, obs/anomaly.py). Each tick also ``tick()``s the
    flight recorder, so the black-box ring advances at telemetry
    cadence even outside the train loop.

Series are keyed ``name{label}`` for counters/gauges and
``name{label}:field`` (``count``/``sum``/``p50``/``p99``) for
histograms — the same label-string format the registry snapshot uses,
which is what lets obs/federate.py merge scraped and stored views into
one keyspace.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import threading
import time
from collections import deque

from ..analysis.lockcheck import make_lock
from .exporter import JsonlSink
from .registry import MetricsRegistry, get_registry
from .sentinel import get_flight_recorder

_CHUNK_RE = re.compile(r"^ts-(\d+)\.jsonl$")

# histogram snapshot fields worth keeping per tick (the full bucket
# ladder stays scrape-side; the history wants the operator numbers)
HIST_FIELDS = ("count", "sum", "p50", "p99")


def series_key(name: str, label: str = "", field: str | None = None) -> str:
    """The canonical series key: ``name{label}:field`` with empty parts
    elided. ``label`` is the registry snapshot's sorted ``k=v,...``
    string."""
    key = name if not label else f"{name}{{{label}}}"
    return key if field is None else f"{key}:{field}"


def split_key(key: str) -> tuple[str, str, str | None]:
    """Inverse of ``series_key`` -> (name, label, field). The field
    suffix is whatever follows the CLOSING brace — label values may
    legitimately contain colons (``host=127.0.0.1:9090``), so parsing
    by first-colon would corrupt every federated key."""
    if "{" in key:
        name, _, rest = key.partition("{")
        label, _, tail = rest.rpartition("}")
        field = tail[1:] if tail.startswith(":") else None
        return name, label, field or None
    base, _, field = key.partition(":")
    return base, "", (field or None)


def key_base(key: str) -> str:
    """The key without its histogram-field suffix: ``name{label}``."""
    name, label, _field = split_key(key)
    return name if not label else f"{name}{{{label}}}"


def key_field(key: str) -> str | None:
    return split_key(key)[2]


def flatten_snapshot(metrics: dict) -> dict[str, float]:
    """A registry snapshot's ``metrics`` dict -> one flat
    ``{series_key: value}`` sample (what the store appends per tick)."""
    out: dict[str, float] = {}
    for name, m in metrics.items():
        kind = m.get("kind")
        for label, value in (m.get("series") or {}).items():
            if kind in ("counter", "gauge"):
                out[series_key(name, label)] = float(value)
            elif kind == "histogram" and value:
                for field in HIST_FIELDS:
                    if value.get(field) is not None:
                        out[series_key(name, label, field)] = \
                            float(value[field])
    return out


def chunk_paths(ts_dir: str) -> list[str]:
    """Every chunk file of a store directory, oldest first."""
    found = []
    for p in glob.glob(os.path.join(ts_dir, "ts-*.jsonl")):
        m = _CHUNK_RE.match(os.path.basename(p))
        if m:
            found.append((int(m.group(1)), p))
    return [p for _, p in sorted(found)]


def _read_chunk(path: str) -> list[dict]:
    """One chunk's samples, torn-line tolerant (a live writer or a
    SIGKILL mid-append must not make the store unreadable)."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "ts_sample" and "t" in rec:
                    out.append(rec)
    except OSError:
        pass
    return out


def load_samples(ts_dir: str) -> list[dict]:
    """Every sample of an on-disk store, oldest first."""
    out: list[dict] = []
    for p in chunk_paths(ts_dir):
        out.extend(_read_chunk(p))
    out.sort(key=lambda r: r["t"])
    return out


def list_keys(samples: list[dict]) -> set[str]:
    keys: set[str] = set()
    for rec in samples:
        keys.update(rec.get("values") or {})
    return keys


def key_matches(metric: str, key: str) -> bool:
    """Does ``key`` belong to the ``metric`` family? ``metric`` may be a
    bare name (matches every labelset + histogram field), a full
    ``name{label}`` base, or an exact key."""
    if key == metric:
        return True
    name, _label, _field = split_key(key)
    return name == metric or key_base(key) == metric


def series_from_samples(samples: list[dict],
                        metric: str) -> dict[str, list[tuple[float, float]]]:
    """Aligned (t, value) points per matching series key."""
    out: dict[str, list[tuple[float, float]]] = {}
    for rec in samples:
        t = rec["t"]
        for key, value in (rec.get("values") or {}).items():
            if key_matches(metric, key):
                out.setdefault(key, []).append((t, float(value)))
    return out


class TimeSeriesStore:
    """Chunked, retention-bounded, append-only sample store.

    ``chunk_samples`` bounds one ``ts-NNNN.jsonl`` file; once more than
    ``max_chunks`` chunks exist the two oldest are merged with
    power-of-two decimation. Pinned samples (``pin=True`` at append, or
    ``pin_recent()`` after the fact) always survive decimation — they
    are the anomaly windows the postmortem needs at full resolution."""

    def __init__(self, ts_dir: str, chunk_samples: int = 256,
                 max_chunks: int = 16, clock=time.time,
                 registry: MetricsRegistry | None = None,
                 recent_samples: int = 512):
        if chunk_samples < 2 or max_chunks < 2:
            raise ValueError(
                f"TimeSeriesStore needs chunk_samples >= 2 and "
                f"max_chunks >= 2, got {chunk_samples}/{max_chunks}")
        self.dir = ts_dir
        os.makedirs(ts_dir, exist_ok=True)
        self.chunk_samples = chunk_samples
        self.max_chunks = max_chunks
        self._clock = clock
        self._lock = make_lock("obs.tsstore")
        self._recent: deque = deque(maxlen=recent_samples)
        self._pinned: set[float] = set()
        self._sink: JsonlSink | None = None
        self._count = 0
        existing = chunk_paths(ts_dir)
        self._next_index = 0
        if existing:
            # resume appending into the newest chunk (a restarted loop
            # keeps one continuous history)
            tail = existing[-1]
            self._next_index = int(
                _CHUNK_RE.match(os.path.basename(tail)).group(1)) + 1
            records = _read_chunk(tail)
            if len(records) < chunk_samples:
                self._sink = JsonlSink(tail)
                self._count = len(records)
        reg = registry or get_registry()
        self._obs_samples = reg.counter(
            "deepgo_ts_samples_total",
            "telemetry samples appended to the on-disk time-series store")

    # -- write side --------------------------------------------------------

    def append(self, values: dict, t: float | None = None,
               pin: bool = False) -> float:
        """Append one flattened sample; returns its timestamp."""
        t = self._clock() if t is None else float(t)
        with self._lock:
            if self._sink is None or self._count >= self.chunk_samples:
                self._roll()
            self._sink.write("ts_sample", t=t, pin=bool(pin), values=values)
            self._count += 1
            if pin:
                self._pinned.add(t)
            self._recent.append({"t": t, "pin": bool(pin),
                                 "values": values})
        self._obs_samples.inc()
        return t

    def pin_recent(self, n: int = 8) -> int:
        """Pin the last ``n`` samples (the anomaly detector's series
        window): they survive every future decimation pass. The current
        chunk is re-stamped on disk so the pins are durable — an offline
        reader of a killed run still sees which window an anomaly
        protected. Returns how many were pinned."""
        with self._lock:
            tail = list(self._recent)[-n:]
            for rec in tail:
                self._pinned.add(rec["t"])
            self._stamp_current_chunk()
            return len(tail)

    def _stamp_current_chunk(self) -> None:
        """Rewrite the (bounded-size) current chunk with ``pin: true``
        on every pinned sample — atomic, append resumes after."""
        if self._sink is None:
            return
        from ..utils.atomicio import atomic_write

        path = self._sink.path
        records = _read_chunk(path)
        if not any(not r.get("pin") and r["t"] in self._pinned
                   for r in records):
            return
        self._sink.close()
        for rec in records:
            if rec["t"] in self._pinned:
                rec["pin"] = True
        try:
            with atomic_write(path, mode="w") as f:
                for rec in records:
                    f.write(json.dumps(rec) + "\n")
        except OSError as e:
            print(f"timeseries: pin stamp of {path} failed: {e}",
                  file=sys.stderr, flush=True)
        self._sink = JsonlSink(path)

    def _roll(self) -> None:
        if self._sink is not None:
            self._sink.close()
        path = os.path.join(self.dir, f"ts-{self._next_index:04d}.jsonl")
        self._next_index += 1
        self._sink = JsonlSink(path)
        self._count = 0
        chunks = chunk_paths(self.dir)
        if len(chunks) > self.max_chunks:
            self._downsample_oldest(chunks)

    def _downsample_oldest(self, chunks: list[str]) -> None:
        """Merge the two oldest chunks, dropping every other unpinned
        sample (power-of-two decimation): old history halves in
        resolution instead of vanishing. The merged chunk is written
        atomically over the first chunk's name; the second is removed
        only after the replacement is durable."""
        from ..utils.atomicio import atomic_write

        first, second = chunks[0], chunks[1]
        merged = _read_chunk(first) + _read_chunk(second)
        merged.sort(key=lambda r: r["t"])
        kept = []
        for i, rec in enumerate(merged):
            if rec.get("pin") or rec["t"] in self._pinned or i % 2 == 0:
                if rec.get("pin") or rec["t"] in self._pinned:
                    rec["pin"] = True  # durable across process restarts
                else:
                    rec["ds"] = int(rec.get("ds", 0)) + 1
                kept.append(rec)
        try:
            with atomic_write(first, mode="w") as f:
                for rec in kept:
                    f.write(json.dumps(rec) + "\n")
            os.remove(second)
        except OSError as e:
            # retention is bookkeeping: a full disk must degrade to
            # "kept more than budgeted", never to a crashed sampler
            print(f"timeseries: downsample of {first} failed: {e}",
                  file=sys.stderr, flush=True)

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- read side ---------------------------------------------------------

    def samples(self) -> list[dict]:
        """Everything on disk (oldest first), torn-line tolerant."""
        return load_samples(self.dir)

    def series(self, metric: str) -> dict[str, list[tuple[float, float]]]:
        return series_from_samples(self.samples(), metric)

    def keys(self) -> set[str]:
        return list_keys(self.samples())

    def recent_window(self, n: int | None = None) -> list[dict]:
        """The in-memory tail (newest last) — the flight-recorder
        ``series_window`` section and the live ``/series`` route read
        this so neither ever touches the disk on a hot path."""
        with self._lock:
            tail = list(self._recent)
        return tail if n is None else tail[-n:]

    def recent_series(self, metric: str,
                      n: int | None = None) -> dict[str, list]:
        out: dict[str, list] = {}
        for rec in self.recent_window(n):
            for key, value in (rec.get("values") or {}).items():
                if key_matches(metric, key):
                    out.setdefault(key, []).append((rec["t"], float(value)))
        return out


class TelemetrySampler:
    """Background registry sampler: snapshot -> flatten -> store +
    listeners, on a fixed cadence with an injectable clock.

    The cadence contract lives in ``maybe_sample()`` (due-time
    arithmetic over ``clock()``, fixed-rate, catch-up skips forward
    instead of bursting) so tests drive it with a fake clock and never
    sleep; the daemon thread is just ``maybe_sample`` in a short-wait
    loop."""

    def __init__(self, store: TimeSeriesStore,
                 registry: MetricsRegistry | None = None,
                 interval_s: float = 1.0, clock=time.time,
                 listeners=(), flight_tick: bool = True):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.store = store
        self.interval_s = interval_s
        self._registry = registry or get_registry()
        self._clock = clock
        self._listeners = list(listeners)
        self._flight_tick = flight_tick
        self._due: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples_taken = 0

    def add_listener(self, fn) -> None:
        """``fn(t, values)`` called after every sample lands."""
        self._listeners.append(fn)

    def sample_once(self) -> dict:
        """Take one sample now, regardless of cadence."""
        t = self._clock()
        values = flatten_snapshot(self._registry.snapshot()["metrics"])
        self.store.append(values, t=t)
        self.samples_taken += 1
        for fn in self._listeners:
            try:
                fn(t, values)
            except Exception as e:  # noqa: BLE001 — a listener must not kill the sampler
                print(f"telemetry sampler: listener {fn!r} raised: {e!r}",
                      file=sys.stderr, flush=True)
        if self._flight_tick:
            get_flight_recorder().tick()
        return values

    def maybe_sample(self) -> bool:
        """Sample iff the cadence says one is due. A long stall (a GC
        pause, a wedged snapshot) does NOT backfill missed ticks — the
        due time skips forward so the store never gets a burst of
        identical samples stamped with stale intent."""
        now = self._clock()
        if self._due is None:
            self._due = now + self.interval_s
            self.sample_once()
            return True
        if now < self._due:
            return False
        while self._due <= now:
            self._due += self.interval_s
        self.sample_once()
        return True

    def start(self) -> "TelemetrySampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="obs-ts-sampler", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        wait = min(0.05, self.interval_s / 4.0)
        while not self._stop.is_set():
            try:
                self.maybe_sample()
            except Exception as e:  # noqa: BLE001 — the sampler must outlive a dying registry
                print(f"telemetry sampler: tick failed: {e!r}",
                      file=sys.stderr, flush=True)
            self._stop.wait(wait)

    def stop(self, final_sample: bool = False) -> None:
        """Idempotent. ``final_sample`` appends one last snapshot after
        the thread is down (the close-time state, like obs_snapshot)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        if final_sample:
            self.sample_once()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# -- the process-wide live store (what the exporter's /series serves) ----

_live_store: TimeSeriesStore | None = None


def set_live_store(store: TimeSeriesStore | None) -> None:
    """Install the store the live ``/series`` route reads. One per
    process (like the cost ledger / trace recorder)."""
    global _live_store
    _live_store = store


def get_live_store() -> TimeSeriesStore | None:
    return _live_store
