"""Regression sentinel: the noise-aware bench gate + the crash flight
recorder.

Two enforcement tools that turn PR 5's passive observability into a gate
and a black box:

  * **gate** — ``evaluate_gate`` compares a fresh bench result against the
    committed last-good record (``BENCH_LAST_GOOD.json``, 104k
    boards/sec/chip) and returns a typed verdict; ``bench.py --gate``
    folds the verdict into its one-line JSON and exits nonzero on ``fail``
    so a regression breaks loudly at the developer's desk, not three PRs
    later on the pod. Noise-aware three ways: a relative threshold sits
    above measured run-to-run jitter, a warn band below it flags drift
    without failing, and when either side of the comparison recorded its
    own repeat spread (``noise_frac``) the effective threshold widens to
    cover it. Cross-device comparisons are refused (``skip``): a CPU smoke
    value regressing against a TPU capture is not a measurement.

  * **flight recorder** — a ring buffer of the last N seconds of registry
    snapshots plus the most recent completed spans, dumped atomically as
    ``flight-NNNN.json`` when an incident trips: a supervisor engine
    restart, an elastic ``HostLost``, an SLO fast burn, a telemetry
    anomaly (obs/anomaly.py — those dumps additionally carry the
    surrounding ``series_window`` section the detector registers), or an
    external watchdog about to fire (the watchdog child sends SIGUSR1 one second
    before the SIGKILL; ``install_signal_dump`` makes that signal dump —
    best-effort, since a C-level GIL-held wedge cannot run any Python,
    signal handlers included). Disabled by default (zero overhead);
    ``configure`` arms it with a dump directory. Every dump path is
    exception-proof: the postmortem must never mask the fault it records.
"""

from __future__ import annotations

import json
import os
import re
import signal
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..analysis.lockcheck import make_lock
from .registry import MetricsRegistry, get_registry
from .spans import add_span_listener, remove_span_listener

# ---- the regression gate ----

# metrics where a LOWER fresh value is the improvement; everything else
# (throughput) is higher-is-better
LOWER_IS_BETTER = frozenset({
    "policy_inference_latency_ms",
    "distributed_elastic_recovery_latency_s",
})


@dataclass(frozen=True)
class GateConfig:
    """Knobs for one gate evaluation. ``threshold`` is the relative
    regression that fails (default 10 %); ``warn_threshold`` opens a
    warn-only band below it; ``noise_multiplier`` scales a recorded
    repeat spread into extra threshold headroom (2x: the fresh value and
    the baseline each wobble by up to one spread)."""

    threshold: float = 0.10
    warn_threshold: float = 0.05
    noise_multiplier: float = 2.0
    require_device_match: bool = True


def evaluate_gate(result: dict, last_good: dict | None,
                  config: GateConfig = GateConfig()) -> dict:
    """Compare one fresh bench result against its last-good record.

    Returns ``{"verdict": "pass"|"warn"|"fail"|"skip", "reason", ...}``
    with the regression arithmetic spelled out. ``skip`` (no baseline,
    device mismatch, stale/errored fresh run) deliberately does NOT fail:
    the gate enforces regressions it can measure, never punishes missing
    data."""
    metric = result.get("metric", "?")
    out: dict = {"metric": metric, "threshold": config.threshold}
    value = result.get("value")
    if result.get("stale") or result.get("error") or not value:
        out.update(verdict="skip",
                   reason="fresh run is stale/errored — nothing measured "
                          "to gate on")
        return out
    if not last_good or not last_good.get("value"):
        out.update(verdict="skip",
                   reason=f"no last-good record for {metric}")
        return out
    base = float(last_good["value"])
    fresh_dev, base_dev = result.get("device"), last_good.get("device")
    if config.require_device_match and fresh_dev != base_dev:
        out.update(verdict="skip",
                   reason=f"device mismatch: fresh {fresh_dev!r} vs "
                          f"last-good {base_dev!r} — cross-device ratios "
                          "are not regressions")
        return out
    if metric in LOWER_IS_BETTER:
        regression = (float(value) - base) / base
    else:
        regression = (base - float(value)) / base
    noise = max(float(result.get("noise_frac") or 0.0),
                float(last_good.get("noise_frac") or 0.0))
    effective = max(config.threshold, config.noise_multiplier * noise)
    out.update(baseline=base, value=value,
               regression=round(regression, 4),
               effective_threshold=round(effective, 4),
               baseline_timestamp=last_good.get("timestamp"),
               baseline_git_sha=last_good.get("git_sha"))
    if noise:
        out["noise_frac"] = round(noise, 4)
    if regression >= effective:
        out.update(verdict="fail",
                   reason=f"{regression:.1%} regression vs last-good "
                          f"{base:g} (threshold {effective:.1%})")
    elif regression >= min(config.warn_threshold, effective):
        out.update(verdict="warn",
                   reason=f"{regression:.1%} drift vs last-good {base:g} "
                          f"(within the {effective:.1%} gate, above the "
                          f"{config.warn_threshold:.1%} warn band)")
    else:
        out.update(verdict="pass",
                   reason=f"regression {regression:+.1%} vs last-good "
                          f"{base:g} (negative = improvement), within "
                          f"the {effective:.1%} gate")
    return out


# ---- the flight recorder ----

_FLIGHT_RE = re.compile(r"^flight-(\d+)\.json$")


class FlightRecorder:
    """In-memory black box: registry snapshots + spans, dumped on fault.

    ``tick()`` (called from the train-loop window boundary and the SLO
    evaluator thread) appends one registry snapshot to a time-bounded ring;
    completed spans stream in via the spans listener hook. ``dump()``
    freezes the ring — plus one final snapshot taken at dump time — into an
    atomically-written ``flight-NNNN.json``. Everything is a no-op until
    ``configure()`` arms it, so unconfigured processes pay nothing."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 window_s: float = 120.0, max_snapshots: int = 256,
                 max_spans: int = 512, clock=time.time):
        self._registry = registry or get_registry()
        self.window_s = window_s
        self.enabled = False
        self.dump_dir: str | None = None
        self._clock = clock
        self._lock = make_lock("obs.flight")
        self._snapshots: deque = deque(maxlen=max_snapshots)
        self._spans: deque = deque(maxlen=max_spans)
        # extra dump sections: name -> zero-arg callable returning JSON-
        # serializable data, evaluated at dump time (the request-tracing
        # exemplar ring registers here, so every incident postmortem
        # carries the slow/failed request anatomy, not just aggregates)
        self._sections: dict[str, object] = {}
        self.dumps: list[str] = []

    def configure(self, dump_dir: str, window_s: float | None = None,
                  registry: MetricsRegistry | None = None) -> "FlightRecorder":
        """Arm the recorder (idempotent; re-configuring moves the dump
        directory). Registers the span listener on first arm."""
        if window_s is not None:
            self.window_s = window_s
        if registry is not None:
            self._registry = registry
        self.dump_dir = dump_dir
        if not self.enabled:
            self.enabled = True
            add_span_listener(self.record_span)
        return self

    def close(self) -> None:
        if self.enabled:
            self.enabled = False
            remove_span_listener(self.record_span)

    def add_section(self, name: str, fn) -> None:
        """Register ``fn() -> json-serializable`` to ride in every dump
        under ``name``. Re-registering a name replaces the provider;
        a raising provider is reported inline, never masks the dump."""
        with self._lock:
            self._sections[name] = fn

    def remove_section(self, name: str) -> None:
        with self._lock:
            self._sections.pop(name, None)

    def record_span(self, record: dict) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._spans.append(record)

    def tick(self) -> None:
        """Capture one registry snapshot into the ring. Cheap enough for
        a per-print-window cadence; never raises (a dying registry must
        not take the loop down with it)."""
        if not self.enabled:
            return
        try:
            snap = self._registry.snapshot()
        except Exception:  # noqa: BLE001 — observers never raise out
            return
        now = self._clock()
        with self._lock:
            self._snapshots.append((now, snap))
            self._evict(now)

    def _evict(self, now: float) -> None:
        while self._snapshots and now - self._snapshots[0][0] > self.window_s:
            self._snapshots.popleft()

    def _next_path(self) -> str:
        taken = [-1]
        try:
            for name in os.listdir(self.dump_dir):
                m = _FLIGHT_RE.match(name)
                if m:
                    taken.append(int(m.group(1)))
        except OSError:
            pass
        return os.path.join(self.dump_dir,
                            f"flight-{max(taken) + 1:04d}.json")

    def dump(self, reason: str, **detail) -> str | None:
        """Freeze the ring to disk; returns the path, or None when the
        recorder is unarmed or the write itself failed (logged — a failed
        postmortem is a fact, not an exception)."""
        if not self.enabled or not self.dump_dir:
            return None
        try:
            final = self._registry.snapshot()
        except Exception:  # noqa: BLE001
            final = None
        with self._lock:
            # ring time LAST: the registry snapshot carries its own
            # "time" (its clock), which must not mask the ring position
            snapshots = [{**s, "time": t} for t, s in self._snapshots]
            spans = list(self._spans)
            sections = dict(self._sections)
        extra = {}
        for name, fn in sections.items():
            try:
                extra[name] = fn()
            except Exception as e:  # noqa: BLE001 — reported, not raised
                extra[name] = {"error": repr(e)}
        record = {
            "kind": "flight_recorder",
            "reason": reason,
            "time": self._clock(),
            "window_s": self.window_s,
            "detail": detail,
            "snapshots": snapshots,
            "final_snapshot": final,
            "spans": spans,
            **extra,
        }
        from ..utils.atomicio import atomic_write

        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = self._next_path()
            with atomic_write(path, mode="w") as f:
                json.dump(record, f, default=str)
        except (OSError, ValueError, TypeError) as e:
            print(f"flight recorder: dump for {reason!r} failed: {e}",
                  file=sys.stderr, flush=True)
            return None
        self.dumps.append(path)
        print(f"flight recorder: {reason} -> {path} "
              f"({len(snapshots)} snapshots, {len(spans)} spans)",
              file=sys.stderr, flush=True)
        return path


_recorder: FlightRecorder | None = None


def get_flight_recorder() -> FlightRecorder:
    """The process-wide recorder every trigger site dumps through.
    Unconfigured (the default) it is inert."""
    global _recorder
    if _recorder is None:
        _recorder = FlightRecorder()
    return _recorder


def configure_flight(dump_dir: str, **kw) -> FlightRecorder:
    """Arm the process-wide recorder. ``DEEPGO_FLIGHT=0`` vetoes — an
    operator's off switch that every wiring site honors."""
    rec = get_flight_recorder()
    if os.environ.get("DEEPGO_FLIGHT") == "0":
        return rec
    return rec.configure(dump_dir, **kw)


def flight_dump(reason: str, **detail) -> str | None:
    """Trigger-site convenience: dump the process-wide recorder (no-op
    while unarmed). Used by the serving supervisor (engine restart), the
    elastic loop (HostLost), the SLO tracker (fast burn), and the
    telemetry anomaly detector (obs/anomaly.py)."""
    return get_flight_recorder().dump(reason, **detail)


def install_signal_dump(signum: int = signal.SIGUSR1) -> bool:
    """Make ``signum`` dump the flight recorder — the external watchdog's
    pre-kill grace signal (utils/watchdog.arm(flight=True)) lands here.
    Returns False when the handler cannot be installed (non-main thread)
    or a caller already owns the signal; best-effort by design."""
    def _handler(sig, frame):  # noqa: ARG001 — signal contract
        flight_dump("signal", signum=sig)

    try:
        existing = signal.getsignal(signum)
        if existing not in (signal.SIG_DFL, signal.SIG_IGN, None,
                            signal.default_int_handler) \
                and getattr(existing, "__qualname__", "") != \
                _handler.__qualname__:
            return False
        signal.signal(signum, _handler)
        return True
    except (ValueError, OSError):  # non-main thread / unsupported platform
        return False
