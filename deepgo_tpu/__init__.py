"""deepgo_tpu — a TPU-native (JAX/XLA/Pallas) Go move-prediction framework.

Re-implements the capabilities of the reference Torch7 codebase
(wqzsscc/deep-go, mounted at /root/reference) with a TPU-first design:
packed uint8 feature records expanded to model planes on-device inside the
jitted train step, a functional conv policy network, data parallelism via
``jax.sharding`` over a device mesh, and a native C++ transcription engine.
"""

__version__ = "0.1.0"

BOARD_SIZE = 19
NUM_POINTS = BOARD_SIZE * BOARD_SIZE
