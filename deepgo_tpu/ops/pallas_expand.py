"""Pallas TPU kernel for packed-record -> 37-plane expansion.

The Pallas twin of ``deepgo_tpu.ops.expand.expand_planes``: one VMEM-resident
pass per batch block computes all 37 binary planes from the 9 packed channels
(board positions flattened to the 361-lane axis, batch on sublanes). Output
layout is (B, 37, 361); ``expand_planes_pallas`` reshapes/transposes to the
model's NHWC.

This exists as an alternative backend for the input-expansion op (config
``expand_backend="pallas"``): XLA's fused elementwise code for the default
path is already excellent, so the kernel earns its place as the template for
custom TPU work (and is cross-tested against the NumPy reference in both
interpret and compiled modes), not as a default.

Note on this build environment: custom Mosaic kernels cannot be compiled
through the axon relay today (the terminal's remote-compile helper rejects
with a TPU_WORKER_HOSTNAMES error, and client-side AOT compilation hits a
libtpu version mismatch with the terminal). ``pallas_supported()`` probes
for this at runtime so callers degrade to the XLA path automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import NUM_POINTS
from ..features import NUM_PLANES, PACKED_CHANNELS


_SUPPORTED: bool | None = None


def pallas_supported() -> bool:
    """Can a Mosaic kernel actually compile on the current default backend?
    Probed once with a trivial kernel; False on CPU (interpret-only) and on
    relay setups that cannot compile custom kernels."""
    global _SUPPORTED
    if _SUPPORTED is None:
        def tiny(ref, out):
            out[:] = ref[:] + 1.0

        try:
            x = jnp.zeros((8, 128), jnp.float32)
            out = pl.pallas_call(
                tiny, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
            )(x)
            _SUPPORTED = bool((out == 1.0).all())
        except Exception:
            _SUPPORTED = False
    return _SUPPORTED


def _planes_from_packed(packed, player, rank, out_dtype):
    """The 37-plane stack from one packed block — shared by the plain
    expansion kernel and the fused symmetry-gather variant, so the plane
    grammar cannot drift between them. ``packed`` (Bb, 9, 361) int32,
    ``player``/``rank`` (Bb, 1) broadcasting over the 361 lanes."""
    stones = packed[:, 0]
    libs = packed[:, 1]
    age = packed[:, 6]
    is_black = player == 1
    lib_after = jnp.where(is_black, packed[:, 2], packed[:, 3])
    kills = jnp.where(is_black, packed[:, 4], packed[:, 5])
    ladder = jnp.where(is_black, packed[:, 7], packed[:, 8])

    empty = stones == 0
    planes = [empty, stones == player, stones == (3 - player)]
    planes += [libs == i for i in (1, 2, 3)] + [libs >= 4]
    planes += [empty & (lib_after == 0)]
    planes += [lib_after == i for i in range(1, 6)] + [lib_after >= 6]
    planes += [kills == i for i in range(1, 7)] + [kills >= 7]
    planes += [age == i for i in range(1, 6)]
    planes += [ladder >= 1]
    planes += [jnp.zeros_like(empty)]  # the reference's dead RANK base plane
    planes += [jnp.broadcast_to(rank == i, empty.shape) for i in range(1, 10)]
    return jnp.stack(planes, axis=1).astype(out_dtype)


def _expand_kernel(packed_ref, player_ref, rank_ref, out_ref):
    packed = packed_ref[:].astype(jnp.int32)  # (Bb, 9, 361)
    out_ref[:] = _planes_from_packed(packed, player_ref[:], rank_ref[:],
                                     out_ref.dtype)


def _sym_expand_kernel(perm_ref, packed_ref, player_ref, rank_ref, out_ref):
    """One (symmetry, batch-block) grid cell: gather the view's board
    permutation and expand its planes in the same VMEM pass — the fused
    transform+expand the batch-stacked dihedral ensemble dispatches
    (models/quant.make_fused_sym_policy_fn). Every packed channel is a
    spatial map and the player/rank planes are spatially constant, so
    permute-then-expand equals expand-then-permute; doing the gather
    here saves materializing the 8x packed views in HBM."""
    perm = perm_ref[:][0]                       # (361,) this view's gather
    packed = packed_ref[:].astype(jnp.int32)    # (Bb, 9, 361)
    view = jnp.take(packed, perm, axis=2)
    out_ref[:] = _planes_from_packed(view, player_ref[:], rank_ref[:],
                                     out_ref.dtype)[None]


@functools.partial(jax.jit, static_argnames=("dtype", "block", "interpret"))
def expand_planes_pallas(packed, player, rank, dtype=jnp.bfloat16, block=8,
                         interpret=False):
    """packed (B, 9, 19, 19) uint8; player, rank (B,) int32 ->
    (B, 19, 19, 37) planes, identical to ``expand_planes``."""
    b = packed.shape[0]
    assert b % block == 0, f"batch {b} must be a multiple of block {block}"
    flat = packed.reshape(b, PACKED_CHANNELS, NUM_POINTS)
    out = pl.pallas_call(
        _expand_kernel,
        grid=(b // block,),
        in_specs=[
            pl.BlockSpec((block, PACKED_CHANNELS, NUM_POINTS), lambda i: (i, 0, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, NUM_PLANES, NUM_POINTS), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, NUM_PLANES, NUM_POINTS), dtype),
        interpret=interpret,
    )(flat, player.reshape(b, 1), rank.reshape(b, 1))
    # NCHW-flat -> the model's NHWC
    return out.reshape(b, NUM_PLANES, 19, 19).transpose(0, 2, 3, 1)


@functools.partial(jax.jit, static_argnames=("symmetries", "dtype", "block",
                                             "interpret"))
def expand_planes_sym_pallas(packed, player, rank, symmetries=8,
                             dtype=jnp.bfloat16, block=8, interpret=False):
    """packed (B, 9, 19, 19) uint8; player, rank (B,) int32 ->
    (S*B, 19, 19, 37) planes: the S dihedral views of every board,
    symmetry-major (view k of board i at row ``k*B + i``) — exactly the
    layout ``make_fused_sym_policy_fn``'s XLA path produces by gathering
    views then expanding. The permutation gather rides INSIDE the
    expansion kernel (one VMEM pass per (symmetry, batch-block) grid
    cell), so the 8x packed views never hit HBM."""
    from .augment import _PERM_NP

    b = packed.shape[0]
    block = block if b % block == 0 else 1
    flat = packed.reshape(b, PACKED_CHANNELS, NUM_POINTS)
    perm = jnp.asarray(_PERM_NP[:symmetries])
    out = pl.pallas_call(
        _sym_expand_kernel,
        grid=(symmetries, b // block),
        in_specs=[
            pl.BlockSpec((1, NUM_POINTS), lambda s, i: (s, 0)),
            pl.BlockSpec((block, PACKED_CHANNELS, NUM_POINTS),
                         lambda s, i: (i, 0, 0)),
            pl.BlockSpec((block, 1), lambda s, i: (i, 0)),
            pl.BlockSpec((block, 1), lambda s, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, NUM_PLANES, NUM_POINTS),
                               lambda s, i: (s, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (symmetries, b, NUM_PLANES, NUM_POINTS), dtype),
        interpret=interpret,
    )(perm, flat, player.reshape(b, 1), rank.reshape(b, 1))
    # (S, B, C, 361) NCHW-flat -> the model's NHWC, stacked on batch
    return out.reshape(symmetries * b, NUM_PLANES, 19, 19) \
        .transpose(0, 2, 3, 1)
