"""On-device expansion of packed records into the 37 model input planes.

The reference expands per-sample on host worker threads (preprocess,
dataloader.lua:50-92), paying ~54 KB of float traffic per board. Here the
host ships the 3.2 KB packed uint8 record and this jit-friendly function
expands it on device as part of the train/inference step, where XLA fuses
the comparisons into the surrounding program. Semantics match
``deepgo_tpu.features.expand_planes_np`` exactly (tested against it).

Layout: returns NHWC (batch, 19, 19, 37) — channels-last is the natural
layout for TPU convolutions.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..features import NUM_PLANES, PACKED_CHANNELS  # noqa: F401  (doc cross-ref)


def expand_planes(packed, player, rank, dtype=jnp.bfloat16):
    """packed: (B, 9, 19, 19) uint8; player, rank: (B,) int32.

    Returns (B, 19, 19, 37) binary planes in ``dtype`` from the to-move
    player's perspective.
    """
    packed = packed.astype(jnp.int32)
    p3 = player[:, None, None]  # broadcast over the board
    stones = packed[:, 0]
    libs = packed[:, 1]
    age = packed[:, 6]
    # per-player packed channels, selected by the player to move
    is_black = p3 == 1
    lib_after = jnp.where(is_black, packed[:, 2], packed[:, 3])
    kills = jnp.where(is_black, packed[:, 4], packed[:, 5])
    ladder = jnp.where(is_black, packed[:, 7], packed[:, 8])

    empty = stones == 0
    planes = [empty, stones == p3, stones == (3 - p3)]
    planes += [libs == i for i in (1, 2, 3)] + [libs >= 4]
    planes += [empty & (lib_after == 0)]
    planes += [lib_after == i for i in range(1, 6)] + [lib_after >= 6]
    planes += [kills == i for i in range(1, 7)] + [kills >= 7]
    planes += [age == i for i in range(1, 6)]
    planes += [ladder >= 1]
    planes += [jnp.zeros_like(empty)]  # reference's dead RANK base plane
    r3 = rank[:, None, None]
    planes += [jnp.broadcast_to(r3 == i, empty.shape) for i in range(1, 10)]
    out = jnp.stack(planes, axis=-1).astype(dtype)
    assert out.shape[-1] == NUM_PLANES
    return out
