"""Device-side ops: feature expansion, model kernels, Pallas kernels."""

from .expand import expand_planes  # noqa: F401


def get_expand_fn(backend: str = "xla"):
    """Select the plane-expansion backend: "xla" (default), "pallas", or
    "auto" (pallas when the current backend can compile Mosaic kernels)."""
    if backend == "xla":
        return expand_planes
    from .pallas_expand import expand_planes_pallas, pallas_supported

    if backend == "pallas" or (backend == "auto" and pallas_supported()):
        return expand_planes_pallas
    return expand_planes
