"""Device-side ops: feature expansion, model kernels, Pallas kernels."""

from .expand import expand_planes  # noqa: F401
