"""Nibble wire format: halve host->device bytes for packed records.

End-to-end streamed training through the relay moves ~3.2 KB per position
(the (9, 19, 19) uint8 packed record) and round 3 measured it running ~10x
under the fused-step ceiling — the feed, not the chip, is the bottleneck
(RESULTS.md, round-3 verdict weak finding 3). Every packed channel's value
is only ever *compared against small constants* by the expansion
(deepgo_tpu.features.expand_planes_np): the largest threshold anywhere is
kills >= 7, so clamping values to 15 provably preserves every expanded
plane. That makes 4 bits per cell lossless for the model, and two cells
pack into one byte.

Layout: the 19-cell board rows pack pairwise along the last axis into 10
bytes (cell 18 pairs with a zero pad): (..., 19, 19) uint8 ->
(..., 19, 10) uint8, low nibble = even cell, high nibble = odd cell.
Packing happens on host (NumPy, in the loader workers); unpacking is the
first op of the jitted step (jnp), where XLA fuses the shifts into the
expansion's comparisons. The on-disk shard format is unchanged — this is
transfer encoding only.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import BOARD_SIZE

WIRE_WIDTH = (BOARD_SIZE + 1) // 2  # 10 bytes per 19-cell row


def nibble_pack_np(packed: np.ndarray) -> np.ndarray:
    """(..., 19, 19) uint8 -> (..., 19, 10) uint8 on host.

    Values clamp to 15 first; see module docstring for why that is lossless
    with respect to the expanded planes.
    """
    assert packed.shape[-1] == BOARD_SIZE and packed.dtype == np.uint8
    clamped = np.minimum(packed, 15)
    even = clamped[..., 0::2]  # cells 0,2,...,18 -> all 10 output bytes
    out = even.copy()
    out[..., : BOARD_SIZE // 2] |= clamped[..., 1::2] << 4
    return out


def nibble_unpack(wire: jnp.ndarray) -> jnp.ndarray:
    """(..., 19, 10) uint8 -> (..., 19, 19) uint8 on device (jit-friendly)."""
    lo = wire & jnp.uint8(0x0F)
    hi = wire >> jnp.uint8(4)
    # interleave lo/hi back to 20 cells, drop the pad cell
    out = jnp.stack([lo, hi], axis=-1).reshape(*wire.shape[:-1],
                                               2 * WIRE_WIDTH)
    return out[..., :BOARD_SIZE]
