"""Nibble wire format: halve host->device bytes for packed records.

End-to-end streamed training through the relay moves ~3.2 KB per position
(the (9, 19, 19) uint8 packed record) and round 3 measured it running ~10x
under the fused-step ceiling — the feed, not the chip, is the bottleneck
(RESULTS.md, round-3 verdict weak finding 3). Every packed channel's value
is only ever *compared against small constants* by the expansion
(deepgo_tpu.features.expand_planes_np): the largest threshold anywhere is
kills >= 7, so clamping values to 15 provably preserves every expanded
plane. That makes 4 bits per cell lossless for the model, and two cells
pack into one byte.

Layout: the whole (9, 19, 19) record flattens to 3,249 cells, pads one
zero cell, and ADJACENT cells pack pairwise into 1,625 bytes (low nibble
= even cell, high nibble = odd cell). Pairing adjacent bytes of the
contiguous record — rather than round 4's stride-2 slicing within each
19-cell board row — lets the host pack through a uint16 view in a few
contiguous SIMD passes; the strided version measured 137 ms per 10k
positions on the feed host, several times the memmap gather it sat
behind (round-5 feed work, VERDICT item 5). Packing happens on host
(NumPy, in the loader workers); unpacking is the first op of the jitted
step (jnp), where XLA fuses the shifts into the expansion's comparisons.
The on-disk shard format is unchanged — this is transfer encoding only.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import BOARD_SIZE
from ..features import PACKED_CHANNELS

RECORD_CELLS = PACKED_CHANNELS * BOARD_SIZE * BOARD_SIZE  # 3,249
WIRE_BYTES = (RECORD_CELLS + 1) // 2  # 1,625 per position

# the uint16 pairing trick reads the even cell from the LOW byte
assert np.little_endian, "nibble wire pack assumes a little-endian host"


# positions per packing pass: the pack makes ~4 passes over its working
# set, so chunking keeps those passes cache-resident — 10k positions in
# one monolithic pass measured 3x slower than the same work in chunks
# (84 ms vs 27 ms on the feed host; size is flat from 256 to 2048)
_PACK_CHUNK = 1024


def nibble_pack_np(packed: np.ndarray) -> np.ndarray:
    """(..., 9, 19, 19) uint8 -> (..., 1625) uint8 on host.

    Values clamp to 15 first; see module docstring for why that is
    lossless with respect to the expanded planes. The pad cell and the
    uint16 view make every pass contiguous.
    """
    assert packed.dtype == np.uint8 and packed.shape[-3:] == (
        PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE)
    lead = packed.shape[:-3]
    flat = packed.reshape(-1, RECORD_CELLS)
    n = flat.shape[0]
    out = np.empty((n, WIRE_BYTES), dtype=np.uint8)
    buf = np.empty((min(n, _PACK_CHUNK), RECORD_CELLS + 1), dtype=np.uint8)
    buf[:, RECORD_CELLS] = 0  # the pad cell, constant across chunks
    for i in range(0, n, _PACK_CHUNK):
        chunk = flat[i:i + _PACK_CHUNK]
        b = buf[:len(chunk)]
        np.minimum(chunk, 15, out=b[:, :RECORD_CELLS])
        pairs = b.view(np.uint16)  # little-endian: low byte = even cell
        out[i:i + _PACK_CHUNK] = ((pairs & 0x0F)
                                  | ((pairs >> 4) & 0xF0)).astype(np.uint8)
    return out.reshape(*lead, WIRE_BYTES)


def nibble_unpack(wire: jnp.ndarray) -> jnp.ndarray:
    """(..., 1625) uint8 -> (..., 9, 19, 19) uint8 on device (jit-friendly)."""
    lo = wire & jnp.uint8(0x0F)
    hi = wire >> jnp.uint8(4)
    flat = jnp.stack([lo, hi], axis=-1).reshape(*wire.shape[:-1],
                                                2 * WIRE_BYTES)
    return flat[..., :RECORD_CELLS].reshape(
        *wire.shape[:-1], PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE)
