"""Dihedral-group data augmentation for board positions.

The reference stubbed this out (``transform``, dataloader.lua:41-44:
"eventually this should do random rotation and reflection") — here it is
implemented, on device. Go is symmetric under the 8 board symmetries and
every packed channel is a spatial map (the rules are rotation/reflection
equivariant), so augmentation is a pure position permutation applied to both
the packed record and the move target.

The 8 permutations are precomputed host-side as an (8, 361) gather table:
``transformed_flat[p] = flat[PERM[k, p]]`` and the target moves with
``TARGET_MAP[k, target]``.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import NUM_POINTS
from ..utils import digest as _digest


def _dihedral_tables() -> tuple[np.ndarray, np.ndarray]:
    """(PERM, TARGET_MAP), each (8, 361) int32.

    Variant k = (r, f) with r quarter-turn rotations (0..3) and f horizontal
    flip (0..1), applied to the (x, y) grid as numpy rot90/fliplr. One
    implementation in ``utils/digest.py``, shared with the workload
    recorder's content digests and the position cache's canonical-key
    remap (``tests/test_cache.py`` pins all three consumers equal).
    """
    return _digest.PERMS, _digest.INV_PERMS


# the tables are baked into every compiled program that traces through
# augment_batch (jit-boundary): utils/digest freezes them at construction
# so an accidental in-place mutation raises immediately instead of
# silently serving programs compiled against the old values
_PERM_NP, _TARGET_MAP_NP = _dihedral_tables()
NUM_SYMMETRIES = _digest.NUM_SYMMETRIES


def augment_batch(packed, target, sym):
    """Apply per-sample board symmetries on device.

    packed (B, 9, 19, 19) uint8, target (B,) int32, sym (B,) int32 in [0, 8)
    -> (packed', target') with identical semantics under Go's symmetry group.
    """
    b = packed.shape[0]
    # lint: allow[jit-boundary] tables frozen read-only at module init (setflags); baked per compile by design
    perm = jnp.asarray(_PERM_NP)[sym]  # (B, 361)
    flat = packed.reshape(b, packed.shape[1], NUM_POINTS)
    out = jnp.take_along_axis(flat, perm[:, None, :], axis=2)
    new_target = jnp.take_along_axis(
        # lint: allow[jit-boundary] tables frozen read-only at module init (setflags); baked per compile by design
        jnp.asarray(_TARGET_MAP_NP)[sym], target[:, None], axis=1
    )[:, 0]
    return out.reshape(packed.shape), new_target
