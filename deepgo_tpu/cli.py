"""Command-line entry points.

  python -m deepgo_tpu.cli train       train (or resume) an experiment
  python -m deepgo_tpu.cli eval        evaluate a checkpoint on a split
  python -m deepgo_tpu.cli localtest   20-iteration CPU-size smoke run on the
                                       bundled fixture (reference localtest.lua)
  python -m deepgo_tpu.cli selfplay    engine-driven batched self-play
                                       (forwards to deepgo_tpu.selfplay;
                                       inference rides the serving engine)
  python -m deepgo_tpu.cli serve       serving-fleet daemon: N supervised
                                       replicas behind the failover router,
                                       live /healthz, verified checkpoint
                                       hot-reload
  python -m deepgo_tpu.cli loop        always-on expert-iteration service:
                                       selfplay actors -> replay buffer ->
                                       continuous learner -> arena
                                       gatekeeper, champion hot-swapped
                                       through the fleet (docs/loop.md)
  python -m deepgo_tpu.cli obs         offline observability report: join a
                                       run's metrics/trace/elastic JSONL
                                       streams into one per-stage table
  python -m deepgo_tpu.cli dash        live operator dashboard: watchlist
                                       sparklines, fleet health grid,
                                       active anomalies, SLO burn state —
                                       over a run directory's time-series
                                       store or N scraped /metrics
                                       endpoints federated into one view
  python -m deepgo_tpu.cli trend       bench trajectory: every committed
                                       BENCH_r*.json round joined with
                                       BENCH_LAST_GOOD.json into one
                                       per-metric history table
  python -m deepgo_tpu.cli trace       reconstruct one request's waterfall
                                       (from sampled trace_request
                                       exemplars) or a champion's lineage
                                       chain (games -> segments -> window
                                       -> gate -> champion) from a run
                                       directory's JSONL streams
  python -m deepgo_tpu.cli cost        AOT device cost ledger: lower +
                                       compile every jitted entrypoint of
                                       one model config and print its
                                       FLOPs / bytes / HBM bill with the
                                       platform roofline verdict
                                       (docs/observability.md)
  python -m deepgo_tpu.cli lint        invariant linter: machine-check the
                                       atomic-write/determinism/thread/
                                       typed-error disciplines and the
                                       code<->docs grammar
                                       (docs/static_analysis.md)

Config overrides are ``--set key=value`` pairs against ExperimentConfig
(the reference's prototype-override tables, experiments.lua:19-31, and its
torch.CmdLine flags, experiments/repeated.lua:6-10).
"""

from __future__ import annotations

import argparse
import dataclasses

from .experiments import Experiment, ExperimentConfig
from .utils import honor_platform_env


def parse_overrides(pairs: list[str]) -> dict:
    """``key=value`` strings -> typed config overrides.

    Dispatches on the runtime type of each field's default value (bool
    before int: bool subclasses int), not on the stringified annotation —
    so config evolution (new field types) fails loudly here instead of
    silently coercing to str."""
    defaults = ExperimentConfig()
    fields = {f.name for f in dataclasses.fields(ExperimentConfig)}
    out = {}
    for pair in pairs:
        key, _, raw = pair.partition("=")
        if key not in fields:
            raise SystemExit(f"unknown config field {key!r}; valid: {sorted(fields)}")
        default = getattr(defaults, key)
        if isinstance(default, bool):
            out[key] = raw.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            out[key] = int(raw)
        elif isinstance(default, float):
            out[key] = float(raw)
        elif isinstance(default, str):
            out[key] = raw
        else:
            raise SystemExit(
                f"field {key!r} has non-scalar type "
                f"{type(default).__name__}; set it in code, not via --set")
    return out


def _train_overrides(args) -> dict:
    overrides = parse_overrides(args.set)
    if args.tp is not None:
        overrides["tensor_parallel"] = args.tp  # shorthand for --set
    return overrides


def cmd_train(args) -> None:
    if args.resume and args.auto_resume:
        raise SystemExit("--resume and --auto-resume are mutually exclusive")
    if args.elastic:
        # elastic multi-host training (docs/robustness.md, "Distributed
        # failure domains"): every host runs this same command over a
        # shared run dir; peer death is detected via heartbeat silence,
        # survivors converge on the latest valid checkpoint, re-mesh, and
        # resume — --iters stays the TOTAL step target, so re-running the
        # identical command after any number of host losses converges on
        # the same final state
        if not args.auto_resume:
            raise SystemExit("--elastic requires --auto-resume RUN_DIR "
                             "(the shared directory hosts converge on)")
        from .parallel.elastic import ElasticConfig, run_elastic

        ecfg = ElasticConfig(
            process_id=args.process_id,
            expected_hosts=args.expected_hosts,
            heartbeat_interval_s=args.heartbeat_interval,
            miss_budget=args.miss_budget,
            straggler_factor=args.straggler_factor,
            init_deadline_s=args.init_deadline,
            step_deadline_s=args.step_deadline,
            max_recoveries=args.max_recoveries,
            reshard=args.reshard,
            coordinator=args.coordinator,
            num_processes=args.num_processes,
            obs_port=args.obs_port,
        )
        summary = run_elastic(args.auto_resume, args.iters,
                              overrides=_train_overrides(args), ecfg=ecfg)
        print(f"elastic host {ecfg.process_id} done at step "
              f"{summary['final_step']} ({summary['recoveries']} recoveries, "
              f"{summary['steps_lost_total']} steps rolled back)")
        return
    if args.auto_resume:
        # elastic restart loop: --iters is the TOTAL step target, so
        # re-running the identical command after any number of kills
        # converges on the same final state as one uninterrupted run
        # (docs/robustness.md)
        exp = Experiment.auto_resume(args.auto_resume,
                                     overrides=_train_overrides(args))
        if exp.step > 0:
            print(f"auto-resumed {exp.id} at step {exp.step}")
        else:
            print(f"experiment {exp.id} (no valid checkpoint in "
                  f"{args.auto_resume}; starting fresh)")
        iters = args.iters - exp.step
        if iters <= 0:
            print(f"step {exp.step} already meets --iters {args.iters}; "
                  f"nothing to do")
            return
    elif args.resume:
        exp = Experiment.load(args.resume)
        print(f"resumed {exp.id} at step {exp.step}")
        iters = args.iters
    else:
        config = ExperimentConfig(**_train_overrides(args))
        exp = Experiment(config)
        print(f"experiment {exp.id}")
        iters = args.iters
    exporter = None
    if args.obs_port is not None:
        # live /metrics + /healthz for the single-host run
        # (docs/observability.md); the elastic path wires its own
        # exporter with ledger-backed health inside run_elastic
        from .obs import start_exporter

        exporter = start_exporter(args.obs_port)
        exporter.add_health(
            "train", lambda: {"healthy": True, "run_id": exp.id,
                              "step": exp.step})
    tracker = None
    if args.slo:
        # declarative SLOs over the live registry: burn-rate states show
        # as gauge + slo_burn lines, and /healthz reports degraded (but
        # stays 200) while an objective burns (docs/observability.md)
        from .obs.slo import SloTracker, parse_slo_spec

        health_fn = exporter.check_health if exporter is not None else None
        tracker = SloTracker(parse_slo_spec(args.slo, health_fn=health_fn))
        tracker.start(interval_s=args.slo_interval)
        if exporter is not None:
            exporter.add_health("slo", tracker.health)
    try:
        summary = exp.run(iters)
    finally:
        if tracker is not None:
            tracker.stop()
        if exporter is not None:
            exporter.close()
    print(f"final EWMA cost {summary['final_ewma']:.4f}; "
          f"checkpoint at {exp.save()}")


def verified_reload(fleet, path: str) -> dict | None:
    """Hot-reload ``path`` through the fleet ONLY if it passes the full
    format-v2 integrity check (per-array CRC32s + whole-file digest, the
    ``find_latest_valid`` discipline). Returns the reload report, or None
    when the checkpoint is unverifiable — the fleet keeps serving its
    current weights and the operator sees why. The publish side writes
    atomically (utils.atomicio), so a rejection here means real
    corruption or a non-atomic producer, never a mid-write race."""
    import sys

    from .experiments import checkpoint as ckpt

    try:
        ckpt.verify_checkpoint(path)
    except ckpt.CheckpointError as e:
        print(f"serve: NOT reloading {e.path}: {e.reason} — fleet keeps "
              "its current weights", file=sys.stderr, flush=True)
        return None
    return fleet.reload(path)


def cmd_serve(args) -> None:
    """Long-running serving daemon: a FleetRouter of N supervised policy
    replicas with live /metrics + /healthz and checkpoint hot-reload.

    This is the operational front for the always-on loop (ROADMAP item
    4): a trainer/gatekeeper publishes a new champion checkpoint at
    ``--watch PATH``, and the daemon rolls it through the fleet one
    replica at a time — in-flight futures never drop, capacity never
    dips below N-1, nothing recompiles (docs/serving.md)."""
    import os
    import signal
    import threading
    import time as _time

    from .models import policy_cnn
    from .obs import health_from_engine, start_exporter
    from .serving import EngineConfig, fleet_policy_engine

    if args.checkpoint:
        from .models.serving import load_policy

        _, params, cfg = load_policy(args.checkpoint)
        source = args.checkpoint
    else:
        import jax

        cfg = policy_cnn.CONFIGS[args.model]
        params = policy_cnn.init(jax.random.key(0), cfg)
        source = f"random-init {args.model!r}"
    variants = tuple(v.strip() for v in args.variant.split(",") if v.strip())
    # the serve gate: lossy variants tolerance-verify against the f32
    # forward of this very checkpoint before any replica exists — a
    # failing variant refuses to serve, typed (docs/serving.md)
    from .serving import VariantToleranceError

    try:
        fleet = fleet_policy_engine(
            params, cfg, replicas=args.fleet,
            config=EngineConfig(max_wait_ms=args.max_wait_ms),
            variants=variants)
    except VariantToleranceError as e:
        raise SystemExit(
            f"serve: {e}\n(quantizing an undecided net flips tied "
            "argmaxes — gate a trained champion, or serve --variant "
            "f32; docs/serving.md \"Serving variants\")") from e
    warmed = fleet.warmup()
    assignment = [variants[i % len(variants)] for i in range(args.fleet)]
    if set(assignment) != {"f32"}:
        print(f"serve: replica variants {assignment} (hot-reload "
              "re-prepares each replica's program from the new base "
              "checkpoint)", flush=True)
    exporter = start_exporter(args.obs_port)
    exporter.add_health("fleet", health_from_engine(fleet))
    session_service = None
    if args.sessions:
        # the durable game-session service rides the same daemon: the
        # store auto-recovers (checkpoint + WAL replay) in its
        # constructor, so a restarted daemon resumes every live game
        # before the first request lands; its liveness (open sessions,
        # WAL lag, corrupt count) joins the composed /healthz verdict
        from .sessions import GameService, SessionStore

        store = SessionStore(args.sessions)
        session_service = GameService(fleet, store,
                                      search_sims=args.search_sims)
        exporter.add_health("sessions", session_service.health)
        rec = store.recovery
        print(f"serve: session store {args.sessions} — "
              f"{rec['sessions']} live game(s) resumed "
              f"(checkpoint seq {rec['checkpoint_seq']}, "
              f"{rec['wal_records_applied']} WAL record(s) replayed"
              + (f", {len(rec['corrupt'])} corrupt" if rec["corrupt"]
                 else "") + ")", flush=True)
    sampler = telem_sink = None
    if args.telemetry_dir:
        # the fleet telemetry plane on the daemon (docs/observability.md
        # "Fleet telemetry plane"): registry history + streaming anomaly
        # watchlist into --telemetry-dir; `cli dash DIR` renders it live
        # and the exporter's /series serves the recent window
        from .obs import (AnomalyDetector, JsonlSink, TelemetrySampler,
                          TimeSeriesStore, set_live_store)

        ts_store = TimeSeriesStore(args.telemetry_dir)
        telem_sink = JsonlSink(os.path.join(args.telemetry_dir,
                                            "metrics.jsonl"))
        detector = AnomalyDetector(sink=telem_sink, store=ts_store)
        sampler = TelemetrySampler(ts_store,
                                   interval_s=args.telemetry_interval,
                                   listeners=[detector.observe])
        set_live_store(ts_store)
        sampler.start()
        print(f"serve: telemetry -> {args.telemetry_dir} "
              f"(ts-NNNN.jsonl every {args.telemetry_interval:g}s; "
              "`cli dash` it)", flush=True)
    print(f"serve: fleet of {args.fleet} replica(s) over {source} "
          f"({warmed} warm shapes/replica); /healthz composes the fleet "
          "verdict", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    watched_mtime = (os.path.getmtime(args.watch)
                     if args.watch and os.path.exists(args.watch) else None)
    t_end = (None if args.duration <= 0
             else _time.monotonic() + args.duration)
    try:
        while not stop.is_set():
            if t_end is not None and _time.monotonic() >= t_end:
                break
            stop.wait(min(args.watch_interval, 0.5))
            if args.watch and os.path.exists(args.watch):
                mtime = os.path.getmtime(args.watch)
                if watched_mtime is None or mtime > watched_mtime:
                    watched_mtime = mtime
                    # verify-before-swap: a torn or corrupt publish must
                    # never reach live replicas (docs/loop.md)
                    out = verified_reload(fleet, args.watch)
                    if out is not None:
                        print(f"serve: hot-reloaded {args.watch} through "
                              f"{out['replicas']} replica(s) in "
                              f"{out['seconds']:.3f}s (zero dropped "
                              "futures, zero recompiles)", flush=True)
    finally:
        health = fleet.health()
        if session_service is not None:
            # final compacting checkpoint: the next start resumes from
            # one file instead of replaying the whole WAL tail
            session_service.close()
        if sampler is not None:
            sampler.stop(final_sample=True)
            sampler.store.close()
        if telem_sink is not None:
            telem_sink.close()
        exporter.close()
        fleet.close()
        print(f"serve: done ({health['replicas_serving']}/"
              f"{health['replicas_total']} serving, "
              f"{health['respawns']} respawns, {health['reloads']} "
              "reloads)", flush=True)


def cmd_loop(args) -> None:
    """The always-on expert-iteration service (docs/loop.md): selfplay
    actors → replay buffer → continuous learner → arena gatekeeper, all
    supervised, champion hot-swapped through the serving fleet on every
    gate pass. Supersedes the hand-sequenced tools/r5_value_loop.sh —
    one long-running process instead of stage-by-stage shell queues, and
    it survives kills: re-running the identical command over the same
    --run-dir resumes bit-exactly (learner checkpoint + read cursor)."""
    import json as _json

    from .loop import ExpertIterationLoop, LoopConfig

    config = LoopConfig(
        trace=args.trace,
        telemetry=args.telemetry,
        telemetry_interval_s=args.telemetry_interval,
        actors=args.actors,
        fleet=args.fleet,
        games_per_round=args.games_per_round,
        max_moves=args.max_moves,
        temperature=args.temperature,
        steps_per_window=args.window_steps,
        min_window_positions=args.min_positions,
        scheme=args.scheme,
        segment_games=args.segment_games,
        capacity_positions=args.buffer_capacity,
        gate_games=args.gate_games,
        gate_threshold=args.gate_threshold,
        windows=args.windows,
        duration_s=args.duration,
        stall_timeout_s=args.stall_timeout,
        max_wait_ms=args.max_wait_ms,
        seed=args.seed,
        search_sims=args.search_sims,
    )
    overrides = parse_overrides(args.set)
    overrides.setdefault("name", "loop-learner")
    lcfg = ExperimentConfig(**overrides)
    loop = ExpertIterationLoop(args.run_dir, config, lcfg,
                               seed_checkpoint=args.checkpoint)
    exporter = None
    if args.obs_port is not None:
        from .obs import health_from_engine, start_exporter

        exporter = start_exporter(args.obs_port)
        exporter.add_health("fleet", health_from_engine(loop.fleet))
        exporter.add_health(
            "loop", lambda: {"healthy": not loop.fatal,
                             **{k: v for k, v in loop.summary().items()
                                if k in ("windows_trained", "gates_passed",
                                         "games_acked")}})
    try:
        summary = loop.run()
    finally:
        if exporter is not None:
            exporter.close()
    print(_json.dumps(summary, default=str))


def cmd_obs(args) -> None:
    """Offline per-stage report over one run directory (obs/report.py)."""
    import json as _json

    from .obs.report import format_report, summarize_run

    summary = summarize_run(args.run_dir)
    if args.json:
        print(_json.dumps(summary, indent=1, default=str))
    else:
        print(format_report(summary))


def cmd_dash(args) -> None:
    """The live operator dashboard (obs/dash.py, docs/observability.md
    "Fleet telemetry plane"): one terminal frame of watchlist
    sparklines, the per-host/per-replica fleet health grid, the anomaly
    tail, and SLO burn state — refreshed in place until interrupted,
    or rendered once for CI with ``--once`` / ``--json``."""
    import json as _json
    import time as _time

    from .obs import dash as dash_mod

    urls = {}
    for i, u in enumerate(p.strip()
                          for p in (args.scrape or "").split(",")):
        if u:
            # host label: the URL's host:port (stable + readable), not
            # the list index — the same endpoint keeps the same label
            # across invocations
            urls[u.split("//")[-1].rstrip("/") or f"host{i}"] = u
    if not args.run_dir and not urls:
        raise SystemExit("dash needs RUN_DIR or --scrape URL[,URL...]")
    history = dash_mod.DashHistory(window=args.window) if urls else None
    once = args.once or args.json
    try:
        while True:
            data = dash_mod.collect_dash(
                args.run_dir or None, urls or None, history=history,
                window=args.window)
            if args.json:
                print(_json.dumps(data, indent=1, default=str))
            else:
                frame = dash_mod.render_dash(data)
                if not once:
                    # clear + home: redraw in place, no scrollback spam
                    print("\x1b[2J\x1b[H", end="")
                print(frame, flush=True)
            if once:
                return
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return
    except BrokenPipeError:
        return  # `cli dash ... | head` is legitimate operator usage


def cmd_trend(args) -> None:
    """The bench trajectory table (obs/dash.py): BENCH_r*.json rounds
    + BENCH_LAST_GOOD.json, per metric, stale captures marked — the
    history the regression gate's verdicts come from."""
    import json as _json

    from .obs import dash as dash_mod

    data = dash_mod.collect_trend(args.root)
    if args.json:
        print(_json.dumps(data, indent=1, default=str))
    else:
        print(dash_mod.render_trend(data))


def _workload_engine(args):
    """The record/replay target: a FleetRouter over --fleet supervised
    policy replicas (the cmd_serve build, without the daemon loop)."""
    from .models import policy_cnn
    from .serving import EngineConfig, fleet_policy_engine

    if getattr(args, "checkpoint", None):
        from .models.serving import load_policy

        _, params, cfg = load_policy(args.checkpoint)
    else:
        import jax

        cfg = policy_cnn.CONFIGS[args.model]
        params = policy_cnn.init(jax.random.key(0), cfg)
    fleet = fleet_policy_engine(
        params, cfg, replicas=args.fleet,
        config=EngineConfig(max_wait_ms=args.max_wait_ms))
    fleet.warmup()
    return fleet


def _pct(rate) -> str:
    return "n/a" if rate is None else f"{rate:.1%}"


def cmd_workload(args) -> None:
    """The workload observatory (obs/workload.py + serving/replay.py,
    docs/observability.md "Workload observatory"):

    ``record``   drive a live fleet with a deterministic opening-heavy
                 synthetic workload (real game openings via the go/
                 rules engine, Zipf-skewed popularity, Poisson arrivals)
                 with the workload recorder armed — producing a REAL
                 capture: per-request content digests, canonical
                 8-fold-symmetry keys, tiers, buckets, outcomes,
                 latencies, plus the deduplicated position store.
    ``analyze``  characterize a capture: unique-vs-total positions,
                 symmetry-dedup gain, popularity skew, burstiness, tier
                 mix, and the projected cache hit rate.
    ``replay``   re-serve a capture against a live fleet with open-loop
                 arrival fidelity at --speed x, reporting timeline error
                 vs the recorded arrivals next to the served outcomes."""
    import json as _json

    from .obs import workload as workload_mod

    if args.wcmd == "analyze":
        stats = workload_mod.analyze_capture(args.capture)
        if args.simulate_cache:
            from .serving import cache as cache_mod

            sizes = [int(s) for s in args.simulate_cache.split(",")
                     if s.strip()]
            reqs = workload_mod.load_capture(args.capture)["requests"]
            exact_keys = [r["digest"] for r in reqs]
            canon_keys = [r.get("canonical") or r["digest"] for r in reqs]
            stats["simulated_cache"] = {
                str(size): {
                    "exact": cache_mod.simulate(exact_keys, size),
                    "canonical": cache_mod.simulate(canon_keys, size),
                } for size in sizes}
        if args.json:
            print(_json.dumps(stats, indent=1, default=str))
        else:
            print(workload_mod.format_workload(stats))
            for size, sim in (stats.get("simulated_cache") or {}).items():
                ex, ca = sim["exact"], sim["canonical"]
                print(f"  simulated cache[{size}]: exact "
                      f"{_pct(ex['hit_rate'])} hit rate "
                      f"({ex['evictions']} evictions), canonical "
                      f"{_pct(ca['hit_rate'])} ({ca['evictions']})")
        return

    from .serving import replay as replay_mod

    if args.wcmd == "record":
        items = replay_mod.build_synthetic_requests(
            args.sgf_dir, requests=args.requests, games=args.games,
            opening_moves=args.opening_moves, rate_per_s=args.rate,
            zipf_s=args.zipf, seed=args.seed)
        recorder = workload_mod.configure_workload(args.out)
        fleet = _workload_engine(args)
        searches = []
        try:
            replayed = replay_mod.WorkloadReplayer(
                fleet, items, speed=args.speed).run()
            if args.search:
                # search-shaped traffic: PUCT searches rooted at the
                # first distinct synthetic positions, leaf evals labeled
                # search:<id> so `workload analyze` can report the
                # transposition dup ratio the tree actually produced
                from .search import Search, SearchConfig, game_from_packed

                searcher = Search(fleet, SearchConfig(
                    simulations=args.search_sims, tier="interactive"))
                seen = set()
                for item in items:
                    if len(searches) >= args.search:
                        break
                    key = item["packed"].tobytes()
                    if key in seen:
                        continue
                    seen.add(key)
                    res = searcher.search(game_from_packed(
                        item["packed"], item["player"]))
                    searches.append({"search_id": res.search_id,
                                     "move": res.move,
                                     "simulations": res.simulations,
                                     "lost": res.lost,
                                     "wave_occupancy": res.wave_occupancy})
        finally:
            fleet.close()
            recorder.drain()
            workload_mod.disable_workload()
        stats = workload_mod.analyze_capture(args.out)
        out = {"capture": args.out, "drive": replayed, "workload": stats}
        if searches:
            out["searches"] = searches
        if args.json:
            print(_json.dumps(out, indent=1, default=str))
        else:
            print(f"recorded {stats['requests']} request(s) -> {args.out}")
            print(workload_mod.format_workload(stats))
        return

    # replay: fidelity vs the recorded timeline + live outcomes
    source = workload_mod.analyze_capture(args.capture)
    trace = replay_mod.load_trace(args.capture)
    fleet = _workload_engine(args)
    try:
        report = replay_mod.WorkloadReplayer(
            fleet, trace, speed=args.speed,
            timeout_s=args.timeout or None).run()
    finally:
        fleet.close()
    report["capture"] = args.capture
    report["mix_match"] = (
        report["requests"] == source.get("requests")
        and report["tiers"] == source.get("tiers"))
    if args.json:
        print(_json.dumps(report, indent=1, default=str))
    else:
        print(f"replayed {report['requests']} request(s) from "
              f"{args.capture} at {args.speed:g}x")
        print(f"  timeline: span {report['actual_span_s']}s vs target "
              f"{report['target_span_s']}s (error "
              f"{report['span_error_frac']:.2%}, mean lag "
              f"{report['mean_lag_ms']}ms, p99 {report['p99_lag_ms']}ms) "
              f"fidelity_ok={report['fidelity_ok']}")
        print(f"  mix: tiers {report['tiers']} "
              f"(matches capture: {report['mix_match']})")
        print(f"  outcomes: {report['outcomes']}  "
              f"{report['boards_per_sec']} boards/sec")
    if not report["fidelity_ok"]:
        raise SystemExit(1)


def cmd_chaos(args) -> None:
    """Chaos campaigns against a live fleet (deepgo_tpu/chaos,
    docs/robustness.md "Chaos campaigns"):

    ``run``     build a fleet (defenses armed unless --no-defenses),
                replay an opening-heavy trace while the scenario's
                fault timeline executes, and write the graded JSON
                campaign report; exits nonzero when the grade fails.
    ``report``  re-render (and re-grade) a stored campaign report."""
    import json as _json

    from .chaos import (CampaignConfig, CampaignRunner, Scenario,
                        acceptance_scenario, brownout_scenario,
                        grade_report)

    def _render(rep: dict) -> None:
        grade = rep.get("grade", {})
        slo = rep.get("slo", {})
        answers = rep.get("answers", {})
        canary = rep.get("canary")
        print(f"scenario: {rep['scenario']['name']} "
              f"(seed {rep['scenario']['seed']}, "
              f"{len(rep['scenario']['events'])} event(s))")
        print(f"  answers: {answers.get('checked', 0)} checked, "
              f"{answers.get('wrong', 0)} wrong, "
              f"{answers.get('lost', 0)} lost")
        print(f"  slo[{slo.get('tier')}]: {slo.get('good_frac')} within "
              f"{slo.get('threshold_s')}s vs target {slo.get('target')} "
              f"(burn {slo.get('burn')}) -> "
              f"{'ok' if slo.get('ok') else 'MISSED'}")
        if canary:
            print(f"  canary: {canary['probes']} probe(s), "
                  f"{canary['failures']} failure(s), detected "
                  f"{sorted({d['replica'] for d in canary['detected']})}")
        print(f"  counters: {rep.get('counters')}")
        verdict = "PASS" if grade.get("pass") else "FAIL"
        print(f"  grade: {verdict}"
              + ("" if grade.get("pass")
                 else " — " + "; ".join(grade.get("reasons", []))))

    if args.ccmd == "report":
        with open(args.report, encoding="utf-8") as fh:
            rep = _json.load(fh)
        rep["grade"] = grade_report(rep)  # re-grade: the verdict is
        # derived from measurements, never trusted from the file
        if args.json:
            print(_json.dumps(rep, indent=1, default=str))
        else:
            _render(rep)
        if not rep["grade"]["pass"]:
            raise SystemExit(1)
        return

    from .serving import replay as replay_mod

    if args.trace:
        trace = replay_mod.load_trace(args.trace)
    else:
        trace = replay_mod.build_synthetic_requests(
            args.sgf_dir, requests=args.requests, games=args.games,
            rate_per_s=args.rate, seed=args.seed)
    span_s = ((trace[-1]["t"] - trace[0]["t"]) / args.speed
              if len(trace) > 1 else 1.0)
    if args.scenario:
        with open(args.scenario, encoding="utf-8") as fh:
            scenario = Scenario.from_dict(_json.load(fh))
    elif args.preset == "full":
        scenario = acceptance_scenario(span_s, seed=args.seed)
    else:
        scenario = brownout_scenario(span_s, seed=args.seed)
    # per-scenario SLO defaults mirror the robustness contract
    # (docs/robustness.md): a pure brownout is the hedging/ejection A/B
    # axis and is graded tight; a kill- or corruption-bearing scenario
    # is an integrity campaign whose latency legitimately spikes around
    # the failover/eject/respawn, so it is graded on survival unless
    # the caller pins the bar explicitly
    hard = any(e.kind in ("kill", "corrupt") for e in scenario.events)
    slo_threshold = (args.slo_threshold if args.slo_threshold is not None
                     else (2.0 if hard else 0.15))
    slo_target = (args.slo_target if args.slo_target is not None
                  else (0.5 if hard else 0.95))
    # the canary is armed only when the scenario can corrupt: probes
    # submit straight to a target replica (no hedging), so against a
    # pure brownout every probe through the slow replica is a
    # guaranteed SLO-histogram miss — measurement pollution, not a
    # defense (bench --mode chaos splits its arms the same way)
    canary = (not args.no_defenses) and any(
        e.kind == "corrupt" for e in scenario.events)
    fleet = _chaos_fleet(args)
    try:
        report = CampaignRunner(
            fleet, trace, scenario,
            CampaignConfig(slo_threshold_s=slo_threshold,
                           slo_target=slo_target, speed=args.speed,
                           canary=canary)
        ).run(report_path=args.out)
    finally:
        fleet.close()
    if args.json:
        print(_json.dumps(report, indent=1, default=str))
    else:
        _render(report)
        if args.out:
            print(f"report -> {args.out}")
    if not report["grade"]["pass"]:
        raise SystemExit(1)


def _chaos_fleet(args):
    """The campaign target: a FleetRouter of supervised policy replicas
    with ``max_restarts=0`` (a dispatcher kill crosses into the FLEET
    failure domain) and the gray-failure defense posture armed unless
    --no-defenses (the A/B's control arm)."""
    from .chaos import defended_config
    from .models import policy_cnn
    from .serving import (EngineConfig, FleetConfig, SupervisorConfig,
                          fleet_policy_engine)

    if getattr(args, "checkpoint", None):
        from .models.serving import load_policy

        _, params, cfg = load_policy(args.checkpoint)
    else:
        import jax

        cfg = policy_cnn.CONFIGS[args.model]
        params = policy_cnn.init(jax.random.key(0), cfg)
    # fast respawn + a short bucket ladder, as in bench --mode chaos:
    # an ejected/killed replica must rebuild within the short smoke
    # trace, and its warmup must not re-execute 128/512-wide rungs —
    # on CPU those monopolize the shared XLA intra-op pool for ~1s,
    # starving the survivor, and the SLO verdict ends up measuring the
    # rebuild instead of the defenses
    base = FleetConfig(respawn_base_s=0.01, respawn_cap_s=0.05)
    fleet = fleet_policy_engine(
        params, cfg, replicas=args.fleet,
        config=EngineConfig(buckets=(1, 8, 32),
                            max_wait_ms=args.max_wait_ms),
        fleet=base if args.no_defenses else defended_config(base),
        supervisor=SupervisorConfig(max_restarts=0))
    fleet.warmup()
    return fleet


def cmd_trace(args) -> None:
    """Request waterfall / lineage chain reconstruction (obs/tracing.py).

    ``ID`` is a trace-id prefix (from the `cli obs` exemplar table, a
    `trace_request` record, or a flight dump), ``champion`` / a window
    number / a params-digest prefix for the provenance chain. With no ID,
    lists what the run directory has to offer."""
    import json as _json

    from .obs.tracing import load_trace_events, trace_report

    if args.id is None:
        events = load_trace_events(args.run_dir)
        print(trace_report(args.run_dir, ""))
        if not events["requests"] and not events["lineage"]:
            raise SystemExit(1)
        return
    if args.json:
        from .obs.tracing import build_lineage, find_request

        events = load_trace_events(args.run_dir)
        record = find_request(events, args.id)
        out = record if record is not None \
            else build_lineage(events, args.id)
        if out is None:
            raise SystemExit(f"no trace or lineage matches {args.id!r} "
                             f"in {args.run_dir}")
        print(_json.dumps(out, indent=1, default=str))
        return
    print(trace_report(args.run_dir, args.id))


def cmd_cost(args) -> None:
    """The AOT device cost ledger (obs/costmodel.py): every jitted
    entrypoint of one model config — the serving bucket ladder, the
    8-fold sym ensemble, the fused train/eval steps — lowered and
    compiled ahead of time, with XLA's FLOPs / bytes-accessed / HBM bill
    and the compute-vs-memory roofline verdict per entrypoint. Nothing
    executes (``jax.eval_shape`` avals in, ``cost_analysis()`` out), so
    the sweep allocates no device buffers. Backends without a cost model
    degrade to analytic-estimator rows marked ``estimated``."""
    import json as _json

    from .obs import costmodel

    buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    ledger = costmodel.standard_ledger(
        model=args.model, buckets=buckets, train_batch=args.train_batch,
        sym_bucket=args.sym_bucket)
    costmodel.set_cost_ledger(ledger)
    if args.json:
        print(_json.dumps(ledger.roofline(), indent=1, default=str))
    else:
        print(costmodel.format_ledger(ledger))


def cmd_lint(args) -> None:
    """Invariant linter + grammar drift checker (docs/static_analysis.md).

    Exits non-zero on any strict finding: raw durable writes outside
    utils/atomicio, nondeterminism in step-indexed/replay modules,
    anonymous/unsupervised threads, service-layer asserts, and code<->docs
    grammar drift. ``tools/`` is linted at warn level only (legacy
    one-offs; the exemption is checked in at analysis/config.py)."""
    import json as _json

    from .analysis.linter import format_report, run_lint

    findings = run_lint(args.root, paths=args.paths or None,
                        grammar=not args.no_grammar)
    strict = sum(1 for f in findings if f.level == "strict")
    if args.json:
        print(_json.dumps({
            "findings": [f.to_dict() for f in findings],
            "strict": strict,
            "warn": len(findings) - strict,
        }, indent=1))
    else:
        print(format_report(findings))
    if strict:
        raise SystemExit(1)


def cmd_eval(args) -> None:
    exp = Experiment.load(args.checkpoint)
    result = exp.evaluate(split=args.split, limit=args.limit)
    print(f"{args.split}: cost={result['cost']:.4f} "
          f"accuracy={result['accuracy']:.4f} n={result['n']}")


def cmd_localtest(args) -> None:
    """End-to-end smoke on the bundled data (reference localtest.lua:1-11)."""
    defaults = dict(
        name="localtest",
        batch_size=16,
        channels=32,
        validation_size=64,
        validation_interval=20,
        loader_threads=1,
        data_parallel=1,
    )
    # --set wins over the smoke-run defaults (the reference's override
    # tables work the same way, localtest.lua:4-10)
    defaults.update(parse_overrides(args.set))
    config = ExperimentConfig(**defaults)
    exp = Experiment(config)
    summary = exp.run(args.iters)
    print(f"localtest done: final EWMA {summary['final_ewma']:.4f}")


def main(argv=None) -> None:
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if argv[:1] == ["selfplay"]:
        # plain forwarding, before argparse: REMAINDER cannot capture
        # leading --flags, and the selfplay driver owns its own help
        from . import selfplay

        honor_platform_env()
        return selfplay.main(argv[1:])

    ap = argparse.ArgumentParser(prog="deepgo_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("train", help="train or resume an experiment")
    p.add_argument("--iters", type=int, required=True,
                   help="steps to run (TOTAL step target with --auto-resume)")
    p.add_argument("--resume", help="checkpoint path to continue from")
    p.add_argument("--auto-resume", metavar="RUN_DIR",
                   help="continue from the newest valid checkpoint in "
                        "RUN_DIR (corrupt ones are skipped), or start a "
                        "fresh run there; --set applies to fresh starts "
                        "only")
    p.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE")
    p.add_argument("--elastic", action="store_true",
                   help="multi-host elastic mode (requires --auto-resume): "
                        "heartbeat liveness, deadline-wrapped bootstrap, "
                        "checkpoint-coordinated re-mesh recovery on host "
                        "loss over the composed dp×tp×ZeRO mesh; combine "
                        "with --tp/--reshard for tp-crossing recovery "
                        "(docs/robustness.md)")
    p.add_argument("--tp", type=int, default=None, metavar="N",
                   help="tensor-parallel factor of the mesh (shorthand for "
                        "--set tensor_parallel=N): conv channels shard "
                        "over the \"model\" axis, composing with data "
                        "parallelism and ZeRO optimizer-state sharding")
    p.add_argument("--reshard", action="store_true",
                   help="(--elastic) let recovery SHRINK the tp factor "
                        "with the surviving fraction and reshard the "
                        "checkpoint state into the new dp×tp×ZeRO layout "
                        "(parallel/reshard.py); without it a re-mesh "
                        "keeps the stored tp")
    p.add_argument("--process-id", type=int, default=0,
                   help="(--elastic) this host's id in [0, expected-hosts)")
    p.add_argument("--expected-hosts", type=int, default=1,
                   help="(--elastic) fleet size whose liveness to watch")
    p.add_argument("--heartbeat-interval", type=float, default=1.0,
                   metavar="S", help="(--elastic) expected beat cadence")
    p.add_argument("--miss-budget", type=int, default=3,
                   help="(--elastic) beats of silence before a peer is "
                        "declared lost (budget = interval x this)")
    p.add_argument("--straggler-factor", type=float, default=3.0,
                   help="(--elastic) flag hosts slower than this multiple "
                        "of the fleet median step latency")
    p.add_argument("--init-deadline", type=float, default=120.0, metavar="S",
                   help="(--elastic) external-watchdog fuse around the "
                        "distributed bootstrap (0 disables)")
    p.add_argument("--step-deadline", type=float, default=0.0, metavar="S",
                   help="(--elastic) external-watchdog fuse around the "
                        "FIRST sharded step (compile + first collective; "
                        "0 disables)")
    p.add_argument("--max-recoveries", type=int, default=8,
                   help="(--elastic) bounded recovery budget before a host "
                        "loss is surfaced instead of absorbed")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="(--elastic) jax.distributed coordinator address "
                        "(omit on single-host / simulated fleets)")
    p.add_argument("--num-processes", type=int, default=None,
                   help="(--elastic) jax.distributed process count")
    p.add_argument("--obs-port", type=int, default=None, metavar="PORT",
                   help="serve live /metrics (Prometheus text) and "
                        "/healthz on this port for the duration of the "
                        "run (0 = ephemeral port, printed at startup; "
                        "docs/observability.md)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="declarative SLOs evaluated live against the "
                        "metrics registry with multi-window burn-rate "
                        "logic, e.g. 'train_sps=1000,dispatch_ms=50@0.999"
                        ",availability=0.999' (availability needs "
                        "--obs-port). Burns emit slo_burn events, feed "
                        "the deepgo_slo_burn_ratio gauge, and mark "
                        "/healthz degraded without failing it "
                        "(docs/observability.md; plain train path — the "
                        "elastic loop owns its own health wiring)")
    p.add_argument("--slo-interval", type=float, default=2.0, metavar="S",
                   help="SLO evaluation cadence in seconds (default 2)")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("eval", help="evaluate a checkpoint")
    p.add_argument("--checkpoint", required=True)
    p.add_argument("--split", default="test")
    p.add_argument("--limit", type=int)
    p.set_defaults(fn=cmd_eval)

    p = sub.add_parser("localtest", help="bundled-data smoke run")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE")
    p.set_defaults(fn=cmd_localtest)

    p = sub.add_parser("serve", help="serving-fleet daemon: N supervised "
                                     "replicas behind the failover router "
                                     "with live /metrics + /healthz and "
                                     "checkpoint hot-reload "
                                     "(docs/serving.md)")
    p.add_argument("--fleet", type=int, default=2, metavar="N",
                   help="replica count (default 2)")
    p.add_argument("--checkpoint",
                   help="policy checkpoint to serve (default: random init)")
    p.add_argument("--model", default="small",
                   help="model config for random init (no --checkpoint)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="per-replica dispatcher coalescing window")
    p.add_argument("--variant", default="f32", metavar="CSV",
                   help="serving variant(s) assigned round-robin per "
                        "replica: f32 | int8 | sym | int8+sym "
                        "(e.g. 'f32,int8' A/Bs the quantized champion "
                        "against full precision live; lossy variants "
                        "are tolerance-gated before serving — "
                        "docs/serving.md)")
    p.add_argument("--obs-port", type=int, default=0, metavar="PORT",
                   help="port for /metrics + /healthz (0 = ephemeral, "
                        "printed at startup)")
    p.add_argument("--watch", metavar="PATH",
                   help="poll this checkpoint path and hot-reload the "
                        "fleet (one replica at a time, no dropped "
                        "futures) whenever its mtime advances — the "
                        "champion-publish hook for the expert-iteration "
                        "loop")
    p.add_argument("--watch-interval", type=float, default=5.0, metavar="S",
                   help="checkpoint poll cadence (default 5s)")
    p.add_argument("--telemetry-dir", metavar="DIR",
                   help="arm the fleet telemetry plane: append the "
                        "registry to DIR/ts-NNNN.jsonl on a fixed "
                        "cadence and run the streaming anomaly "
                        "watchlist over it (anomaly events -> "
                        "DIR/metrics.jsonl; `cli dash DIR` renders it "
                        "live — docs/observability.md)")
    p.add_argument("--telemetry-interval", type=float, default=1.0,
                   metavar="S",
                   help="telemetry sampling cadence (default 1s)")
    p.add_argument("--duration", type=float, default=0.0, metavar="S",
                   help="serve for S seconds then exit (0 = until "
                        "SIGINT/SIGTERM)")
    p.add_argument("--sessions", metavar="DIR",
                   help="host the durable game-session service over DIR "
                        "(WAL + checkpoints; crashed/killed daemons "
                        "resume every live game on restart) next to "
                        "/metrics + /healthz — session liveness (open "
                        "sessions, WAL lag) joins the composed health "
                        "verdict (docs/robustness.md)")
    p.add_argument("--search-sims", type=int, default=0, metavar="N",
                   help="engine replies in --sessions games run an "
                        "N-simulation PUCT search over the fleet "
                        "instead of one policy argmax (0 = off; "
                        "docs/search.md)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("loop", help="always-on expert-iteration service: "
                                    "selfplay actors → replay buffer → "
                                    "continuous learner → arena gatekeeper, "
                                    "champion hot-swapped through the "
                                    "serving fleet (docs/loop.md; "
                                    "supersedes tools/r5_value_loop.sh)")
    p.add_argument("--run-dir", default="runs/loop",
                   help="the loop's durable home (buffer, learner "
                        "checkpoints + cursor, champion.npz, loop.jsonl); "
                        "re-running over the same dir resumes after any "
                        "kill")
    p.add_argument("--checkpoint", metavar="PATH",
                   help="seed champion checkpoint (default: fresh random "
                        "init from the learner model config)")
    p.add_argument("--actors", type=int, default=2,
                   help="selfplay actor threads (default 2)")
    p.add_argument("--fleet", type=int, default=2, metavar="N",
                   help="serving-fleet replicas behind the failover "
                        "router; actors ride the selfplay tier "
                        "(default 2)")
    p.add_argument("--games-per-round", type=int, default=8,
                   help="games per actor round (a round is the actor's "
                        "restart/replay unit)")
    p.add_argument("--max-moves", type=int, default=120,
                   help="selfplay and gate-match move cap")
    p.add_argument("--temperature", type=float, default=0.25,
                   help="actor sampling temperature (trajectory "
                        "diversity for the corpus)")
    p.add_argument("--search-sims", type=int, default=0, metavar="N",
                   help="actors pick moves by N-simulation PUCT search "
                        "over the fleet instead of one policy sample "
                        "(0 = off; AlphaZero-style search-selfplay, "
                        "docs/search.md)")
    p.add_argument("--window-steps", type=int, default=50,
                   help="learner steps per training window (each window "
                        "publishes one challenger)")
    p.add_argument("--min-positions", type=int, default=512,
                   help="sealed positions required before a window may "
                        "freeze its extent")
    p.add_argument("--scheme", default="game",
                   choices=["game", "uniform", "winner"],
                   help="sampling scheme over the frozen extent "
                        "(winner = outcome-conditioned distillation)")
    p.add_argument("--segment-games", type=int, default=16,
                   help="games per sealed buffer segment (the index "
                        "version granularity)")
    p.add_argument("--buffer-capacity", type=int, default=0,
                   metavar="POSITIONS",
                   help="replay-buffer position bound; oldest segments "
                        "are evicted past it, never across a live "
                        "cursor (0 = unbounded)")
    p.add_argument("--gate-games", type=int, default=64,
                   help="arena games per gate (protocol pins from "
                        "match.standard_gate; production gates want the "
                        "1,000-game pin)")
    p.add_argument("--gate-threshold", type=float, default=0.55,
                   help="challenger win rate required to take the "
                        "champion slot (default 0.55)")
    p.add_argument("--windows", type=int, default=0,
                   help="stop after N completed windows (0 = run "
                        "forever)")
    p.add_argument("--duration", type=float, default=0.0, metavar="S",
                   help="stop after S seconds (0 = no time limit)")
    p.add_argument("--stall-timeout", type=float, default=600.0,
                   metavar="S",
                   help="typed LoopStalled when no ingest/window/gate "
                        "progress lands within S seconds")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="per-replica dispatcher coalescing window")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--trace", action="store_true",
                   help="arm request-scoped tracing: per-request "
                        "timelines through the fleet with tail-exemplar "
                        "sampling streamed to <run-dir>/trace.jsonl — "
                        "`cli trace RUN_DIR ID` renders the waterfalls "
                        "(docs/observability.md)")
    p.add_argument("--telemetry", action="store_true",
                   help="arm the fleet telemetry plane: a background "
                        "sampler appends the registry to "
                        "<run-dir>/ts-NNNN.jsonl (retention-bounded, "
                        "power-of-two downsampled) and the streaming "
                        "anomaly watchlist runs over it — `cli dash "
                        "RUN_DIR` renders the history live "
                        "(docs/observability.md)")
    p.add_argument("--telemetry-interval", type=float, default=1.0,
                   metavar="S",
                   help="telemetry sampling cadence (default 1s)")
    p.add_argument("--obs-port", type=int, default=None, metavar="PORT",
                   help="live /metrics + /healthz (fleet + loop "
                        "progress) for the duration of the run")
    p.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE",
                   help="learner ExperimentConfig overrides (model size, "
                        "batch_size, rate, ... — the train grammar)")
    p.set_defaults(fn=cmd_loop)

    p = sub.add_parser("cost", help="AOT device cost ledger: FLOPs / "
                                    "bytes / HBM per jitted entrypoint "
                                    "plus the roofline bound class vs "
                                    "the detected platform peak — "
                                    "nothing executes on the device "
                                    "(docs/observability.md)")
    p.add_argument("--model", default="full",
                   help="model config to price (small/medium/full/large; "
                        "default full — the flagship 12L/128)")
    p.add_argument("--buckets", default="1,8,32,128,512",
                   help="serving-ladder rungs to price (CSV)")
    p.add_argument("--train-batch", type=int, default=256, metavar="B",
                   help="batch for the train/eval step programs "
                        "(0 skips them — their backward-pass compile "
                        "dominates the sweep on CPU)")
    p.add_argument("--sym-bucket", type=int, default=8, metavar="B",
                   help="batch for the 8-fold sym-ensemble forward "
                        "(0 skips)")
    p.add_argument("--json", action="store_true",
                   help="emit the roofline block as JSON")
    p.set_defaults(fn=cmd_cost)

    p = sub.add_parser("lint", help="invariant linter: atomic-write/"
                       "determinism/thread/typed-error discipline + "
                       "code<->docs grammar drift (docs/static_analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="specific files to lint with every rule in scope "
                        "(default: the configured repo sweep)")
    p.add_argument("--root", default=".",
                   help="repo root the configured sweep runs from")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings for CI")
    p.add_argument("--no-grammar", action="store_true",
                   help="skip the repo-level code<->docs drift check")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("trace", help="reconstruct one request's waterfall "
                                     "or a champion's lineage chain from "
                                     "a run directory's sampled "
                                     "trace_request / lineage event "
                                     "streams (docs/observability.md)")
    p.add_argument("run_dir")
    p.add_argument("id", nargs="?", default=None,
                   help="a trace-id prefix (request waterfall), "
                        "`champion`, a window number, or a params-digest "
                        "prefix (lineage chain); omit to list what the "
                        "run has")
    p.add_argument("--json", action="store_true",
                   help="emit the raw record/chain as JSON")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("obs", help="offline observability report: one "
                                   "per-stage table (loader wait, "
                                   "dispatch latency, step time, spans, "
                                   "recoveries) joined from a run's "
                                   "JSONL streams")
    p.add_argument("run_dir")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of the table")
    p.set_defaults(fn=cmd_obs)

    p = sub.add_parser("dash", help="live operator dashboard: watchlist "
                                    "sparklines, fleet health grid, "
                                    "anomalies, SLO burn — over a run "
                                    "dir's time-series store or scraped "
                                    "/metrics endpoints "
                                    "(docs/observability.md)")
    p.add_argument("run_dir", nargs="?", default=None,
                   help="run directory holding ts-NNNN.jsonl chunks "
                        "(written by a --telemetry loop run or a bench "
                        "run); omit with --scrape")
    p.add_argument("--scrape", metavar="URL[,URL...]",
                   help="federate these live /metrics endpoints instead "
                        "of reading a store (fleet replicas, elastic "
                        "hosts); each gets a host label, dead endpoints "
                        "are tolerated and flagged")
    p.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="refresh cadence (default 2s)")
    p.add_argument("--window", type=int, default=240, metavar="N",
                   help="samples per sparkline window (default 240)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (CI)")
    p.add_argument("--json", action="store_true",
                   help="emit the frame's underlying dict once as JSON "
                        "(implies --once; schema in "
                        "docs/observability.md)")
    p.set_defaults(fn=cmd_dash)

    p = sub.add_parser("trend", help="bench trajectory: BENCH_r*.json "
                                     "rounds + BENCH_LAST_GOOD.json as "
                                     "one per-metric history table "
                                     "(stale captures marked)")
    p.add_argument("--root", default=".",
                   help="repo root holding the BENCH_r*.json artifacts")
    p.add_argument("--json", action="store_true",
                   help="emit the joined history as JSON")
    p.set_defaults(fn=cmd_trend)

    p = sub.add_parser("workload", help="workload observatory: record a "
                                        "live opening-heavy capture, "
                                        "characterize it (dup ratio, "
                                        "projected cache hit rate), or "
                                        "replay it with open-loop arrival "
                                        "fidelity (docs/observability.md)")
    wsub = p.add_subparsers(dest="wcmd", required=True)

    def _workload_target_args(w) -> None:
        w.add_argument("--fleet", type=int, default=2, metavar="N",
                       help="replicas in the target fleet (default 2)")
        w.add_argument("--model", default="small",
                       help="policy config for a random-init fleet "
                            "(default small)")
        w.add_argument("--checkpoint", default=None,
                       help="serve this checkpoint instead of random init")
        w.add_argument("--max-wait-ms", type=float, default=2.0)
        w.add_argument("--speed", type=float, default=1.0,
                       help="arrival-timeline speedup (1.0 = recorded "
                            "pace, N = N-times faster)")
        w.add_argument("--json", action="store_true")

    w = wsub.add_parser("record", help="drive a live fleet with the "
                                       "synthetic opening-heavy workload "
                                       "and capture it")
    w.add_argument("--out", required=True, metavar="DIR",
                   help="capture directory (workload.jsonl + "
                        "positions.jsonl)")
    w.add_argument("--requests", type=int, default=256)
    w.add_argument("--games", type=int, default=16,
                   help="real games whose openings build the position "
                        "pool")
    w.add_argument("--opening-moves", type=int, default=10,
                   help="plies kept per game (the opening tree depth)")
    w.add_argument("--rate", type=float, default=200.0, metavar="REQ/S",
                   help="Poisson arrival rate of the synthetic trace")
    w.add_argument("--zipf", type=float, default=1.1,
                   help="popularity-skew exponent over move depth")
    w.add_argument("--seed", type=int, default=0,
                   help="the trace is a pure function of this seed")
    w.add_argument("--sgf-dir", default="data/sgf/train")
    w.add_argument("--search", type=int, default=0, metavar="N",
                   help="after the synthetic drive, run N PUCT searches "
                        "rooted at distinct captured positions — the "
                        "capture gains search:<id>-labeled leaf traffic "
                        "and `workload analyze` reports its "
                        "transposition dup ratio")
    w.add_argument("--search-sims", type=int, default=32, metavar="S",
                   help="simulation budget per recorded search")
    _workload_target_args(w)
    w.set_defaults(fn=cmd_workload)

    w = wsub.add_parser("analyze", help="characterization report over a "
                                        "capture: unique/canonical "
                                        "positions, symmetry-dedup gain, "
                                        "popularity skew, burstiness, "
                                        "projected cache hit rate")
    w.add_argument("capture", help="capture directory (or workload.jsonl)")
    w.add_argument("--simulate-cache", default=None, metavar="SIZES",
                   help="replay the capture's key stream through the "
                        "position cache's LRU offline at each capacity "
                        "(comma-separated entry counts) and report the "
                        "ACHIEVED hit rate per size and keying — the "
                        "capacity-planning number next to the projection")
    w.add_argument("--json", action="store_true")
    w.set_defaults(fn=cmd_workload)

    w = wsub.add_parser("replay", help="re-serve a capture against a live "
                                       "fleet at the recorded arrival "
                                       "pace (open loop); exits nonzero "
                                       "when timeline fidelity misses "
                                       "the 10%% bar")
    w.add_argument("capture")
    w.add_argument("--timeout", type=float, default=0.0, metavar="S",
                   help="per-request deadline (0 = none)")
    _workload_target_args(w)
    w.set_defaults(fn=cmd_workload)

    p = sub.add_parser("chaos", help="chaos campaigns: replay an "
                                     "opening-heavy trace against a live "
                                     "fleet while a fault timeline kills, "
                                     "brownouts, and corrupts replicas; "
                                     "grade SLO burn + integrity "
                                     "invariants (docs/robustness.md)")
    csub = p.add_subparsers(dest="ccmd", required=True)

    c = csub.add_parser("run", help="execute one campaign and write the "
                                    "graded JSON report (exits nonzero "
                                    "on a failing grade)")
    c.add_argument("--out", default=None, metavar="FILE",
                   help="write the campaign report JSON here")
    c.add_argument("--scenario", default=None, metavar="FILE",
                   help="scenario JSON (Scenario.to_dict layout); "
                        "default: the --preset timeline scaled to the "
                        "trace span")
    c.add_argument("--preset", default="brownout",
                   choices=["brownout", "full"],
                   help="built-in scenario: 'brownout' (one replica "
                        "slows — the hedging/ejection A/B axis) or "
                        "'full' (kill + brownout + corruption)")
    c.add_argument("--no-defenses", action="store_true",
                   help="disarm hedging/ejection/integrity/canary: the "
                        "A/B control arm")
    c.add_argument("--trace", default=None, metavar="DIR",
                   help="replay this workload capture instead of the "
                        "synthetic opening-heavy trace")
    c.add_argument("--requests", type=int, default=200)
    c.add_argument("--games", type=int, default=16)
    c.add_argument("--rate", type=float, default=45.0, metavar="REQ/S")
    c.add_argument("--seed", type=int, default=0)
    c.add_argument("--sgf-dir", default="data/sgf/train")
    c.add_argument("--slo-threshold", type=float, default=None,
                   metavar="S",
                   help="interactive latency SLO threshold (default: "
                        "0.15 for a pure brownout, 2.0 once the "
                        "scenario kills or corrupts — the integrity "
                        "campaign is graded on survival)")
    c.add_argument("--slo-target", type=float, default=None,
                   help="fraction of requests that must land within "
                        "the threshold (default 0.95 brownout / 0.5 "
                        "kill+corrupt)")
    c.add_argument("--fleet", type=int, default=2, metavar="N")
    c.add_argument("--model", default="small")
    c.add_argument("--checkpoint", default=None)
    c.add_argument("--max-wait-ms", type=float, default=2.0)
    c.add_argument("--speed", type=float, default=1.0)
    c.add_argument("--json", action="store_true")
    c.set_defaults(fn=cmd_chaos)

    c = csub.add_parser("report", help="re-render and re-grade a stored "
                                       "campaign report")
    c.add_argument("report", help="campaign report JSON from `chaos run`")
    c.add_argument("--json", action="store_true")
    c.set_defaults(fn=cmd_chaos)

    # "selfplay" is forwarded before parsing (above); listed here so it
    # shows up in --help output
    sub.add_parser("selfplay", help="engine-driven batched self-play "
                                    "(flags forward to deepgo_tpu.selfplay, "
                                    "e.g. --games 32 --max-wait-ms 2; "
                                    "--supervised runs the engine under "
                                    "the resilience supervisor)")

    args = ap.parse_args(argv)
    honor_platform_env()
    args.fn(args)


if __name__ == "__main__":
    main()
