"""SGF (Smart Game Format) parsing for 19x19 Go game records.

Replaces the reference's line-oriented tokenizer (reference makedata.lua:24-120:
``split_sgf``/``all_moves``/``handicaps``/``get_ranks``) with a real SGF
property scanner: properties may span lines, carry multiple bracketed values,
and escape ``]`` inside values. Behavioral parity points:

  * moves: B/W properties in order; passes (empty value or ``tt``) are
    dropped, exactly like the reference's ``to_move`` returning nil for
    values it cannot map (makedata.lua:60-67).
  * handicap/setup stones: AB/AW values in order of appearance
    (makedata.lua:24-38); order matters because stone placement order
    determines the age feature plane.
  * ranks: BR/WR must both parse as dan ranks ``<n>d`` with n in 1..9,
    otherwise the game is rejected (makedata.lua:92-120; the 1..9 bound is
    implied there by the 9 rank feature planes, dataloader.lua:12).

Coordinates are 0-based: 'a'..'s' -> 0..18, x = first letter, y = second.
Players are 1 (black) and 2 (white).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from . import BOARD_SIZE

BLACK, WHITE = 1, 2

_COORD_OF_CHAR = {c: i for i, c in enumerate("abcdefghijklmnopqrs")}


@dataclass(frozen=True)
class Move:
    player: int  # 1 black, 2 white
    x: int  # 0..18
    y: int  # 0..18


@dataclass
class Game:
    moves: list[Move] = field(default_factory=list)  # passes excluded
    handicaps: list[Move] = field(default_factory=list)  # AB/AW setup stones
    ranks: tuple[int, int] | None = None  # (black dan, white dan) or None
    properties: dict[str, list[str]] = field(default_factory=dict)


# PropIdent then one-or-more bracketed values; values may escape ']' as '\]'.
_PROP_RE = re.compile(r"([A-Za-z]+)((?:\s*\[(?:[^\\\]]|\\.)*\])+)", re.S)
_VALUE_RE = re.compile(r"\[((?:[^\\\]]|\\.)*)\]", re.S)


def _to_point(value: str) -> tuple[int, int] | None:
    """SGF move value -> (x, y), or None for a pass.

    Empty value and the conventional 19x19 pass value 'tt' both map to None,
    matching the reference dropping any value it cannot convert
    (makedata.lua:60-67 via the a..s char table).
    """
    if len(value) != 2:
        return None
    x = _COORD_OF_CHAR.get(value[0])
    y = _COORD_OF_CHAR.get(value[1])
    if x is None or y is None:
        return None
    return x, y


def _to_rank(value: str) -> int | None:
    """Dan-rank string '<n>d' -> n, else None (reference to_rank, makedata.lua:92-100)."""
    m = re.fullmatch(r"(\d+)d", value.strip())
    if not m:
        return None
    return int(m.group(1))


def parse(text: str) -> Game:
    """Parse one SGF game record into a Game."""
    game = Game()
    for m in _PROP_RE.finditer(text):
        ident = m.group(1)
        values = [v.group(1).replace("\\]", "]") for v in _VALUE_RE.finditer(m.group(2))]
        game.properties.setdefault(ident, []).extend(values)
        if ident in ("B", "W"):
            player = BLACK if ident == "B" else WHITE
            for value in values:
                pt = _to_point(value)
                if pt is not None:
                    game.moves.append(Move(player, *pt))
        elif ident in ("AB", "AW"):
            player = BLACK if ident == "AB" else WHITE
            for value in values:
                pt = _to_point(value)
                if pt is not None:
                    game.handicaps.append(Move(player, *pt))

    br = game.properties.get("BR", [])
    wr = game.properties.get("WR", [])
    black_rank = _to_rank(br[0]) if br else None
    white_rank = _to_rank(wr[0]) if wr else None
    if (black_rank is not None and white_rank is not None
            and 1 <= black_rank <= 9 and 1 <= white_rank <= 9):
        game.ranks = (black_rank, white_rank)
    return game


def parse_file(path: str) -> Game:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return parse(f.read())


def coord_to_sgf(x: int, y: int) -> str:
    """0-based (x, y) -> two-letter SGF coordinate."""
    chars = "abcdefghijklmnopqrs"
    assert 0 <= x < BOARD_SIZE and 0 <= y < BOARD_SIZE
    return chars[x] + chars[y]
