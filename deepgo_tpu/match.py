"""Match harness: batched games between two agents, scored, win rates out.

N games advance in lockstep, colors alternate across games (game i gives
black to agent ``i % 2``), each ply batches all boards where a given
agent is to move into one TPU forward (for policy agents) or one
vectorized host step (for baselines), and finished games are
Tromp-Taylor scored (``go.scoring.area_score``) to produce W/L and
margins. The players live in deepgo_tpu.agents; the ``python -m
deepgo_tpu.arena`` CLI entry is preserved by the arena shim.

Usage:
  python -m deepgo_tpu.arena --a checkpoint:runs/<id>/checkpoint.npz \
      --b random --games 64 [--komi 7.5] [--sgf-out arena_games/]
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from .agents import Agent, _make_agent
from .go import BLACK
from .go.scoring import area_score
from .selfplay import (GameState, legal_mask, step_games, summarize_states,
                       to_sgf)

# The pinned evaluation protocol every strength number in RESULTS.md is
# quoted under ("1,000-game precision"): 1,000 games vs the oneply
# baseline, 8 shared random opening plies per color-swapped pair, seed 29,
# rank plane 8 (the synthetic corpus's strongest tag). One definition —
# standard_gate() below, the arena CLI's --standard-gate, and the shell
# queues (tools/r5_value_loop.sh vmatch) all read these — so the arena
# gatekeeper and the historical match queues can never drift apart.
GATE_GAMES = 1000
GATE_OPENING_PLIES = 8
GATE_SEED = 29
GATE_RANK = 8


def standard_gate(agent_a: Agent, agent_b: Agent, n_games: int = GATE_GAMES,
                  komi: float = 7.5, max_moves: int = 450):
    """``play_match`` under the pinned arena protocol.

    Returns (games, scores, stats) with the protocol recorded in
    ``stats["protocol"]`` and agent A's win rate surfaced as
    ``stats["win_rate_a"]`` (the per-name key play_match emits depends on
    the agent's name; gate consumers want a fixed key). ``n_games`` stays
    overridable — an in-process loop turn gates on a handful of games,
    the production gate keeps the 1,000-game pin — but the opening /
    seed / pairing discipline is not: that is the part that makes two win
    rates comparable."""
    games, scores, stats = play_match(
        agent_a, agent_b, n_games=n_games, komi=komi, max_moves=max_moves,
        seed=GATE_SEED, opening_plies=GATE_OPENING_PLIES,
        shared_openings=True)
    name_a = agent_a.name
    stats["win_rate_a"] = stats[f"{name_a}_win_rate"]
    stats["protocol"] = {"games": n_games, "opening_plies": GATE_OPENING_PLIES,
                         "seed": GATE_SEED, "rank": GATE_RANK,
                         "komi": komi, "max_moves": max_moves}
    return games, scores, stats


def play_match(agent_a: Agent, agent_b: Agent, n_games: int = 32,
               komi: float = 7.5, max_moves: int = 450, seed: int = 0,
               opening_plies: int = 0, shared_openings: bool = True):
    """Run n_games with alternating colors; returns (games, scores, stats).

    Game i gives black to agent_a when i is even. Every active game advances
    one ply per iteration, so all active boards share a side-to-move and each
    agent sees at most one batch per ply.

    ``opening_plies > 0`` starts each game with that many uniformly-random
    legal moves before the agents take over, with games 2i and 2i+1
    SHARING an opening (the color-swapped rematch starts from the same
    position). Two deterministic agents otherwise produce one pair of
    games replicated n_games/2 times — sub-ulp tie-break noise almost
    never flips a trained net's argmax — so a 200-game match carries two
    games' worth of evidence; balanced random openings restore n_games
    distinct trajectories while keeping the color-paired fairness.

    ``shared_openings=False`` draws an independent opening per GAME
    instead of per pair. Win-rate evaluation wants the pair-shared
    default (the color-swapped rematch from the same position is what
    makes the pairing fair); corpus generation wants maximum trajectory
    diversity — a deterministic agent playing itself from a pair-shared
    opening produces the SAME game twice, and the duplicates can
    straddle train/validation splits downstream.
    """
    import sys

    rng = np.random.default_rng(seed)
    games = [GameState() for _ in range(n_games)]
    # black_agent[i] plays BLACK in game i
    agent_of = [(agent_a, agent_b) if i % 2 == 0 else (agent_b, agent_a)
                for i in range(n_games)]
    plies = 0
    t0 = time.time()
    last_report = t0

    while True:
        live = [i for i, g in enumerate(games) if not g.done]
        if not live:
            break
        # long matches (a 1,000-game pin is hours on a host core) print
        # nothing until scoring without this: a heartbeat on stderr keeps
        # the run observable and log-stall supervisors satisfied
        now = time.time()
        if now - last_report > 120:
            last_report = now
            print(f"# match {n_games - len(live)}/{n_games} games done, "
                  f"{plies:,} plies, {plies / (now - t0):.1f} pos/sec",
                  file=sys.stderr, flush=True)
        packed = summarize_states([games[i] for i in live])
        players = np.array([games[i].player for i in live], dtype=np.int32)
        legal = legal_mask(packed, players, [games[i] for i in live])
        plies += len(live)

        moves = np.full(len(live), -1, dtype=np.int64)
        if len(games[live[0]].moves) < opening_plies:
            # balanced random opening: draw one legal point per PAIR and
            # give it to both color assignments (identical positions, so
            # one draw is legal in both)
            u = rng.random(legal.shape)
            pick = np.where(legal, u, -1.0).argmax(axis=1)
            pick = np.where(legal.any(axis=1), pick, -1)
            for j, i in enumerate(live):
                if shared_openings:
                    mate = live.index(i ^ 1) if (i ^ 1) in live else j
                    moves[j] = pick[min(j, mate)]
                else:
                    moves[j] = pick[j]
        else:
            agents = (agent_a,) if agent_b is agent_a else (agent_a, agent_b)
            for agent in agents:
                sel = [j for j, i in enumerate(live)
                       if agent_of[i][games[i].player - 1] is agent]
                if sel:
                    moves[sel] = agent.select_moves(
                        packed[sel], players[sel], legal[sel], rng)

        step_games([games[i] for i in live], moves.tolist(), max_moves)

    scores = [area_score(g.stones, komi=komi) for g in games]
    dt = time.time() - t0

    a_wins = b_wins = draws = 0
    a_black_wins = 0
    margins = []
    for i, s in enumerate(scores):
        winner = s.winner
        black, white = agent_of[i]
        margins.append(s.margin if black is agent_a else -s.margin)
        if winner == 0:
            draws += 1
        elif (black if winner == BLACK else white) is agent_a:
            a_wins += 1
            if winner == BLACK and black is agent_a:
                a_black_wins += 1
        else:
            b_wins += 1
    name_a = agent_a.name
    name_b = agent_b.name if agent_b.name != name_a else agent_b.name + "-b"
    # area-scoring a move-cap-truncated board is an approximation; surface
    # how much of the result rests on it so win-rate consumers can judge
    truncated = sum(1 for g in games if g.passes < 2)
    stats = {
        "games": n_games,
        "truncated": truncated,
        f"{name_a}_wins": a_wins,
        f"{name_b}_wins": b_wins,
        "draws": draws,
        f"{name_a}_win_rate": a_wins / n_games,
        f"{name_a}_wins_as_black": a_black_wins,
        "mean_margin_for_a": float(np.mean(margins)),
        "plies": plies,
        "seconds": dt,
        "positions_per_sec": plies / dt,
    }
    return games, scores, stats


def main(argv=None) -> None:
    import os

    from .utils.atomicio import atomic_write

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--a", default="model:small", help="agent A spec")
    ap.add_argument("--b", default="random", help="agent B spec")
    ap.add_argument("--games", type=int, default=32)
    ap.add_argument("--komi", type=float, default=7.5)
    ap.add_argument("--max-moves", type=int, default=450)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="softmax sampling temperature for checkpoint:/model: "
                         "policy agents (0 = argmax; >0 diversifies "
                         "policy-vs-policy games); search: agents stay "
                         "deterministic regardless")
    ap.add_argument("--rank", type=int, default=9,
                    help="dan rank fed to policy agents' rank planes; match "
                         "the training corpus (e.g. 8 for the synthetic "
                         "corpus, whose strongest games are tagged 8d)")
    ap.add_argument("--opening-plies", type=int, default=0,
                    help="start each game pair from this many shared "
                         "uniformly-random legal moves — restores distinct "
                         "trajectories in deterministic-vs-deterministic "
                         "matches (the color-swapped rematch shares the "
                         "opening, keeping the pairing fair)")
    ap.add_argument("--standard-gate", action="store_true",
                    help="apply the pinned arena protocol (the RESULTS.md "
                         "'1,000-game precision' pins shared with the "
                         "expert-iteration gatekeeper): --b oneply, "
                         f"--games {GATE_GAMES}, --opening-plies "
                         f"{GATE_OPENING_PLIES}, --seed {GATE_SEED}, "
                         f"--rank {GATE_RANK}; explicit --games/--b win "
                         "over the defaults, the protocol pins do not")
    ap.add_argument("--search-sims", type=int, default=128, metavar="N",
                    help="simulation budget for mcts: agents "
                         "(deepgo_tpu.search): the pinned per-move PUCT "
                         "budget the Elo gate quotes — "
                         "'--a mcts:P.npz:V.npz --b value2:P.npz:V.npz "
                         "--standard-gate --search-sims 128' is the "
                         "search-vs-shallow gate (docs/search.md)")
    ap.add_argument("--sgf-out", help="directory to write scored games")
    ap.add_argument("--engine", action="store_true",
                    help="route net-backed agents through the shared "
                         "micro-batching inference engine "
                         "(deepgo_tpu.serving): both sides of a match "
                         "built from the same checkpoint coalesce into "
                         "the same padded dispatches (docs/serving.md)")
    ap.add_argument("--supervised", action="store_true",
                    help="like --engine, but the shared engines run under "
                         "the resilience supervisor: dispatcher-death "
                         "auto-restart with replay, batch-poison "
                         "isolation, circuit breaker, deadline shedding "
                         "(docs/robustness.md)")
    ap.add_argument("--fleet", type=int, default=1, metavar="N",
                    help="like --supervised, but the shared engines become "
                         "a FleetRouter of N supervised replicas: "
                         "least-wait placement, failover with exclusion, "
                         "background respawn, tiered QoS "
                         "(docs/serving.md)")
    ap.add_argument("--variant-a", default="f32", metavar="V",
                    help="serving variant for agent A's policy forward "
                         "(f32 | int8 | sym | int8+sym — serving/"
                         "variants.py). The live quantization A/B: "
                         "'--a checkpoint:C --variant-a int8 --b "
                         "checkpoint:C' gates the int8 champion against "
                         "the f32 one under the pinned protocol; lossy "
                         "variants tolerance-verify before serving and "
                         "imply --engine (docs/serving.md)")
    ap.add_argument("--variant-b", default="f32", metavar="V",
                    help="serving variant for agent B's policy forward")
    args = ap.parse_args(argv)

    if args.standard_gate:
        # the protocol pins are not negotiable under --standard-gate (they
        # are what makes the number comparable to every RESULTS.md rung);
        # the opponent and game count keep their explicit overrides so a
        # smoke run can gate 32 games against a different baseline
        args.rank = GATE_RANK
        args.seed = GATE_SEED
        args.opening_plies = GATE_OPENING_PLIES
        if args.b == ap.get_default("b"):
            args.b = "oneply"
        if args.games == ap.get_default("games"):
            args.games = GATE_GAMES

    from .utils import honor_platform_env

    honor_platform_env()
    use_engine = ("supervised" if args.supervised
                  else args.engine or args.fleet > 1
                  or args.variant_a != "f32" or args.variant_b != "f32")
    agent_a = _make_agent(args.a, args.seed, args.temperature, args.rank,
                          use_engine=use_engine, fleet=args.fleet,
                          variant=args.variant_a,
                          search_sims=args.search_sims)
    agent_b = _make_agent(args.b, args.seed + 1, args.temperature, args.rank,
                          use_engine=use_engine, fleet=args.fleet,
                          variant=args.variant_b,
                          search_sims=args.search_sims)
    # distinct names keep the A/B's win-rate keys readable when both
    # sides are the same checkpoint under different serving variants
    if args.variant_a != "f32":
        agent_a.name = f"{agent_a.name}+{args.variant_a}"
    if args.variant_b != "f32":
        agent_b.name = f"{agent_b.name}+{args.variant_b}"
    try:
        games, scores, stats = play_match(
            agent_a, agent_b, n_games=args.games, komi=args.komi,
            max_moves=args.max_moves, seed=args.seed,
            opening_plies=args.opening_plies)
    finally:
        if use_engine:
            from .serving import close_shared_engines

            close_shared_engines()
    print({k: round(v, 3) if isinstance(v, float) else v
           for k, v in stats.items()})

    if args.sgf_out:
        os.makedirs(args.sgf_out, exist_ok=True)
        finished = 0
        for i, (g, s) in enumerate(zip(games, scores)):
            # RE[] only for games that ended on double pass; a move-cap
            # truncation is scored for the stats table (standard
            # approximation) but not stamped into the record
            done = g.passes >= 2
            finished += done
            # atomic: a kill mid-write must not leave a torn SGF that a
            # later corpus build half-parses (docs/static_analysis.md)
            with atomic_write(os.path.join(args.sgf_out,
                                           f"match_{i:04d}.sgf"),
                              mode="w") as f:
                f.write(to_sgf(g, result=s.result_string() if done else None,
                               komi=args.komi))
        print(f"wrote {len(games)} SGFs ({finished} finished/scored, "
              f"{len(games) - finished} move-cap truncated) to {args.sgf_out}")


if __name__ == "__main__":
    main()
