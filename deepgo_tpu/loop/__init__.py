"""The always-on expert-iteration service (``cli loop``, docs/loop.md).

Selfplay actors → replay buffer → continuous learner → arena gatekeeper,
every component supervised, every artifact crash-safe, the whole cycle
running forever under chaos:

  * ``actors``     — selfplay over the serving fleet's selfplay tier,
    finished games durably ingested;
  * ``replay``     — bounded on-disk replay buffer with window-versioned
    index segments; a frozen extent is an immutable dataset, which is
    what keeps the step-indexed stream bit-exact while the corpus grows;
  * ``learner``    — windowed training with a checkpointed read cursor
    and atomic per-window challenger publishes; ``--auto-resume`` after
    any kill replays the interrupted window bit-identically;
  * ``gatekeeper`` — challengers reach serving only by beating the
    incumbent at >= 55% under the pinned arena protocol
    (``match.standard_gate``); a pass atomically publishes the champion
    and hot-reloads the fleet in place (PR 7's ``FleetRouter.reload``);
  * ``service``    — the supervisor wiring it together with bounded
    component restarts, stall detection, ``loop_*`` events and
    ``deepgo_loop_*`` metrics.

Chaos-tested end to end by ``bench.py --mode loop --faults`` (kills an
actor, the learner, and a fleet replica; asserts zero lost games, a
bit-exact learner resume, and a served champion newer than the seed) and
``make verify-loop`` (a full in-process loop turn).
"""

from .replay import (ReplayBuffer, ReplayError, ReplayView,  # noqa: F401
                     count_durable_games)
from .learner import (ContinuousLearner, LoopError,  # noqa: F401
                      LoopStalled, params_digest, read_windows,
                      replay_window)
from .actors import SelfplayActor, game_records  # noqa: F401
from .gatekeeper import (ArenaGatekeeper, GateRejected,  # noqa: F401
                         publish_checkpoint)
from .service import ExpertIterationLoop, LoopConfig  # noqa: F401
