"""Arena gatekeeper: challengers earn the serving fleet, they don't get it.

The expert-iteration failure mode RESULTS.md measured is distribution
collapse: a learner can minimize its loss on its own games while getting
*weaker*. The gate is the loop's only defense — a challenger checkpoint
reaches the serving fleet exclusively by beating the incumbent champion
in a pinned arena match (``match.standard_gate``: the same opening /
seed / pairing discipline every RESULTS.md strength number used) at
``threshold`` (default 55%) or better. On a pass the gatekeeper:

  1. verifies the challenger file end-to-end (format v2 CRC/SHA — a torn
     or corrupt publish is REJECTED before it can touch serving);
  2. atomically publishes it as the champion checkpoint
     (``utils.atomicio`` — a watcher never observes a partial file);
  3. rolls it through the fleet in place (``FleetRouter.reload``: zero
     dropped futures, zero recompiles, capacity never below N-1), which
     retargets every selfplay actor's next ply at once.

On a miss it raises a typed ``GateRejected`` carrying the full match
stats — a normal loop outcome the service counts, not a crash. Fault
site ``loop_gate`` fires at evaluation start (docs/robustness.md).
"""

from __future__ import annotations

import time

from .. import match
from ..agents import PolicyAgent
from ..experiments import checkpoint as ckpt
from ..models.serving import load_policy
from ..obs import get_registry
from ..utils import faults
from ..utils.atomicio import atomic_write
from .learner import LoopError


class GateRejected(LoopError):
    """The challenger did not clear the arena gate. Carries ``win_rate``,
    ``threshold``, and the full match ``stats``; the incumbent keeps
    serving and the loop moves on to the next window."""

    def __init__(self, win_rate: float, threshold: float, stats: dict):
        self.win_rate = win_rate
        self.threshold = threshold
        self.stats = stats
        super().__init__(
            f"challenger won {win_rate:.1%} < gate threshold "
            f"{threshold:.1%} ({stats.get('games')} games)")


def publish_checkpoint(src: str, dst: str) -> None:
    """Atomically copy a verified checkpoint into the champion slot.

    Read fully, then ``atomic_write`` — watchers (``cli serve --watch``,
    a peer gatekeeper) only ever see old-complete or new-complete, never
    a torn champion. The source must already be verified by the caller."""
    with open(src, "rb") as f:
        data = f.read()
    with atomic_write(dst) as f:
        f.write(data)


class ArenaGatekeeper:
    """Challenger-vs-incumbent gate over the pinned arena protocol.

    ``fleet`` (optional) is the live FleetRouter serving the champion;
    on a gate pass its weights are hot-reloaded in place. ``engine``
    (optional) routes the *incumbent's* match inference through the
    serving fleet — the gate then measures exactly the policy the users
    are getting, QoS tiers included — while the challenger plays through
    its own direct ladder path (it has no serving presence yet, by
    definition)."""

    def __init__(self, champion_path: str, games: int = 64,
                 threshold: float = 0.55, max_moves: int = 450,
                 komi: float = 7.5, fleet=None, engine=None,
                 metrics=None, clock=time.time):
        self.champion_path = champion_path
        self.games = games
        self.threshold = threshold
        self.max_moves = max_moves
        self.komi = komi
        self.fleet = fleet
        self.engine = engine
        self._metrics = metrics
        self._clock = clock
        self.gates_passed = 0
        self.gates_rejected = 0
        self._champion_since = clock()
        reg = get_registry()
        self._obs_passed = reg.counter(
            "deepgo_loop_gates_passed_total",
            "challengers promoted to champion by the arena gate")
        self._obs_rejected = reg.counter(
            "deepgo_loop_gates_rejected_total",
            "challengers rejected by the arena gate")
        self._obs_age = reg.gauge(
            "deepgo_loop_champion_age_s",
            "seconds since the serving champion last changed")

    def champion_age_s(self) -> float:
        age = self._clock() - self._champion_since
        self._obs_age.set(age)
        return age

    def evaluate(self, challenger_path: str) -> dict:
        """Gate one challenger. Returns the pass record (win_rate, stats,
        reload report); raises GateRejected on a miss and CheckpointError
        on an unverifiable challenger file."""
        faults.check("loop_gate")
        t0 = self._clock()
        # full integrity pass FIRST: a corrupt challenger must fail here,
        # not after a 1,000-game match or mid-reload
        ckpt.verify_checkpoint(challenger_path)
        _, c_params, c_cfg = load_policy(challenger_path)
        _, i_params, i_cfg = load_policy(self.champion_path)
        # the challenger's bitwise identity — the key the lineage chain
        # joins on (it equals the learner's lineage_window digest for the
        # window that published this challenger)
        from .learner import params_digest

        challenger_digest = params_digest(c_params)
        challenger = PolicyAgent(c_params, c_cfg, name="challenger",
                                 rank=match.GATE_RANK)
        incumbent = PolicyAgent(i_params, i_cfg, name="champion",
                                rank=match.GATE_RANK, engine=self.engine)
        _, _, stats = match.standard_gate(
            challenger, incumbent, n_games=self.games, komi=self.komi,
            max_moves=self.max_moves)
        win_rate = stats["win_rate_a"]
        if win_rate < self.threshold:
            self.gates_rejected += 1
            self._obs_rejected.inc(1)
            if self._metrics is not None:
                self._metrics.write("loop_gate", outcome="rejected",
                                    win_rate=round(win_rate, 4),
                                    threshold=self.threshold,
                                    games=self.games,
                                    seconds=round(self._clock() - t0, 3))
                self._metrics.write("lineage_gate", outcome="rejected",
                                    digest=challenger_digest,
                                    win_rate=round(win_rate, 4),
                                    games=self.games)
            raise GateRejected(win_rate, self.threshold, stats)
        publish_checkpoint(challenger_path, self.champion_path)
        reload_report = None
        if self.fleet is not None:
            reload_report = self.fleet.reload(self.champion_path)
        self.gates_passed += 1
        self._champion_since = self._clock()
        self._obs_passed.inc(1)
        self._obs_age.set(0.0)
        record = {
            "outcome": "passed",
            "win_rate": round(win_rate, 4),
            "threshold": self.threshold,
            "games": self.games,
            "champion": self.champion_path,
            "champion_step": ckpt.load_meta(self.champion_path).get("step"),
            "reload": reload_report,
            "seconds": round(self._clock() - t0, 3),
        }
        if self._metrics is not None:
            self._metrics.write("loop_gate", **{
                k: v for k, v in record.items() if k != "reload"})
            self._metrics.write("lineage_gate", outcome="passed",
                                digest=challenger_digest,
                                win_rate=round(win_rate, 4),
                                games=self.games)
            # the chain's root: what the fleet serves NOW, and the
            # digest that walks back to its training window
            self._metrics.write("lineage_champion",
                                digest=challenger_digest,
                                step=record["champion_step"],
                                path=self.champion_path,
                                source="gate")
        record["stats"] = stats
        return record
