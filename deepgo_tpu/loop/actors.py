"""Selfplay actors: the game-producing side of the expert-iteration loop.

Each actor plays rounds of engine-driven selfplay (deepgo_tpu.selfplay)
against the serving fleet's ``selfplay`` priority tier and durably ingests
every finished game into the replay buffer. Two properties matter more
than raw speed:

  * actors hold NO weights — they submit boards to the shared fleet, so a
    champion hot-reload (``FleetRouter.reload``) retargets every actor's
    very next ply with zero actor-side coordination. The publish
    mechanism PR 7 built is the only weight channel the loop has.
  * actors are crash-disposable — all durable state lives in the buffer.
    A restarted actor replays its interrupted round from the round seed;
    games the buffer already acked stay acked (never lost), games it
    hadn't don't exist yet (never half-ingested).

Training records are produced by replaying the finished game's move list
through the rules engine — the same pre-move-summarize convention as
``go.replay.replay_positions`` and the SGF transcription path, so
buffer-fed training and corpus-fed training see byte-identical features
for the same game.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..data.dataset import META_COLS, RECORD_SHAPE
from ..go import native, new_board, play
from ..go.scoring import area_score
from ..go.summarize import summarize
from ..obs import get_registry
from ..selfplay import GameState, self_play
from .replay import ReplayBuffer


def game_records(game: GameState, black_rank: int = 8,
                 white_rank: int = 8) -> tuple[np.ndarray, np.ndarray]:
    """(packed (M,9,19,19) uint8, meta (M,6) int32) for one finished game.

    Replays the move list from an empty board, summarizing the *pre-move*
    position for each move (passes never enter ``game.moves``, so the
    board — age channel included — evolves exactly as transcription's
    replay does). The game_id column is left 0; the buffer rewrites it
    to the ingest gid at seal time."""
    moves = game.moves
    stones, age = new_board()
    packed = np.empty((len(moves), *RECORD_SHAPE), np.uint8)
    meta = np.empty((len(moves), META_COLS), np.int32)
    for i, m in enumerate(moves):
        packed[i] = (native.summarize_native(stones, age)
                     if native.available() else summarize(stones, age))
        meta[i] = (m.player, m.x, m.y, black_rank, white_rank, 0)
        play(stones, age, m.x, m.y, m.player)
    return packed, meta


class SelfplayActor:
    """One actor: rounds of selfplay over a shared engine, games into the
    buffer. ``engine`` is anything with the InferenceEngine surface — in
    the loop service it is the FleetRouter, so submissions carry the
    fleet's selfplay-tier QoS and pick up champion reloads in place."""

    def __init__(self, actor_id: int, buffer: ReplayBuffer, engine,
                 games_per_round: int = 8, max_moves: int = 120,
                 temperature: float = 0.25, rank: int = 8,
                 komi: float = 7.5, seed: int = 0, metrics=None,
                 search_sims: int = 0):
        self.actor_id = actor_id
        self.buffer = buffer
        self.engine = engine
        self.games_per_round = games_per_round
        self.max_moves = max_moves
        self.temperature = temperature
        self.rank = rank
        self.komi = komi
        self.seed = seed
        self._metrics = metrics
        # search_sims > 0 upgrades the actor to AlphaZero-style
        # search-selfplay: each move is a PUCT search over the same
        # fleet (selfplay tier, root noise + visit temperature), so the
        # expert-iteration corpus is produced by policy+search rather
        # than the raw policy (docs/search.md)
        self.search_sims = search_sims
        self._move_selector = None
        if search_sims > 0:
            from ..search import SearchConfig, make_move_selector

            self._move_selector = make_move_selector(
                engine, SearchConfig(
                    simulations=search_sims, tier="selfplay",
                    rank=rank, max_moves=max_moves, temperature=1.0,
                    root_noise_frac=0.25),
                metrics=metrics)
        self.round = 0          # advances only when a round fully ingests
        self.games_acked = 0
        reg = get_registry()
        self._obs_games = reg.counter(
            "deepgo_loop_games_ingested_total",
            "finished selfplay games durably ingested into the replay "
            "buffer")
        self._obs_positions = reg.counter(
            "deepgo_loop_positions_ingested_total",
            "training positions durably ingested into the replay buffer")

    def run_round(self) -> dict:
        """Play one round of games and ingest every finished one.

        The round seed is a pure function of (actor seed, round index):
        a restarted actor repeats the round it died in rather than
        skipping it, so an ingest crash costs the un-acked remainder of
        one round, never a hole in the schedule."""
        t0 = time.monotonic()
        games, stats = self_play(
            None, None, n_games=self.games_per_round,
            max_moves=self.max_moves, temperature=self.temperature,
            rank=self.rank,
            seed=int(np.random.SeedSequence(
                (self.seed, self.actor_id, self.round)).generate_state(1)[0]),
            engine=self.engine, move_selector=self._move_selector)
        ingested = positions = 0
        for g in games:
            if not g.moves:
                continue  # an immediate double pass carries no training data
            packed, meta = game_records(g, self.rank, self.rank)
            winner = (area_score(g.stones, komi=self.komi).winner
                      if g.passes >= 2 else 0)
            gid = self.buffer.ingest_game(packed, meta, winner=winner,
                                          source=f"actor-{self.actor_id}")
            ingested += 1
            positions += len(g.moves)
            self.games_acked += 1
            self._obs_games.inc(1)
            self._obs_positions.inc(len(g.moves))
            if self._metrics is not None:
                # the lineage chain's leaf: game gid -> its producer.
                # `cli trace RUN_DIR champion` joins these against the
                # seal/window/gate records to answer "which games
                # trained the champion currently serving"
                self._metrics.write(
                    "lineage_game", gid=gid, positions=len(g.moves),
                    winner=winner, source=f"actor-{self.actor_id}",
                    round=self.round)
        record = {
            "actor": self.actor_id,
            "round": self.round,
            "games": ingested,
            "positions": positions,
            "seconds": round(time.monotonic() - t0, 3),
            "positions_per_sec": stats["positions_per_sec"],
        }
        if self._metrics is not None:
            self._metrics.write("loop_actor_round", **record)
        self.round += 1
        return record

    def run_forever(self, stop: threading.Event) -> None:
        """The component body the loop supervisor runs: rounds until
        stopped. Exceptions propagate — restart policy is the
        supervisor's job, not the actor's."""
        while not stop.is_set():
            self.run_round()
