"""The always-on expert-iteration service: the loop that runs forever.

``ExpertIterationLoop`` wires the four components into one supervised,
long-running pipeline — the unification of ``tools/r5_value_loop.sh``'s
hand-sequenced stages into a service where (FireCaffe's framing,
arXiv:1511.00175) every component stays saturated concurrently instead
of barrier-stepping through phases:

    actors ──games──▶ replay buffer ──windows──▶ learner
      ▲                                             │ challenger ckpt
      │  fleet.reload (champion hot-swap)           ▼
    serving fleet ◀──publish+reload── arena gatekeeper

  * N selfplay actors submit boards on the fleet's ``selfplay`` tier and
    durably ingest finished games (loop/actors.py);
  * the replay buffer seals games into window-versioned segments while
    the learner reads (loop/replay.py);
  * the continuous learner trains a window per cycle over a frozen,
    cursor-pinned extent and atomically publishes each window's
    challenger checkpoint (loop/learner.py — bit-exact auto-resume);
  * the arena gatekeeper promotes a challenger only on a >= 55% win rate
    against the incumbent, then hot-reloads the fleet in place
    (loop/gatekeeper.py).

Every component runs under the same restart discipline the serving
supervisor established (PR 3): a component crash is caught, counted,
logged as a ``loop_restart`` event, backed off with bounded full jitter,
and re-run — the actor replays its round, the learner auto-resumes
bit-exactly from its checkpoint + cursor, the gatekeeper re-gates the
re-queued challenger. A component that exhausts its restart budget stops
the loop with its error recorded; ``GateRejected`` is a counted outcome,
never a restart. Progress is watched: a loop where nothing has been
ingested, trained, or gated inside ``stall_timeout_s`` raises a typed
``LoopStalled``. Chaos: ``bench.py --mode loop --faults`` kills an
actor (``loop_ingest``), the learner (``train_step``), and a fleet
replica (``serving_dispatch``) in one soak and asserts zero lost games,
a bit-exact learner resume, and a newer served champion.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import random
import threading
import time

import jax

from ..analysis.lockcheck import make_lock
from ..experiments import ExperimentConfig
from ..experiments import checkpoint as ckpt
from ..models import policy_cnn
from ..obs import get_registry
from ..serving import (EngineConfig, FleetConfig, SupervisorConfig,
                       fleet_policy_engine, ladder_for)
from ..serving.resilience import full_jitter_delay
from ..training.optimizers import OPTIMIZERS
from ..utils import MetricsWriter
from .actors import SelfplayActor
from .gatekeeper import ArenaGatekeeper, GateRejected
from .learner import ContinuousLearner, LoopStalled
from .replay import ReplayBuffer, count_durable_games

CHAMPION_NAME = "champion.npz"
CHALLENGER_NAME = "challenger.npz"


@dataclasses.dataclass(frozen=True)
class LoopConfig:
    """Knobs for one ExpertIterationLoop (the learner's model/optimizer
    knobs ride in an ExperimentConfig, same override grammar as train)."""

    actors: int = 2
    fleet: int = 2
    games_per_round: int = 8
    max_moves: int = 120
    temperature: float = 0.25
    rank: int = 8
    komi: float = 7.5
    # actors: search_sims > 0 = AlphaZero-style search-selfplay (each
    # move a PUCT search over the fleet's selfplay tier; docs/search.md)
    search_sims: int = 0
    # learner
    steps_per_window: int = 50
    min_window_positions: int = 512
    scheme: str = "game"
    keep_checkpoints: int = 0  # 0 = keep all (offline window replay needs
    #                            window-start checkpoints)
    # buffer
    segment_games: int = 16
    capacity_positions: int = 0
    # gate
    gate_games: int = 32
    gate_threshold: float = 0.55
    gate_through_fleet: bool = True
    # run shape
    windows: int = 0          # stop after N completed windows (0 = forever)
    duration_s: float = 0.0   # stop after S seconds (0 = no time limit)
    # supervision
    max_component_restarts: int = 8
    restart_base_s: float = 0.05
    restart_cap_s: float = 2.0
    stall_timeout_s: float = 600.0
    # chaos: replica supervisors' restart budget (None = supervisor
    # default; the chaos soak passes 0 so a dispatcher kill crosses into
    # the FLEET failure domain — failover + respawn — like bench --fleet)
    replica_max_restarts: int | None = None
    max_wait_ms: float = 2.0
    seed: int = 0
    # request-scoped tracing (obs/tracing.py): arm the exemplar sampler
    # over the fleet's serving path, streaming trace_request records to
    # <run_dir>/trace.jsonl so `cli trace` can render waterfalls offline
    trace: bool = False
    # the fleet telemetry plane (obs/timeseries.py + obs/anomaly.py):
    # sample the registry into <run_dir>/ts-NNNN.jsonl on this cadence
    # and stream the anomaly watchlist over it — anomaly events land in
    # loop.jsonl, pin their series window in the store, and trip the
    # flight recorder; `cli dash RUN_DIR` renders the history live
    telemetry: bool = False
    telemetry_interval_s: float = 1.0


class ExpertIterationLoop:
    """Supervisor + wiring for the four loop components.

    ``run_dir`` owns everything durable: ``buffer/`` (the replay buffer),
    ``learner/`` (rolling checkpoints + cursor + windows.jsonl),
    ``champion.npz`` (what the fleet serves; the ``cli serve --watch``
    hook in a split deployment), ``challenger.npz`` (the learner's latest
    publish), ``loop.jsonl`` (the event stream). Re-running the identical
    command over the same run_dir resumes the loop from wherever any
    number of kills left it."""

    def __init__(self, run_dir: str, config: LoopConfig | None = None,
                 learner_config: ExperimentConfig | None = None,
                 seed_checkpoint: str | None = None):
        self.config = config or LoopConfig()
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self.champion_path = os.path.join(run_dir, CHAMPION_NAME)
        self.challenger_path = os.path.join(run_dir, CHALLENGER_NAME)
        self.metrics = MetricsWriter(os.path.join(run_dir, "loop.jsonl"))
        self._trace_sink = None
        if self.config.trace:
            from ..obs import JsonlSink, configure_tracing

            self._trace_sink = JsonlSink(os.path.join(run_dir,
                                                      "trace.jsonl"))
            configure_tracing(sink=self._trace_sink)
        self._sampler = None
        self._detector = None
        if self.config.telemetry:
            from ..obs import (AnomalyDetector, TelemetrySampler,
                               TimeSeriesStore, set_live_store)

            ts_store = TimeSeriesStore(run_dir)
            self._detector = AnomalyDetector(sink=self.metrics,
                                             store=ts_store)
            self._sampler = TelemetrySampler(
                ts_store, interval_s=self.config.telemetry_interval_s,
                listeners=[self._detector.observe])
            set_live_store(ts_store)
        self._stop = threading.Event()
        self._learner_done = threading.Event()
        self._gate_queue: queue.Queue = queue.Queue()
        self._rng = random.Random(self.config.seed)
        self._lock = make_lock("loop.service")
        self.restarts: dict[str, int] = {}
        self.fatal: dict[str, str] = {}
        self.gates_rejected = 0
        self._progress = time.monotonic()
        reg = get_registry()
        self._obs_restarts = reg.counter(
            "deepgo_loop_component_restarts_total",
            "loop component crashes absorbed by the supervisor")
        self._obs_stalls = reg.counter(
            "deepgo_loop_stalls_total",
            "typed LoopStalled events (a stage starved past its budget)")

        lcfg = learner_config or ExperimentConfig(name="loop-learner")
        bootstrap_source = self._ensure_champion(lcfg, seed_checkpoint)
        _, self._champ_params, self._model_cfg = _load_champion(
            self.champion_path)
        if bootstrap_source is not None:
            # the provenance chain's root for a brand-new run: a champion
            # that was NOT earned through a gate (seed checkpoint or
            # fresh init), so `cli trace RUN_DIR champion` can say where
            # the incumbent came from even before the first gate pass
            from .learner import params_digest

            self.metrics.write(
                "lineage_champion", digest=params_digest(self._champ_params),
                step=ckpt.load_meta(self.champion_path).get("step"),
                path=self.champion_path, source=bootstrap_source)
        cfg = self.config
        sup = (None if cfg.replica_max_restarts is None
               else SupervisorConfig(max_restarts=cfg.replica_max_restarts,
                                     backoff_base_s=0.01,
                                     backoff_cap_s=0.1))
        self.fleet = fleet_policy_engine(
            self._champ_params, self._model_cfg, replicas=cfg.fleet,
            config=EngineConfig(
                buckets=ladder_for(cfg.games_per_round * cfg.actors).buckets,
                max_wait_ms=cfg.max_wait_ms),
            fleet=FleetConfig(default_tier="selfplay"),
            supervisor=sup, metrics=self.metrics, name="loop-fleet")
        self.buffer = ReplayBuffer(
            os.path.join(run_dir, "buffer"),
            segment_games=cfg.segment_games,
            capacity_positions=cfg.capacity_positions, metrics=self.metrics)
        self.learner = ContinuousLearner(
            self.buffer, os.path.join(run_dir, "learner"), lcfg,
            steps_per_window=cfg.steps_per_window,
            min_window_positions=cfg.min_window_positions,
            scheme=cfg.scheme, publish_path=self.challenger_path,
            seed_checkpoint=self.champion_path,
            stall_timeout_s=cfg.stall_timeout_s,
            keep_checkpoints=cfg.keep_checkpoints, metrics=self.metrics)
        self.gatekeeper = ArenaGatekeeper(
            self.champion_path, games=cfg.gate_games,
            threshold=cfg.gate_threshold, max_moves=cfg.max_moves,
            komi=cfg.komi, fleet=self.fleet,
            engine=self.fleet if cfg.gate_through_fleet else None,
            metrics=self.metrics)
        self.actors = [
            SelfplayActor(i, self.buffer, self.fleet,
                          games_per_round=cfg.games_per_round,
                          max_moves=cfg.max_moves,
                          temperature=cfg.temperature, rank=cfg.rank,
                          komi=cfg.komi, seed=cfg.seed,
                          metrics=self.metrics,
                          search_sims=cfg.search_sims)
            for i in range(cfg.actors)
        ]

    # -- bootstrap ---------------------------------------------------------

    def _ensure_champion(self, lcfg: ExperimentConfig,
                         seed_checkpoint: str | None) -> str | None:
        """The loop needs an incumbent before anything runs: an existing
        champion.npz wins (the loop is resuming), else the seed
        checkpoint is published into the slot, else a fresh random init
        (step 0 — any trained challenger should eventually beat it).
        Returns the bootstrap source ("seed" / "init") when a NEW
        champion was published, None on resume — the lineage root
        event is only written for champions this call created."""
        if os.path.exists(self.champion_path):
            ckpt.verify_checkpoint(self.champion_path)
            return None
        if seed_checkpoint:
            from .gatekeeper import publish_checkpoint

            ckpt.verify_checkpoint(seed_checkpoint)
            publish_checkpoint(seed_checkpoint, self.champion_path)
            return "seed"
        model_cfg = lcfg.model_config()
        params = policy_cnn.init(jax.random.key(lcfg.seed), model_cfg)
        opt = OPTIMIZERS[lcfg.optimizer]
        optimizer = (opt(lcfg.rate, lcfg.rate_decay, lcfg.momentum)
                     if lcfg.optimizer == "sgd" else opt(lcfg.rate))
        ckpt.save_checkpoint(self.champion_path, params,
                             optimizer.init(params), {
                                 "id": "loop-seed", "step": 0,
                                 "validation_history": [],
                                 "config": lcfg.to_dict(),
                             })
        return "init"

    # -- supervision -------------------------------------------------------

    def _note_progress(self) -> None:
        with self._lock:
            self._progress = time.monotonic()

    def _supervised(self, name: str, body) -> None:
        """Run one component body under the loop restart discipline."""
        attempts = 0
        while not self._stop.is_set():
            try:
                body()
                return  # clean completion (learner hit its window target)
            except Exception as e:  # noqa: BLE001 — the supervisor's job
                if self._stop.is_set():
                    return
                attempts += 1
                with self._lock:
                    self.restarts[name] = self.restarts.get(name, 0) + 1
                self._obs_restarts.inc(1, component=name.split("-")[0])
                if isinstance(e, LoopStalled):
                    self._obs_stalls.inc(1)
                self.metrics.write("loop_restart", component=name,
                                   attempt=attempts,
                                   error=f"{type(e).__name__}: {e}")
                if attempts > self.config.max_component_restarts:
                    with self._lock:
                        self.fatal[name] = f"{type(e).__name__}: {e}"
                    self.metrics.write("loop_fatal", component=name,
                                       error=f"{type(e).__name__}: {e}")
                    self._stop.set()
                    return
                time.sleep(full_jitter_delay(
                    attempts - 1, self.config.restart_base_s,
                    self.config.restart_cap_s, self._rng))

    # -- component bodies --------------------------------------------------

    def _actor_body(self, actor: SelfplayActor):
        def body() -> None:
            while not self._stop.is_set() and not self._learner_done.is_set():
                actor.run_round()
                self._note_progress()
        return body

    def _learner_body(self) -> None:
        # auto-resume from disk FIRST: after a mid-window crash the
        # in-memory params are ahead of the durable truth; the checkpoint
        # + cursor replay the interrupted window bit-exactly
        self.learner.reload_state()
        target = self.config.windows
        while not self._stop.is_set():
            if target and self.learner.window >= target:
                break
            record = self.learner.train_window(stop=self._stop)
            if record is None:  # stop fired mid-window
                return
            self._note_progress()
            self._gate_queue.put((record["window"], self.challenger_path))
        self._learner_done.set()

    def _gatekeeper_body(self) -> None:
        while True:
            try:
                window, path = self._gate_queue.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                if self._learner_done.is_set():
                    return  # queue drained, nothing more is coming
                continue
            try:
                self.gatekeeper.evaluate(path)
            except GateRejected as e:
                # a counted outcome, not a crash: the incumbent keeps
                # serving, the next window gets its own gate
                with self._lock:
                    self.gates_rejected += 1
                self.metrics.write("loop_gate_rejected", window=window,
                                   win_rate=round(e.win_rate, 4))
            except Exception:
                # crash mid-gate (injected loop_gate fault, a wedged
                # match): re-queue the challenger so the restarted
                # component re-gates it instead of dropping the window
                self._gate_queue.put((window, path))
                raise
            self._note_progress()

    # -- the run -----------------------------------------------------------

    def run(self) -> dict:
        """Start every component, watch progress, return the summary.

        Stops when: the learner reached ``config.windows`` and the gate
        queue drained; ``config.duration_s`` elapsed; ``stop()`` was
        called; or a component went fatal. Either way every thread is
        joined, the fleet is closed, and the summary is both returned
        and written as the ``loop_close`` event."""
        cfg = self.config
        self.fleet.warmup()
        self.metrics.write(
            "loop_start", actors=cfg.actors, fleet=cfg.fleet,
            steps_per_window=cfg.steps_per_window, windows=cfg.windows,
            gate_games=cfg.gate_games, gate_threshold=cfg.gate_threshold,
            resumed_from=self.learner.resumed_from,
            buffer=self.buffer.stats())
        threads = [
            threading.Thread(target=self._supervised,
                             args=(f"actor-{a.actor_id}",
                                   self._actor_body(a)),
                             name=f"loop-actor-{a.actor_id}", daemon=True)
            for a in self.actors
        ]
        threads.append(threading.Thread(
            target=self._supervised, args=("learner", self._learner_body),
            name="loop-learner", daemon=True))
        threads.append(threading.Thread(
            target=self._supervised,
            args=("gatekeeper", self._gatekeeper_body),
            name="loop-gatekeeper", daemon=True))
        t0 = time.monotonic()
        if self._sampler is not None:
            self._sampler.start()
        for t in threads:
            t.start()
        try:
            while any(t.is_alive() for t in threads):
                if self._stop.is_set():
                    break
                if cfg.duration_s and time.monotonic() - t0 >= cfg.duration_s:
                    self._stop.set()
                    break
                if (self._learner_done.is_set()
                        and not threads[-1].is_alive()):
                    break  # windows target met and the gate queue drained
                with self._lock:
                    idle = time.monotonic() - self._progress
                if idle > cfg.stall_timeout_s:
                    self._obs_stalls.inc(1)
                    self.metrics.write("loop_stall", idle_s=round(idle, 1))
                    self._stop.set()
                    self.fatal["loop"] = (
                        f"LoopStalled: no ingest/window/gate progress for "
                        f"{idle:.0f}s")
                    break
                self.gatekeeper.champion_age_s()
                time.sleep(0.05)
        finally:
            self._stop.set()
            self._learner_done.set()
            for t in threads:
                t.join(timeout=30)
            if self._sampler is not None:
                # one final sample after the threads are down: the
                # close-time state rides in the history like obs_snapshot
                self._sampler.stop(final_sample=True)
                self._sampler.store.close()
            summary = self.summary()
            summary["seconds"] = round(time.monotonic() - t0, 3)
            if self._trace_sink is not None:
                from ..obs import get_trace_recorder

                rec = get_trace_recorder()
                if rec is not None:
                    summary["tracing"] = rec.stats()
            self.metrics.write("loop_close", **summary)
            self.fleet.close()
            if self._trace_sink is not None:
                self._trace_sink.close()
            self.metrics.close()
        if self.fatal.get("loop", "").startswith("LoopStalled"):
            raise LoopStalled(self.fatal["loop"])
        return summary

    def stop(self) -> None:
        self._stop.set()

    def summary(self) -> dict:
        """Accounting snapshot. ``games_durable`` is re-read from the
        on-disk index — the acked-vs-durable comparison is the zero-
        lost-games proof the chaos soak asserts."""
        acked = sum(a.games_acked for a in self.actors)
        fleet_stats = self.fleet.stats()["fleet"]
        champ_step = None
        try:
            champ_step = ckpt.load_meta(self.champion_path).get("step")
        except ckpt.CheckpointError:
            pass
        return {
            "games_acked": acked,
            "games_durable": count_durable_games(self.buffer.dir),
            "windows_trained": self.learner.window,
            "learner_step": self.learner.step,
            "gates_passed": self.gatekeeper.gates_passed,
            "gates_rejected": self.gates_rejected,
            "champion_step": champ_step,
            "component_restarts": dict(self.restarts),
            "fleet_respawns": fleet_stats["respawns"],
            "fleet_failovers": fleet_stats["failovers"],
            "fleet_reloads": fleet_stats["reloads"],
            "buffer": self.buffer.stats(),
            "fatal": dict(self.fatal),
            **({"anomalies": self._detector.summary()}
               if self._detector is not None else {}),
        }


def _load_champion(path: str):
    from ..models.serving import load_policy

    return load_policy(path)
