"""Continuous learner: windowed training over the live replay buffer.

Trains forever in *windows* of K steps. At each window start the learner
freezes the buffer's sealed extent and durably records a **read cursor**
(``cursor.json``, written atomically BEFORE the first step of the
window); each step's batch is then a pure function of
``(seed, step, extent)`` via the step-indexed stream
(``data.loader.step_rng`` + ``make_step_batch``) over the frozen
``ReplayView``. That one ordering rule is the whole bit-exact-resume
story for a growing corpus:

  * killed mid-window → the newest checkpoint sits at the window's start
    step and the cursor pins the extent the window was using, so
    ``auto-resume`` retrains the window over the identical byte range —
    bit-identical to an uninterrupted run — no matter how many games
    actors sealed in the meantime;
  * killed between a window's checkpoint and the next cursor write → the
    resume freezes a fresh extent, exactly as the uninterrupted run
    would have at that same point in the ingestion schedule.

Each completed window atomically publishes a rolling
``checkpoint-{step:08d}.npz`` (format v2: CRC/SHA integrity, the PR 1
machinery — ``find_latest_valid`` is the resume path) whose meta carries
the loop state, appends a ``windows.jsonl`` record with a params digest
(the offline bit-exactness witness ``replay_window`` checks against),
and — when ``publish_path`` is set — atomically publishes the challenger
checkpoint for the arena gatekeeper. Fault sites: the per-step
``train_step`` / ``kill`` sites (the same chaos grammar training has
always had) fire inside the window loop.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

import jax
import numpy as np

from ..data.loader import make_step_batch
from ..experiments import ExperimentConfig
from ..experiments import checkpoint as ckpt
from ..models import policy_cnn
from ..obs import get_registry
from ..training import make_train_step
from ..training.optimizers import OPTIMIZERS
from ..utils import faults
from ..utils.atomicio import atomic_write_bytes
from ..utils.retry import retry_with_backoff
from .replay import ReplayBuffer, ReplayView

CURSOR_NAME = "cursor.json"
WINDOWS_NAME = "windows.jsonl"


class LoopError(RuntimeError):
    """Base for typed expert-iteration-loop failures."""


class LoopStalled(LoopError):
    """A loop stage made no progress inside its stall budget (e.g. the
    learner waited past its deadline for the buffer to reach the minimum
    window extent — dead actors, or a wedged fleet upstream of them)."""


def params_digest(params) -> str:
    """SHA-256 over every leaf's dtype/shape/bytes in tree order — the
    bitwise identity two training runs must share to count as bit-exact."""
    digest = hashlib.sha256()
    for leaf in jax.tree.leaves(params):
        arr = np.asarray(leaf)
        digest.update(str(arr.dtype).encode())
        digest.update(repr(tuple(arr.shape)).encode())
        digest.update(np.ascontiguousarray(arr).tobytes())
    return digest.hexdigest()


class ContinuousLearner:
    """Windowed trainer over a ReplayBuffer with elastic auto-resume.

    ``config`` is a plain ExperimentConfig (model/optimizer/batch/seed —
    data_root is unused; the buffer IS the dataset). The stored config
    wins on resume, same contract as ``Experiment.auto_resume``.
    """

    def __init__(self, buffer: ReplayBuffer, run_dir: str,
                 config: ExperimentConfig,
                 steps_per_window: int = 50,
                 min_window_positions: int = 512,
                 scheme: str = "game",
                 publish_path: str | None = None,
                 seed_checkpoint: str | None = None,
                 stall_timeout_s: float = 300.0,
                 keep_checkpoints: int = 0,
                 metrics=None, clock=time.monotonic, sleep=time.sleep):
        self.buffer = buffer
        self.run_dir = run_dir
        self.steps_per_window = steps_per_window
        self.min_window_positions = min_window_positions
        self.scheme = scheme
        self.publish_path = publish_path
        self.stall_timeout_s = stall_timeout_s
        self.keep_checkpoints = keep_checkpoints
        self._metrics = metrics
        self._clock = clock
        self._sleep = sleep
        self._seed_checkpoint = seed_checkpoint
        os.makedirs(run_dir, exist_ok=True)
        reg = get_registry()
        self._obs_windows = reg.counter(
            "deepgo_loop_windows_trained_total",
            "completed learner training windows (checkpoint published)")
        self._obs_step_gauge = reg.gauge(
            "deepgo_loop_learner_step", "the learner's global step")
        self._resume(config, seed_checkpoint)

    # -- state / resume ----------------------------------------------------

    def _build(self, config: ExperimentConfig) -> None:
        self.config = config
        self.model_cfg = config.model_config()
        opt_fn = OPTIMIZERS[config.optimizer]
        self.optimizer = (opt_fn(config.rate, config.rate_decay,
                                 config.momentum)
                          if config.optimizer == "sgd"
                          else opt_fn(config.rate))
        self.train_step = make_train_step(self.model_cfg, self.optimizer)

    def _resume(self, config: ExperimentConfig,
                seed_checkpoint: str | None) -> None:
        """find_latest_valid over the learner dir (corrupt checkpoints are
        skipped with a logged reason); else seed from the champion
        checkpoint's params; else fresh init."""
        path = ckpt.find_latest_valid(self.run_dir)
        if path is not None:
            meta, p_leaves, o_leaves = ckpt.load_checkpoint(path)
            self._build(ExperimentConfig.from_dict(meta["config"]))
            template_p = policy_cnn.init(jax.random.key(self.config.seed),
                                         self.model_cfg)
            template_o = self.optimizer.init(template_p)
            self.params = ckpt.unflatten_like(template_p, p_leaves, path)
            self.opt_state = ckpt.unflatten_like(template_o, o_leaves, path)
            self.step = int(meta["step"])
            self.ewma = meta.get("ewma")
            self.window = int(meta.get("loop", {}).get("window", 0))
            self.resumed_from = path
            return
        self._build(config)
        self.resumed_from = None
        if seed_checkpoint:
            meta, p_leaves, _ = ckpt.load_checkpoint(seed_checkpoint)
            template_p = policy_cnn.init(jax.random.key(config.seed),
                                         self.model_cfg)
            self.params = ckpt.unflatten_like(template_p, p_leaves,
                                              seed_checkpoint)
            # a fresh optimizer over inherited weights: the champion's
            # opt_state belongs to ITS run; the challenger's momentum
            # history starts here
            self.step = int(meta.get("step", 0))
            self.resumed_from = seed_checkpoint
        else:
            self.params = policy_cnn.init(jax.random.key(config.seed),
                                          self.model_cfg)
            self.step = 0
        self.opt_state = self.optimizer.init(self.params)
        self.ewma = None
        self.window = 0
        # a fresh start durably records its own step-0 boundary: a kill
        # inside the very FIRST window then resumes from this checkpoint
        # plus the cursor (bit-exact, like every later window), and the
        # offline replay witness has a start state for window 1
        self._save_checkpoint(0, 0, -1)

    def reload_state(self) -> None:
        """Discard in-memory training state and auto-resume from disk —
        what a crashed-and-restarted learner MUST do before training
        again: after a mid-window death the in-memory params sit at some
        arbitrary step while the durable truth is the last window-boundary
        checkpoint plus the cursor. Idempotent (a fresh start already
        wrote its step-0 boundary, so this always lands on a checkpoint);
        the loop supervisor calls it at every learner (re)start."""
        self._resume(self.config, self._seed_checkpoint)

    # -- the cursor --------------------------------------------------------

    def _cursor_path(self) -> str:
        return os.path.join(self.run_dir, CURSOR_NAME)

    def _load_cursor(self) -> dict | None:
        try:
            with open(self._cursor_path()) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError, OSError):
            return None  # absent or torn: freeze a fresh extent

    def _freeze_extent(self, stop=None) -> tuple[int, int, int]:
        """The window's extent: the cursor's, when it pins THIS step (a
        resume of an interrupted window); otherwise a freshly frozen
        sealed span, durably recorded before any step runs."""
        cursor = self._load_cursor()
        if cursor is not None and cursor.get("step") == self.step \
                and cursor.get("seed") == self.config.seed:
            lo, hi = cursor["extent"]
            return int(lo), int(hi), int(cursor.get("version", -1))
        lo, hi, version = self._await_buffer(stop)
        cursor = {"window": self.window, "step": self.step,
                  "steps": self.steps_per_window,
                  "extent": [lo, hi], "version": version,
                  "seed": self.config.seed,
                  "batch_size": self.config.batch_size,
                  "scheme": self.scheme}
        atomic_write_bytes(self._cursor_path(), json.dumps(cursor).encode())
        return lo, hi, version

    def _await_buffer(self, stop=None) -> tuple[int, int, int]:
        """Block until the sealed span can feed a window; seal a starved
        partial segment rather than waiting for actors to fill it. Past
        the stall budget this raises a typed LoopStalled — the signal
        that the PRODUCERS are dead, which a learner restart cannot fix
        but the loop supervisor can see and count."""
        deadline = self._clock() + self.stall_timeout_s
        while True:
            lo, hi, version = self.buffer.extent()
            if hi - lo >= self.min_window_positions:
                return lo, hi, version
            # enough ingested but not yet compacted: seal what exists
            if (hi - lo) + self.buffer.open_positions \
                    >= self.min_window_positions:
                self.buffer.seal()
                continue
            if stop is not None and stop.is_set():
                raise LoopStalled("stop requested while awaiting buffer")
            if self._clock() >= deadline:
                raise LoopStalled(
                    f"buffer stuck at {hi - lo} sealed positions "
                    f"(+{self.buffer.open_positions} open) after "
                    f"{self.stall_timeout_s:.0f}s; window needs "
                    f"{self.min_window_positions} — are the actors dead?")
            self._sleep(0.05)

    # -- training ----------------------------------------------------------

    def train_window(self, stop=None) -> dict | None:
        """One window: freeze extent → K deterministic steps → atomic
        checkpoint + windows.jsonl record + challenger publish. Returns
        the window record, or None when ``stop`` fired mid-window (state
        is then exactly a kill's: resume retrains the window)."""
        lo, hi, version = self._freeze_extent(stop)
        view = self.buffer.view(lo, hi)
        step0 = self.step
        t0 = self._clock()
        ewma = self.ewma
        last_loss = float("nan")
        losses = []  # device-resident; fetched once at the window fence
        for t in range(step0, step0 + self.steps_per_window):
            if stop is not None and stop.is_set():
                return None
            batch = make_step_batch(view, self.config.seed, t,
                                    self.config.batch_size,
                                    scheme=self.scheme)
            faults.check("train_step")
            self.params, self.opt_state, loss = self.train_step(
                self.params, self.opt_state, jax.device_put(batch))
            losses.append(loss)
            self.step = t + 1
            faults.check("kill", step=self.step)
        # window-boundary fetch is the DECLARED materialization point:
        # one d2h per window where a per-step float(loss) used to fence
        # every dispatch (same floats folded in the same order, so the
        # checkpointed EWMA — and bit-exact resume — are unchanged)
        # lint: allow[hot-sync] declared materialization point: one fetch per window, was a per-step pipeline stall
        for last_loss in (float(np.asarray(x)) for x in losses):
            ewma = (last_loss if ewma is None
                    else 0.95 * ewma + 0.05 * last_loss)
        self.ewma = ewma
        self.window += 1
        digest = params_digest(self.params)
        path = self._save_checkpoint(lo, hi, version)
        record = {
            "window": self.window,
            "step0": step0,
            "step1": self.step,
            "extent": [lo, hi],
            "version": version,
            "scheme": self.scheme,
            "digest": digest,
            "ewma": ewma,
            "loss": last_loss,
            "seconds": round(self._clock() - t0, 3),
            "checkpoint": path,
        }
        with open(os.path.join(self.run_dir, WINDOWS_NAME), "a") as f:
            f.write(json.dumps(record) + "\n")
        if self.publish_path:
            self.publish(self.publish_path)
            record["published"] = self.publish_path
        self._obs_windows.inc(1)
        self._obs_step_gauge.set(self.step)
        if self._metrics is not None:
            self._metrics.write("loop_window", **{
                k: v for k, v in record.items() if k != "checkpoint"})
            # the lineage chain's extent->window->checkpoint join: the
            # params_digest here is the identity the gatekeeper's verdict
            # and the champion publish carry forward, so provenance walks
            # champion -> gate -> THIS window -> extent -> segments
            self._metrics.write("lineage_window", window=self.window,
                                step0=step0, step1=self.step,
                                extent=[lo, hi], version=version,
                                scheme=self.scheme, digest=digest,
                                checkpoint=path)
        return record

    def _meta(self) -> dict:
        return {
            "id": "loop-learner",
            "step": self.step,
            "validation_history": [],
            "ewma": self.ewma,
            "config": self.config.to_dict(),
            "loop": {"window": self.window},
        }

    def _save_checkpoint(self, lo: int, hi: int, version: int) -> str:
        path = os.path.join(self.run_dir, ckpt.checkpoint_name(self.step))
        meta = self._meta()
        meta["loop"].update(extent=[lo, hi], version=version)
        # transient I/O is retried; a persistently failing periodic save
        # surfaces — unlike Experiment's in-loop save, the loop's windows
        # ARE the publish cadence, so silently skipping one would stall
        # the gatekeeper with no visible cause
        retry_with_backoff(
            lambda: ckpt.save_checkpoint(path, self.params, self.opt_state,
                                         meta),
            attempts=3, base_delay=0.1)
        self._apply_retention()
        return path

    def _apply_retention(self) -> None:
        keep = self.keep_checkpoints
        if keep <= 0:
            return
        entries = ckpt.list_checkpoints(self.run_dir)
        for s, p in entries[:-keep]:
            try:
                os.remove(p)
            except OSError:
                pass

    def publish(self, path: str) -> str:
        """Atomically publish the current state as a challenger
        checkpoint: save_checkpoint rides utils.atomicio, so a watcher
        (the gatekeeper, or ``cli serve --watch``) can never observe a
        partial file — only old-complete or new-complete."""
        ckpt.save_checkpoint(path, self.params, self.opt_state, self._meta())
        return path

    # -- offline bit-exactness witness ------------------------------------


def replay_window(run_dir: str, buffer: ReplayBuffer, record: dict) -> str:
    """Re-train one recorded window from its start checkpoint, offline,
    and return the resulting params digest.

    This is the independent witness the chaos soak compares against the
    learner's own ``windows.jsonl`` digest: the replay is itself an
    uninterrupted run over the recorded extent, so digest equality proves
    the (possibly killed-and-resumed) live window was bit-exact."""
    path = os.path.join(run_dir, ckpt.checkpoint_name(record["step0"]))
    meta, p_leaves, o_leaves = ckpt.load_checkpoint(path)
    config = ExperimentConfig.from_dict(meta["config"])
    model_cfg = config.model_config()
    opt_fn = OPTIMIZERS[config.optimizer]
    optimizer = (opt_fn(config.rate, config.rate_decay, config.momentum)
                 if config.optimizer == "sgd" else opt_fn(config.rate))
    template_p = policy_cnn.init(jax.random.key(config.seed), model_cfg)
    params = ckpt.unflatten_like(template_p, p_leaves, path)
    opt_state = ckpt.unflatten_like(optimizer.init(template_p), o_leaves,
                                    path)
    step_fn = make_train_step(model_cfg, optimizer)
    lo, hi = record["extent"]
    view: ReplayView = buffer.view(int(lo), int(hi))
    for t in range(int(record["step0"]), int(record["step1"])):
        batch = make_step_batch(view, config.seed, t, config.batch_size,
                                scheme=record.get("scheme", "game"))
        params, opt_state, _ = step_fn(params, opt_state,
                                       jax.device_put(batch))
    return params_digest(params)


def read_windows(run_dir: str) -> list[dict]:
    """The windows.jsonl records (torn final line tolerated, like every
    other JSONL consumer in the repo)."""
    out = []
    try:
        with open(os.path.join(run_dir, WINDOWS_NAME)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except FileNotFoundError:
        pass
    return out
