"""Bounded on-disk replay buffer: games in while training reads, bit-exactly.

The expert-iteration learner trains *while* selfplay actors append new
games, which breaks the static-corpus assumption every earlier data path
leaned on: ``GoDataset`` memmaps one immutable shard, and the step-indexed
loader's bit-exact-resume guarantee (``data.loader.step_rng``,
docs/robustness.md) only holds when step t samples from the same byte
range on every replay. This module restores both properties over a
*growing* corpus:

  append      ``ingest_game`` writes each finished game as its own
              fsync'd file (utils.atomicio) before acknowledging — an
              acked game survives any kill, which is what the chaos
              soak's "zero lost games" assertion actually checks.
  seal        open games compact into immutable *segments* (planes.bin /
              meta.npy / winner.npy, the GoDataset layout) in gid order;
              ``index.json`` is replaced atomically and its ``version``
              bumps once per seal — the window-versioned index the
              learner pins its read cursor against.
  extent      positions get a *logical index* that never changes once
              assigned (segment files record their [lo, hi) range).
              A ``ReplayView`` over a frozen extent is an immutable
              dataset: the learner freezes one per training window,
              records it in its checkpointed cursor, and a resumed run
              re-opens the identical byte range no matter how much the
              corpus grew in between — that is the whole bit-exact-resume
              story for a live buffer.
  bounded     ``evict(protect_lo)`` drops whole oldest segments once the
              sealed span exceeds ``capacity_positions``, but never past
              the learner's protected cursor — an extent a checkpoint
              still references cannot be deleted out from under a resume.

Crash recovery is a pure function of the directory: segment dirs not in
``index.json`` are half-built seals and are removed; open-game files at
or below the sealed gid watermark are duplicates of sealed data and are
removed; everything else is replayed into the in-memory state. Fault
site ``loop_ingest`` fires inside ``ingest_game`` (transients are
retried with the loader's backoff policy, hard faults surface to the
actor's supervisor — docs/robustness.md "Loop failure domains").
"""

from __future__ import annotations

import io
import json
import os
import shutil
import threading

import numpy as np

from ..analysis.lockcheck import make_rlock
from ..data.dataset import (M_BLACK_RANK, M_PLAYER, M_WHITE_RANK, M_X, M_Y,
                            META_COLS, RECORD_SHAPE)
from ..utils import faults
from ..utils.atomicio import atomic_write, atomic_write_bytes
from ..utils.retry import retry_with_backoff
from .. import BOARD_SIZE

INDEX_NAME = "index.json"
GAMES_DIR = "games"


class ReplayError(RuntimeError):
    """A replay-buffer invariant was violated (evicted extent, corrupt
    segment, meta/planes disagreement). Carries enough context to decide
    between 'operator bug' and 'disk corruption'."""


def _segment_name(seg_id: int) -> str:
    return f"seg-{seg_id:06d}"


def _game_name(gid: int) -> str:
    return f"g-{gid:08d}.npz"


class _Segment:
    """One sealed, immutable slice of the logical position space."""

    __slots__ = ("name", "dir", "lo", "hi", "first_gid", "last_gid",
                 "games", "_planes", "_meta", "_winner")

    def __init__(self, buffer_dir: str, entry: dict):
        self.name = entry["name"]
        self.dir = os.path.join(buffer_dir, self.name)
        self.lo = int(entry["lo"])
        self.hi = int(entry["hi"])
        self.first_gid = int(entry["first_gid"])
        self.last_gid = int(entry["last_gid"])
        self.games = entry["games"]  # [[gid, logical_start, count], ...]
        self._planes = None
        self._meta = None
        self._winner = None

    @property
    def positions(self) -> int:
        return self.hi - self.lo

    def _load(self) -> None:
        if self._planes is not None:
            return
        n = self.positions
        planes_path = os.path.join(self.dir, "planes.bin")
        try:
            self._planes = np.memmap(planes_path, dtype=np.uint8, mode="r",
                                     shape=(n, *RECORD_SHAPE))
            self._meta = np.load(os.path.join(self.dir, "meta.npy"))
            self._winner = np.load(os.path.join(self.dir, "winner.npy"))
        except (OSError, ValueError) as e:
            raise ReplayError(
                f"segment {self.dir} unreadable ({e}) — sealed segments "
                "are immutable, so this is disk damage, not a race") from e
        if self._meta.shape[0] != n or self._winner.shape[0] != n:
            raise ReplayError(
                f"segment {self.dir}: meta/winner rows "
                f"({self._meta.shape[0]}/{self._winner.shape[0]}) disagree "
                f"with the indexed position count {n}")

    def gather(self, local: np.ndarray):
        self._load()
        return self._planes[local], self._meta[local], self._winner[local]

    def entry(self) -> dict:
        return {"name": self.name, "lo": self.lo, "hi": self.hi,
                "first_gid": self.first_gid, "last_gid": self.last_gid,
                "games": self.games}


class ReplayView:
    """An immutable dataset over one frozen extent [lo, hi).

    Duck-types the slice of ``GoDataset`` the step-indexed loader uses
    (``sample_indices`` / ``batch_at`` / ``__len__`` / game ranges), so
    ``data.loader.make_step_batch`` — and with it the whole bit-exact
    deterministic stream — runs over the buffer unchanged. Indices are
    LOGICAL (stable across corpus growth and eviction), which is what a
    checkpointed cursor needs; sampling maps them into [lo, hi).
    """

    def __init__(self, segments: list[_Segment], lo: int, hi: int):
        if not segments:
            raise ReplayError(f"empty extent [{lo}, {hi}) — nothing sealed")
        self.lo = lo
        self.hi = hi
        self._segments = segments
        self._seg_los = np.array([s.lo for s in segments], dtype=np.int64)
        ranges = []
        for s in segments:
            for _, start, count in s.games:
                if start >= lo and start + count <= hi:
                    ranges.append((start, count))
        self.game_ranges = (np.array(ranges, dtype=np.int64)
                            if ranges else np.zeros((0, 2), np.int64))
        self._winner_positions: np.ndarray | None = None

    def __len__(self) -> int:
        return self.hi - self.lo

    @property
    def num_games(self) -> int:
        return len(self.game_ranges)

    def sample_indices(self, rng: np.random.Generator, n: int,
                       scheme: str = "game") -> np.ndarray:
        if scheme == "uniform":
            return self.lo + rng.integers(0, len(self), size=n)
        if scheme == "game":
            if self.num_games == 0:
                raise ReplayError(
                    f"extent [{self.lo}, {self.hi}) holds no whole game")
            games = rng.integers(0, self.num_games, size=n)
            starts = self.game_ranges[games, 0]
            counts = self.game_ranges[games, 1]
            return starts + (rng.random(n) * counts).astype(np.int64)
        if scheme == "winner":
            cand = self.winner_positions()
            return cand[rng.integers(0, cand.size, size=n)]
        raise ValueError(f"unknown sampling scheme {scheme!r}")

    def winner_positions(self) -> np.ndarray:
        """Logical indices whose side to move went on to win (decided
        games only) — the outcome-conditioned slice expert iteration
        distills from (tools/r3_lib.sh's scheme=winner, buffer-native)."""
        if self._winner_positions is None:
            out = []
            for s in self._segments:
                s._load()
                local = np.flatnonzero(
                    (s._winner == s._meta[:, M_PLAYER]) & (s._winner != 0))
                logical = local + s.lo
                out.append(logical[(logical >= self.lo)
                                   & (logical < self.hi)])
            cand = (np.concatenate(out) if out
                    else np.zeros(0, np.int64))
            if cand.size == 0:
                raise ReplayError(
                    f"scheme='winner': no decided-game positions in "
                    f"extent [{self.lo}, {self.hi})")
            self._winner_positions = cand
        return self._winner_positions

    def batch_at(self, indices: np.ndarray):
        """Gather (packed, player, rank, target), GoDataset.batch_at's
        contract over logical indices. Runs under the same loader_io
        fault site + bounded-jitter retry as the static-corpus gather."""
        indices = np.asarray(indices, dtype=np.int64)
        if indices.size and (indices.min() < self.lo
                             or indices.max() >= self.hi):
            raise ReplayError(
                f"index outside frozen extent [{self.lo}, {self.hi}): "
                f"min={indices.min()} max={indices.max()}")

        def gather():
            faults.check("loader_io")
            packed = np.empty((indices.size, *RECORD_SHAPE), np.uint8)
            meta = np.empty((indices.size, META_COLS), np.int32)
            seg_of = np.searchsorted(self._seg_los, indices, side="right") - 1
            for si in np.unique(seg_of):
                seg = self._segments[si]
                sel = np.flatnonzero(seg_of == si)
                p, m, _ = seg.gather(indices[sel] - seg.lo)
                packed[sel] = p
                meta[sel] = m
            return packed, meta

        packed, meta = retry_with_backoff(gather, attempts=5,
                                          base_delay=0.05, jitter=True)
        player = meta[:, M_PLAYER]
        rank = np.where(player == 1, meta[:, M_BLACK_RANK],
                        meta[:, M_WHITE_RANK])
        target = meta[:, M_X] * BOARD_SIZE + meta[:, M_Y]
        return (packed, player.astype(np.int32), rank.astype(np.int32),
                target.astype(np.int32))


class ReplayBuffer:
    """The writable front: durable per-game ingest, sealing, eviction.

    Thread-safe — every actor ingests concurrently and the learner
    freezes extents from another thread; sealed segments are immutable so
    views never need the lock.
    """

    def __init__(self, buffer_dir: str, segment_games: int = 64,
                 capacity_positions: int = 0, metrics=None):
        if segment_games < 1:
            raise ValueError(f"segment_games must be >= 1, got {segment_games}")
        self.dir = buffer_dir
        self.segment_games = segment_games
        self.capacity_positions = capacity_positions
        self._metrics = metrics
        # reentrant: the seal path re-enters through ingest bookkeeping
        self._lock = make_rlock("loop.replay")
        os.makedirs(os.path.join(buffer_dir, GAMES_DIR), exist_ok=True)
        self._recover()

    # -- recovery ----------------------------------------------------------

    def _index_path(self) -> str:
        return os.path.join(self.dir, INDEX_NAME)

    def _recover(self) -> None:
        """Rebuild in-memory state from the directory. index.json is the
        single source of truth for sealed data; anything else on disk is
        either an open game (kept) or debris from a torn seal (removed)."""
        try:
            with open(self._index_path()) as f:
                idx = json.load(f)
        except FileNotFoundError:
            idx = {"version": 0, "next_seg": 0, "base_lo": 0,
                   "sealed_hi": 0, "segments": []}
        except (OSError, ValueError) as e:
            raise ReplayError(
                f"{self._index_path()} unreadable ({e}) — the index is "
                "written atomically, so this is disk damage") from e
        self.version = int(idx["version"])
        self._next_seg = int(idx["next_seg"])
        self.base_lo = int(idx["base_lo"])
        self.sealed_hi = int(idx["sealed_hi"])
        self._segments = [_Segment(self.dir, e) for e in idx["segments"]]
        indexed = {s.name for s in self._segments}
        watermark = max((s.last_gid for s in self._segments), default=-1)
        for name in sorted(os.listdir(self.dir)):
            if name.startswith("seg-") and name not in indexed:
                # a seal that died before the index flip: its games are
                # still in games/ (deleted only after the flip), so the
                # half-built directory is pure debris
                shutil.rmtree(os.path.join(self.dir, name),
                              ignore_errors=True)
        self._open: list[tuple[int, str]] = []  # (gid, path), gid order
        gdir = os.path.join(self.dir, GAMES_DIR)
        for name in sorted(os.listdir(gdir)):
            if not name.startswith("g-") or not name.endswith(".npz"):
                continue
            gid = int(name[2:-4])
            path = os.path.join(gdir, name)
            if gid <= watermark:
                # sealed before the crash; the file is a duplicate
                os.remove(path)
            else:
                self._open.append((gid, path))
        self._next_gid = max(watermark,
                             max((g for g, _ in self._open), default=-1)) + 1

    # -- ingest ------------------------------------------------------------

    def ingest_game(self, packed: np.ndarray, meta: np.ndarray,
                    winner: int = 0, source: str = "") -> int:
        """Durably append one finished game; returns its gid once — and
        only once — the bytes are fsync'd under their final name. ``meta``
        is the (M, 6) transcription layout (game_id column rewritten);
        ``winner`` is 1 (black) / 2 (white) / 0 (undecided), feeding the
        scheme='winner' slice. Auto-seals a full segment."""
        m = int(packed.shape[0])
        if m == 0:
            raise ValueError("refusing to ingest a zero-move game")
        if packed.dtype != np.uint8 or packed.shape[1:] != RECORD_SHAPE:
            raise ValueError(
                f"packed must be (M, {RECORD_SHAPE}) uint8, got "
                f"{packed.dtype} {packed.shape}")
        if meta.shape != (m, META_COLS):
            raise ValueError(f"meta must be ({m}, {META_COLS}), got {meta.shape}")

        def write() -> int:
            faults.check("loop_ingest")
            with self._lock:
                gid = self._next_gid
                path = os.path.join(self.dir, GAMES_DIR, _game_name(gid))
                buf = io.BytesIO()
                np.savez(buf, packed=packed,
                         meta=meta.astype(np.int32),
                         winner=np.int32(winner))
                atomic_write_bytes(path, buf.getvalue())
                self._next_gid = gid + 1
                self._open.append((gid, path))
            return gid

        # transient injected (or real) I/O faults are absorbed exactly
        # like the loader's memmap gather; hard faults reach the actor's
        # supervisor with the game UN-acked (never half-ingested)
        gid = retry_with_backoff(write, attempts=5, base_delay=0.05,
                                 jitter=True)
        if self._metrics is not None:
            self._metrics.write("loop_ingest", gid=gid, positions=m,
                                winner=winner, source=source)
        with self._lock:
            if len(self._open) >= self.segment_games:
                self.seal()
        return gid

    # -- sealing -----------------------------------------------------------

    def seal(self) -> int | None:
        """Compact every open game into one immutable segment and bump the
        index version. Returns the new version, or None when nothing was
        open. Crash-safe: the index flip is the commit point — the segment
        files land first (atomic each), game files are deleted only after
        the flip, and recovery resolves every intermediate state."""
        with self._lock:
            if not self._open:
                return None
            open_games = list(self._open)
            seg_id = self._next_seg
            name = _segment_name(seg_id)
            seg_dir = os.path.join(self.dir, name)
            os.makedirs(seg_dir, exist_ok=True)
            planes_parts, meta_parts, winner_parts, games = [], [], [], []
            cursor = self.sealed_hi
            for gid, path in open_games:
                try:
                    with np.load(path) as z:
                        packed = z["packed"]
                        meta = z["meta"]
                        winner = int(z["winner"])
                except (OSError, ValueError, KeyError) as e:
                    raise ReplayError(
                        f"open game {path} unreadable ({e}) — ingest is "
                        "atomic, so this is disk damage") from e
                m = packed.shape[0]
                meta = meta.copy()
                meta[:, -1] = gid  # game-id column: the buffer-wide gid
                planes_parts.append(packed)
                meta_parts.append(meta)
                winner_parts.append(np.full(m, winner, np.int32))
                games.append([gid, cursor, m])
                cursor += m
            with atomic_write(os.path.join(seg_dir, "planes.bin")) as f:
                f.write(np.concatenate(planes_parts).tobytes())
            with atomic_write(os.path.join(seg_dir, "meta.npy")) as f:
                np.save(f, np.concatenate(meta_parts))
            with atomic_write(os.path.join(seg_dir, "winner.npy")) as f:
                np.save(f, np.concatenate(winner_parts))
            seg = _Segment(self.dir, {
                "name": name, "lo": self.sealed_hi, "hi": cursor,
                "first_gid": open_games[0][0],
                "last_gid": open_games[-1][0], "games": games,
            })
            self._segments.append(seg)
            self.sealed_hi = cursor
            self._next_seg = seg_id + 1
            self.version += 1
            self._write_index()  # THE commit point
            for _, path in open_games:
                try:
                    os.remove(path)
                except OSError:
                    pass  # recovery drops it via the gid watermark
            self._open = []
            if self._metrics is not None:
                self._metrics.write("loop_seal", segment=name,
                                    version=self.version,
                                    games=len(games),
                                    positions=seg.positions,
                                    sealed_hi=self.sealed_hi)
                # the lineage chain's game->segment join: which gids own
                # which logical position range, so a window's frozen
                # extent resolves back to the games inside it
                self._metrics.write("lineage_segment", segment=name,
                                    version=self.version,
                                    lo=seg.lo, hi=seg.hi,
                                    first_gid=seg.first_gid,
                                    last_gid=seg.last_gid,
                                    games=len(games))
            return self.version

    def _write_index(self) -> None:
        idx = {"version": self.version, "next_seg": self._next_seg,
               "base_lo": self.base_lo, "sealed_hi": self.sealed_hi,
               "segments": [s.entry() for s in self._segments]}
        atomic_write_bytes(self._index_path(),
                           json.dumps(idx).encode())

    # -- reading -----------------------------------------------------------

    def extent(self) -> tuple[int, int, int]:
        """(lo, hi, version) of the currently sealed span — what a
        learner freezes at a window start and records in its cursor."""
        with self._lock:
            return self.base_lo, self.sealed_hi, self.version

    def view(self, lo: int, hi: int) -> ReplayView:
        """An immutable dataset over [lo, hi). Raises ReplayError if the
        extent reaches below the eviction floor (a protect_lo bug) or
        above the sealed span (a cursor from the future)."""
        with self._lock:
            if lo < self.base_lo:
                raise ReplayError(
                    f"extent lo {lo} below eviction floor {self.base_lo} — "
                    "evict() ran past a live cursor")
            if hi > self.sealed_hi:
                raise ReplayError(
                    f"extent hi {hi} beyond sealed span {self.sealed_hi}")
            segs = [s for s in self._segments if s.hi > lo and s.lo < hi]
        return ReplayView(segs, lo, hi)

    # -- retention ---------------------------------------------------------

    def evict(self, protect_lo: int | None = None) -> int:
        """Drop whole oldest segments while the sealed span exceeds
        ``capacity_positions``, never crossing ``protect_lo`` (the oldest
        logical index a live cursor/checkpoint still references).
        Returns the number of segments dropped."""
        if self.capacity_positions <= 0:
            return 0
        dropped = 0
        with self._lock:
            while (len(self._segments) > 1
                   and self.sealed_hi - self.base_lo
                   > self.capacity_positions):
                victim = self._segments[0]
                if protect_lo is not None and victim.hi > protect_lo:
                    break
                self._segments.pop(0)
                self.base_lo = victim.hi
                self._write_index()
                shutil.rmtree(victim.dir, ignore_errors=True)
                dropped += 1
                if self._metrics is not None:
                    self._metrics.write("loop_evict", segment=victim.name,
                                        base_lo=self.base_lo)
        return dropped

    # -- accounting --------------------------------------------------------

    @property
    def total_games(self) -> int:
        with self._lock:
            return (sum(len(s.games) for s in self._segments)
                    + len(self._open))

    @property
    def open_positions(self) -> int:
        with self._lock:
            total = 0
            for _, path in self._open:
                with np.load(path) as z:
                    total += int(z["meta"].shape[0])
            return total

    def stats(self) -> dict:
        with self._lock:
            return {
                "version": self.version,
                "segments": len(self._segments),
                "sealed_positions": self.sealed_hi - self.base_lo,
                "sealed_hi": self.sealed_hi,
                "base_lo": self.base_lo,
                "open_games": len(self._open),
                "total_games": self.total_games,
            }


def count_durable_games(buffer_dir: str) -> int:
    """Games durably on disk, counted WITHOUT constructing a buffer (no
    recovery side effects — safe next to a live writer). Sealed games
    come from index.json; open games are the g-*.npz files above the
    sealed gid watermark. This fresh read is the zero-lost-games witness
    the chaos soak compares against the actors' acked counter."""
    try:
        with open(os.path.join(buffer_dir, INDEX_NAME)) as f:
            idx = json.load(f)
    except (FileNotFoundError, ValueError, OSError):
        idx = {"segments": []}
    sealed = sum(len(e["games"]) for e in idx["segments"])
    watermark = max((int(e["last_gid"]) for e in idx["segments"]),
                    default=-1)
    open_games = 0
    gdir = os.path.join(buffer_dir, GAMES_DIR)
    try:
        names = os.listdir(gdir)
    except FileNotFoundError:
        names = []
    for name in names:
        if name.startswith("g-") and name.endswith(".npz") \
                and int(name[2:-4]) > watermark:
            open_games += 1
    return sealed + open_games
