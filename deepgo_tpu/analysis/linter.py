"""AST invariant linter (``cli lint`` / ``make lint``).

Five per-file rules, each guarding a convention the system's headline
guarantees rest on (docs/static_analysis.md has the full table):

  * ``atomic-write`` — durable artifacts go through ``utils/atomicio``:
    a raw ``open(path, "w")`` destroys the previous contents the moment
    it runs, so a crash mid-write leaves a torn file where the recovery
    artifact used to be. Write-mode ``open`` and ``np.save``/``np.savez``
    straight to a path are findings; append-mode streams (JSONL sinks,
    torn-tail tolerant by design) are not.
  * ``determinism`` — step-indexed / replay / serving-dispatch modules
    must be pure functions of (seed, step): ``time.time()``, module-level
    ``random.*``, unseeded ``random.Random()``, and ``np.random`` global
    state are findings there (injectable ``clock=``/``rng=`` is the fix;
    ``np.random.default_rng(seed)`` and friends are fine).
  * ``thread-discipline`` — every ``threading.Thread`` carries ``name=``
    (leak reports and the lock sanitizer attribute by thread name) and
    is either ``daemon=`` or joined somewhere in its module.
  * ``typed-error`` — no bare ``except:`` anywhere; no ``assert`` in the
    service layers (typed errors must survive ``python -O``).
  * ``bare-sleep`` — no direct ``time.sleep`` in ``serving/``: a bare
    sleep in a dispatcher/router thread is an invisible stall — no span,
    no fault site, uninjectable under test. Delays go through an
    injected ``sleep=`` hook or a waitable event; chaos brownouts go
    through ``utils/faults.maybe_slow`` (the one legal sleep).

Findings carry file:line, rule id, and a fix hint. A narrow pragma
allowlist (``# lint: allow[RULE] reason`` — reason mandatory) admits
the rare legitimate exception without widening the rule.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from .config import NP_RANDOM_OK, PRAGMA_RE, RULES, LintConfig

_HINTS = {
    "atomic-write": "route the write through utils/atomicio.atomic_write",
    "determinism": "inject clock=/rng= (or np.random.default_rng(seed))",
    "thread-discipline": "threading.Thread(..., name=..., daemon=True) "
                         "or join() it",
    "typed-error": "raise a typed error (survives `python -O`); "
                   "catch specific exceptions",
    "bare-sleep": "inject a sleep= hook / wait on an Event; brownout "
                  "delays go through utils/faults.maybe_slow",
    "pragma": "pragmas need a reason: # lint: allow[RULE] why",
    "jit-boundary": "pass the state as an argument (or mark the scalar "
                    "static_argnames=); traced closures bake mutable "
                    "state at compile time",
    "hot-sync": "keep device values on device; materialize once at the "
                "declared point (pragma it with the reason)",
    "donation": "add donate_argnums to step-shaped jits; never read a "
                "donated buffer after the call",
    "constant-upload": "hoist jnp.asarray(CONST) out of the per-call fn "
                       "(factory scope / closure)",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    level: str  # "strict" | "warn"
    message: str

    @property
    def hint(self) -> str:
        return _HINTS.get(self.rule, "")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "level": self.level, "message": self.message,
                "hint": self.hint}

    def format(self) -> str:
        return (f"{self.path}:{self.line}: [{self.level}] {self.rule}: "
                f"{self.message}")


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dotted(node) -> str:
    """'np.random.seed' for an Attribute chain, '' when not a pure chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _FileChecker(ast.NodeVisitor):
    def __init__(self, rel: str, source: str, config: LintConfig):
        self.rel = rel
        self.source = source
        self.config = config
        self.findings: list[Finding] = []
        self._has_join = ".join(" in source
        self._det = config.in_scope(rel, config.determinism_scope)
        self._assert = config.in_scope(rel, config.assert_scope)
        self._atomic = rel not in config.atomic_exempt
        self._sleep = config.in_scope(rel, config.sleep_scope)
        self._sleep_aliases: set[str] = set()  # from time import sleep [as x]

    def _add(self, rule: str, node, message: str) -> None:
        self.findings.append(Finding(rule, self.rel, node.lineno,
                                     "strict", message))

    # -- atomic-write ------------------------------------------------------

    def _check_open(self, node: ast.Call) -> None:
        mode = None
        if len(node.args) >= 2:
            mode = _const_str(node.args[1])
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = _const_str(kw.value)
        if mode and any(c in mode for c in "wx"):
            self._add("atomic-write", node,
                      f'raw open(..., "{mode}") on a durable path '
                      "outside utils/atomicio")

    def _check_np_save(self, node: ast.Call, fn: str) -> None:
        if not node.args:
            return
        dst = node.args[0]
        # np.save(f, ...) into a handle (atomic_write body) is fine; a
        # path expression or literal bypasses the atomic writer
        if isinstance(dst, (ast.Name, ast.Attribute)):
            return
        self._add("atomic-write", node,
                  f"np.{fn} straight to a path bypasses utils/atomicio")

    # -- determinism -------------------------------------------------------

    def _check_determinism(self, node: ast.Call, dotted: str) -> None:
        if dotted == "time.time":
            self._add("determinism", node,
                      "wall clock time.time() in a step-indexed/replay "
                      "module")
        elif dotted == "random.Random" and not node.args:
            self._add("determinism", node,
                      "unseeded random.Random() — hidden nondeterminism "
                      "in a replay-bearing module")
        elif dotted.startswith("random.") and dotted.count(".") == 1 \
                and dotted != "random.Random":
            self._add("determinism", node,
                      f"global-state {dotted}() in a step-indexed/replay "
                      "module")
        elif dotted.startswith(("np.random.", "numpy.random.")):
            fn = dotted.rsplit(".", 1)[1]
            if fn not in NP_RANDOM_OK:
                self._add("determinism", node,
                          f"np.random.{fn} uses the global numpy RNG "
                          "state")

    # -- thread-discipline -------------------------------------------------

    def _check_thread(self, node: ast.Call) -> None:
        kwargs = {kw.arg for kw in node.keywords}
        if None in kwargs:  # **kw — can't see through it
            return
        if "name" not in kwargs:
            self._add("thread-discipline", node,
                      "anonymous threading.Thread — leak reports and "
                      "the lock sanitizer cannot attribute it")
        if "daemon" not in kwargs and not self._has_join:
            self._add("thread-discipline", node,
                      "thread is neither daemon= nor joined in this "
                      "module")

    # -- bare-sleep --------------------------------------------------------

    def _check_sleep(self, node: ast.Call, dotted: str) -> None:
        # time.sleep(...) by attribute, or a from-import alias call.
        # `sleep=time.sleep` default args are references, not calls, and
        # an injected `sleep(...)` parameter is a Name the import scan
        # never saw — both stay legal (that IS the prescribed fix).
        bare = dotted == "time.sleep" or (
            isinstance(node.func, ast.Name)
            and node.func.id in self._sleep_aliases)
        if bare:
            self._add("bare-sleep", node,
                      "direct time.sleep in serving code — an invisible "
                      "stall with no span, no fault site, and no test "
                      "injection point")

    # -- visitors ----------------------------------------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    self._sleep_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "open" and self._atomic:
            self._check_open(node)
        dotted = _dotted(func)
        if dotted in ("threading.Thread",) or (
                isinstance(func, ast.Name) and func.id == "Thread"):
            self._check_thread(node)
        if self._atomic and dotted.startswith(("np.", "numpy.")):
            fn = dotted.split(".", 1)[1]
            if fn in ("save", "savez", "savez_compressed"):
                self._check_np_save(node, fn)
        if self._det and dotted:
            self._check_determinism(node, dotted)
        if self._sleep:
            self._check_sleep(node, dotted)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._add("typed-error", node,
                      "bare except: swallows SystemExit/KeyboardInterrupt "
                      "and hides the fault type")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if self._assert:
            self._add("typed-error", node,
                      "assert in service-layer code vanishes under "
                      "`python -O`")
        self.generic_visit(node)


# module-constant naming: what constant-upload treats as a hoistable table
_CONST_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*$")

# jnp entry points that upload a host constant to the device
_JNP_UPLOAD = ("jnp.asarray", "jnp.array", "jax.numpy.asarray",
               "jax.numpy.array")

# module-level call results the mutable-state scan treats as immutable
_IMMUTABLE_CALLS = ("frozenset", "tuple", "property", "re.compile",
                    "collections.namedtuple", "namedtuple")

# host-side numeric namespaces float() may materialize from without a sync
_HOST_FLOAT_OK = ("np.", "numpy.", "math.", "len", "round", "int", "str",
                  "min", "max", "sum", "abs")


def _jit_decorator_info(dec) -> dict | None:
    """{"donate": bool, "static": bool} when ``dec`` is a jit decorator
    (bare ``jax.jit``, ``jax.jit(...)``, or ``functools.partial(jax.jit,
    ...)``); None otherwise."""
    if _dotted(dec) in ("jax.jit", "jit"):
        return {"donate": False, "static": False}
    if isinstance(dec, ast.Call):
        f = _dotted(dec.func)
        kws = {kw.arg for kw in dec.keywords}
        if f in ("jax.jit", "jit"):
            pass
        elif f in ("functools.partial", "partial") and dec.args \
                and _dotted(dec.args[0]) in ("jax.jit", "jit"):
            pass
        else:
            return None
        return {
            "donate": bool(kws & {"donate_argnums", "donate_argnames"}),
            "static": bool(kws & {"static_argnums", "static_argnames"}),
        }
    return None


def _donate_indices(call: ast.Call) -> tuple[int, ...]:
    """The literal donate_argnums of a ``jax.jit(fn, donate_argnums=...)``
    call, () when absent or not statically literal."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
    return ()


def _assign_target_names(node) -> set[str]:
    names: set[str] = set()
    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
    return names


def _scoped_walk(fn):
    """Every node lexically inside ``fn`` EXCLUDING nested function
    subtrees (those get their own `_check_function` pass, with inherited
    jit/hot context)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _child_functions(fn):
    """Function defs whose nearest enclosing function is ``fn``."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


class _XlaChecker:
    """The XLA performance-contract rules (the static half; the runtime
    half is analysis/xlacheck.py — docs/static_analysis.md):

      * ``jit-boundary`` — a jitted/shard_map'd/traced function reading
        ``self.<attr>`` or a module-level mutable array/container bakes
        that state into the compiled program at trace time (a later
        mutation silently serves stale values or forces a recompile);
        a str/bool-defaulted parameter on a plain jit is traced per
        call instead of marked static.
      * ``hot-sync`` — ``np.asarray`` / ``.item()`` /
        ``block_until_ready`` / ``device_get`` / ``float(<call>)`` in a
        dispatcher thread, train-step loop, or per-request path stalls
        the pipeline on a device round-trip; legal only at the declared
        materialization points (reasoned pragmas).
      * ``donation`` — a step-shaped jit (params + opt_state, or a
        ``*step`` taking params) missing ``donate_argnums`` doubles the
        parameter working set; a donated buffer read after the call is
        garbage.
      * ``constant-upload`` — ``jnp.asarray(MODULE_CONST)`` inside a
        per-call fn re-uploads (or re-bakes) the constant; hoist it to
        factory scope.
    """

    def __init__(self, rel: str, config: LintConfig):
        self.rel = rel
        self.config = config
        self.findings: list[Finding] = []
        self._hot_fns = {fn for path, fn in config.hot_sync_scope
                         if path == rel}
        self._traced_fns = {fn for path, fn in config.traced_scope
                            if path == rel}

    def _add(self, rule: str, node, message: str) -> None:
        self.findings.append(Finding(rule, self.rel, node.lineno,
                                     "strict", message))

    # -- module scan -------------------------------------------------------

    def _scan_module(self, tree: ast.Module) -> None:
        """Module-level mutable names + jit/shard_map wrap-assignments +
        the per-name donation map."""
        self.module_mutable: set[str] = set()
        self.wrapped_traced: set[str] = set()   # defs jitted/mapped by name
        self.donating: dict[str, tuple[int, ...]] = {}
        for stmt in tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            value = stmt.value
            mutable = isinstance(value, (
                ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                ast.SetComp))
            if isinstance(value, ast.Call) \
                    and _dotted(value.func) not in _IMMUTABLE_CALLS:
                mutable = True
            if mutable:
                self.module_mutable |= _assign_target_names(stmt)
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    info = _jit_decorator_info(dec)
                    if info and info["donate"]:
                        self.donating.setdefault(node.name, self._dec_donate(
                            node.decorator_list))
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            f = _dotted(call.func)
            first = call.args[0] if call.args else None
            if not isinstance(first, ast.Name):
                continue
            if f in ("jax.jit", "jit"):
                self.wrapped_traced.add(first.id)
                idx = _donate_indices(call)
                if idx:
                    for name in _assign_target_names(node):
                        self.donating[name] = idx
            elif f.rsplit(".", 1)[-1] in ("shard_map", "_wrap_shard_map"):
                self.wrapped_traced.add(first.id)

    @staticmethod
    def _dec_donate(decorators) -> tuple[int, ...]:
        for dec in decorators:
            if isinstance(dec, ast.Call):
                idx = _donate_indices(dec)
                if idx:
                    return idx
                for inner in dec.args:
                    if isinstance(inner, ast.Call):
                        idx = _donate_indices(inner)
                        if idx:
                            return idx
        return ()

    # -- the walk ----------------------------------------------------------

    def check(self, tree: ast.Module) -> None:
        self._scan_module(tree)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(stmt, in_jit=False,
                                     hot=self._is_hot(stmt.name))
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._check_function(sub, in_jit=False,
                                             hot=self._is_hot(sub.name))

    def _is_hot(self, name: str) -> bool:
        return self.config.all_scopes or name in self._hot_fns

    @staticmethod
    def _param_names(fn) -> list[str]:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def _check_function(self, fn, in_jit: bool, hot: bool) -> None:
        info = None
        for dec in fn.decorator_list:
            info = _jit_decorator_info(dec)
            if info is not None:
                break
        traced_here = (info is not None or fn.name in self.wrapped_traced
                       or fn.name in self._traced_fns)
        now_jit = in_jit or traced_here
        params = self._param_names(fn)
        if info is not None:
            self._check_jit_signature(fn, info, params)
        if now_jit:
            self._check_jit_body(fn, params)
        children = _child_functions(fn)
        # a function that builds nested defs is a factory: its OWN scope
        # is the hoist target ("upload once, close over the device
        # array"), so constant-upload only binds in leaf/jitted scopes
        self._check_calls(fn, hot=hot, in_jit=now_jit,
                          factory=bool(children) and not now_jit)
        self._check_donated_reuse(fn)
        for sub in children:
            self._check_function(sub, in_jit=now_jit, hot=hot)

    # -- jit-boundary ------------------------------------------------------

    def _check_jit_signature(self, fn, info: dict, params: list[str]) -> None:
        if not info["static"]:
            defaults = list(fn.args.defaults) + list(fn.args.kw_defaults)
            for d in defaults:
                if isinstance(d, ast.Constant) \
                        and isinstance(d.value, (str, bool)):
                    self._add("jit-boundary", fn,
                              f"jitted {fn.name}() takes a Python "
                              f"{type(d.value).__name__}-default parameter "
                              "without static_argnames — each distinct "
                              "value is a silent retrace (or a trace-time "
                              "error)")
                    break
        if info["donate"]:
            return
        step_shaped = ("params" in params and "opt_state" in params) or (
            (fn.name == "step" or fn.name.endswith("_step"))
            and "params" in params)
        if step_shaped:
            self._add("donation", fn,
                      f"step-shaped jit {fn.name}() missing donate_argnums "
                      "— the update holds old and new buffers live "
                      "(double the parameter working set)")

    def _check_jit_body(self, fn, params: list[str]) -> None:
        for node in _scoped_walk(fn):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                self._add("jit-boundary", node,
                          f"jitted/traced code reads self.{node.attr} — "
                          "mutable instance state is baked at trace time "
                          "(a later mutation silently serves stale "
                          "values); pass it as an argument")
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in self.module_mutable \
                    and node.id not in params:
                self._add("jit-boundary", node,
                          f"jitted/traced code reads module-level mutable "
                          f"state {node.id!r} — baked per compile; an "
                          "in-place mutation silently invalidates every "
                          "compiled program")

    # -- hot-sync + constant-upload ----------------------------------------

    def _check_calls(self, fn, hot: bool, in_jit: bool,
                     factory: bool = False) -> None:
        for node in _scoped_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if hot:
                self._check_hot_call(node, dotted)
            if not factory and dotted in _JNP_UPLOAD and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and _CONST_RE.match(node.args[0].id) \
                    and not (in_jit
                             and node.args[0].id in self.module_mutable):
                self._add("constant-upload", node,
                          f"jnp upload of module constant "
                          f"{node.args[0].id!r} inside a per-call fn — "
                          "hoist to factory scope so it transfers once")

    def _check_hot_call(self, node: ast.Call, dotted: str) -> None:
        if dotted in ("np.asarray", "numpy.asarray"):
            self._add("hot-sync", node,
                      "np.asarray on a device value in a hot path blocks "
                      "the thread on a d2h transfer")
        elif dotted in ("jax.block_until_ready", "jax.device_get"):
            self._add("hot-sync", node,
                      f"{dotted}() in a hot path stalls the dispatch "
                      "pipeline on the device")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "block_until_ready") \
                and not node.args:
            self._add("hot-sync", node,
                      f".{node.func.attr}() in a hot path is a host<->"
                      "device sync per call")
        elif isinstance(node.func, ast.Name) and node.func.id == "float" \
                and len(node.args) == 1 and isinstance(node.args[0],
                                                       ast.Call):
            inner = _dotted(node.args[0].func)
            if inner and not inner.startswith(_HOST_FLOAT_OK):
                self._add("hot-sync", node,
                          f"float({inner}(...)) materializes a device "
                          "value per call in a hot path")

    # -- donated-buffer reuse ----------------------------------------------

    def _check_donated_reuse(self, fn) -> None:
        if not self.donating:
            return
        # local donating wrappers shadow/extend the module map
        donating = dict(self.donating)
        for node in _scoped_walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _dotted(node.value.func) in ("jax.jit", "jit"):
                idx = _donate_indices(node.value)
                if idx:
                    for name in _assign_target_names(node):
                        donating[name] = idx
        # every assignment line per name (rebinds end a donation hazard)
        assigns: dict[str, list[int]] = {}
        for node in _scoped_walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                for name in _assign_target_names(node):
                    assigns.setdefault(name, []).append(node.lineno)
            elif isinstance(node, ast.For):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        assigns.setdefault(sub.id, []).append(node.lineno)
        # donated positional args, then later un-rebound reads
        for node in _scoped_walk(fn):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name) \
                    or node.func.id not in donating:
                continue
            call_line = node.lineno
            donated = [node.args[i].id for i in donating[node.func.id]
                       if i < len(node.args)
                       and isinstance(node.args[i], ast.Name)]
            for name in donated:
                for read in _scoped_walk(fn):
                    if isinstance(read, ast.Name) and read.id == name \
                            and isinstance(read.ctx, ast.Load) \
                            and read.lineno > call_line \
                            and not any(call_line <= a <= read.lineno
                                        for a in assigns.get(name, ())):
                        self._add("donation", read,
                                  f"donated buffer {name!r} read after "
                                  f"the donating call at line {call_line} "
                                  "— its memory was handed to XLA")
                        break


def _collect_pragmas(rel: str, source: str) -> tuple[dict, list[Finding]]:
    """line -> (rule, reason) for every pragma; malformed ones (missing
    reason, unknown rule) are findings themselves."""
    pragmas: dict[int, tuple[str, str]] = {}
    findings: list[Finding] = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = PRAGMA_RE.search(text)
        if not m:
            continue
        rule, reason = m.group(1), m.group(2)
        if rule not in RULES:
            findings.append(Finding("pragma", rel, i, "strict",
                                    f"allow[{rule}] names no known rule"))
            continue
        if not reason:
            findings.append(Finding("pragma", rel, i, "strict",
                                    f"allow[{rule}] without a reason"))
            continue
        pragmas[i] = (rule, reason)
    return pragmas, findings


def lint_file(path: str, rel: str, config: LintConfig) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    pragmas, findings = _collect_pragmas(rel, source)
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        findings.append(Finding("typed-error", rel, e.lineno or 1,
                                "strict", f"file does not parse: {e.msg}"))
        return findings
    checker = _FileChecker(rel, source, config)
    checker.visit(tree)
    xla = _XlaChecker(rel, config)
    xla.check(tree)
    checker.findings.extend(xla.findings)
    lines = source.splitlines()
    for f_ in checker.findings:
        allowed = False
        for at in (f_.line, f_.line - 1):
            entry = pragmas.get(at)
            if entry and entry[0] == f_.rule:
                # a standalone pragma line covers the NEXT line; an
                # end-of-line pragma covers its own
                if at == f_.line or lines[at - 1].lstrip().startswith("#"):
                    allowed = True
                    break
        if not allowed:
            findings.append(f_)
    return findings


def _iter_py(root: str, sub: str, config: LintConfig):
    top = os.path.join(root, sub)
    if os.path.isfile(top):
        yield top, sub.replace(os.sep, "/")
        return
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = [d for d in dirnames if d not in config.skip_parts]
        for name in sorted(filenames):
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                yield full, os.path.relpath(full, root).replace(os.sep, "/")


def run_lint(root: str, config: LintConfig | None = None,
             paths: list[str] | None = None,
             grammar: bool = True) -> list[Finding]:
    """Lint the repo at ``root`` (or just ``paths``, repo-relative).

    Explicit paths open every rule's scope gate (``all_scopes``) and skip
    the repo-level grammar check — that is the fixture-testing mode."""
    config = config or LintConfig()
    findings: list[Finding] = []
    if paths is not None:
        config = dataclasses.replace(config, all_scopes=True)
        targets = [(os.path.join(root, p), p.replace(os.sep, "/"))
                   for p in paths]
        for full, rel in targets:
            findings.extend(lint_file(full, rel, config))
        return findings

    for sub in config.strict_roots:
        for full, rel in _iter_py(root, sub, config):
            findings.extend(lint_file(full, rel, config))
    for sub in config.warn_roots:
        for full, rel in _iter_py(root, sub, config):
            for f_ in lint_file(full, rel, config):
                f_.level = "warn"
                findings.append(f_)
    if grammar:
        from .grammar import lint_grammar

        findings.extend(lint_grammar(root, config))
    findings.sort(key=lambda f_: (f_.path, f_.line, f_.rule))
    return findings


def format_report(findings: list[Finding], files: int | None = None) -> str:
    out = [f.format() for f in findings]
    strict = sum(1 for f in findings if f.level == "strict")
    warn = len(findings) - strict
    tail = f"lint: {strict} finding(s), {warn} warning(s)"
    if files is not None:
        tail += f" over {files} file(s)"
    if strict:
        hints = {f.rule: f.hint for f in findings
                 if f.level == "strict" and f.hint}
        for rule, hint in sorted(hints.items()):
            out.append(f"  fix[{rule}]: {hint}")
    out.append(tail)
    return "\n".join(out)
