"""AST invariant linter (``cli lint`` / ``make lint``).

Four per-file rules, each guarding a convention the system's headline
guarantees rest on (docs/static_analysis.md has the full table):

  * ``atomic-write`` — durable artifacts go through ``utils/atomicio``:
    a raw ``open(path, "w")`` destroys the previous contents the moment
    it runs, so a crash mid-write leaves a torn file where the recovery
    artifact used to be. Write-mode ``open`` and ``np.save``/``np.savez``
    straight to a path are findings; append-mode streams (JSONL sinks,
    torn-tail tolerant by design) are not.
  * ``determinism`` — step-indexed / replay / serving-dispatch modules
    must be pure functions of (seed, step): ``time.time()``, module-level
    ``random.*``, unseeded ``random.Random()``, and ``np.random`` global
    state are findings there (injectable ``clock=``/``rng=`` is the fix;
    ``np.random.default_rng(seed)`` and friends are fine).
  * ``thread-discipline`` — every ``threading.Thread`` carries ``name=``
    (leak reports and the lock sanitizer attribute by thread name) and
    is either ``daemon=`` or joined somewhere in its module.
  * ``typed-error`` — no bare ``except:`` anywhere; no ``assert`` in the
    service layers (typed errors must survive ``python -O``).

Findings carry file:line, rule id, and a fix hint. A narrow pragma
allowlist (``# lint: allow[RULE] reason`` — reason mandatory) admits
the rare legitimate exception without widening the rule.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from .config import NP_RANDOM_OK, PRAGMA_RE, RULES, LintConfig

_HINTS = {
    "atomic-write": "route the write through utils/atomicio.atomic_write",
    "determinism": "inject clock=/rng= (or np.random.default_rng(seed))",
    "thread-discipline": "threading.Thread(..., name=..., daemon=True) "
                         "or join() it",
    "typed-error": "raise a typed error (survives `python -O`); "
                   "catch specific exceptions",
    "pragma": "pragmas need a reason: # lint: allow[RULE] why",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    level: str  # "strict" | "warn"
    message: str

    @property
    def hint(self) -> str:
        return _HINTS.get(self.rule, "")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "level": self.level, "message": self.message,
                "hint": self.hint}

    def format(self) -> str:
        return (f"{self.path}:{self.line}: [{self.level}] {self.rule}: "
                f"{self.message}")


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dotted(node) -> str:
    """'np.random.seed' for an Attribute chain, '' when not a pure chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _FileChecker(ast.NodeVisitor):
    def __init__(self, rel: str, source: str, config: LintConfig):
        self.rel = rel
        self.source = source
        self.config = config
        self.findings: list[Finding] = []
        self._has_join = ".join(" in source
        self._det = config.in_scope(rel, config.determinism_scope)
        self._assert = config.in_scope(rel, config.assert_scope)
        self._atomic = rel not in config.atomic_exempt

    def _add(self, rule: str, node, message: str) -> None:
        self.findings.append(Finding(rule, self.rel, node.lineno,
                                     "strict", message))

    # -- atomic-write ------------------------------------------------------

    def _check_open(self, node: ast.Call) -> None:
        mode = None
        if len(node.args) >= 2:
            mode = _const_str(node.args[1])
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = _const_str(kw.value)
        if mode and any(c in mode for c in "wx"):
            self._add("atomic-write", node,
                      f'raw open(..., "{mode}") on a durable path '
                      "outside utils/atomicio")

    def _check_np_save(self, node: ast.Call, fn: str) -> None:
        if not node.args:
            return
        dst = node.args[0]
        # np.save(f, ...) into a handle (atomic_write body) is fine; a
        # path expression or literal bypasses the atomic writer
        if isinstance(dst, (ast.Name, ast.Attribute)):
            return
        self._add("atomic-write", node,
                  f"np.{fn} straight to a path bypasses utils/atomicio")

    # -- determinism -------------------------------------------------------

    def _check_determinism(self, node: ast.Call, dotted: str) -> None:
        if dotted == "time.time":
            self._add("determinism", node,
                      "wall clock time.time() in a step-indexed/replay "
                      "module")
        elif dotted == "random.Random" and not node.args:
            self._add("determinism", node,
                      "unseeded random.Random() — hidden nondeterminism "
                      "in a replay-bearing module")
        elif dotted.startswith("random.") and dotted.count(".") == 1 \
                and dotted != "random.Random":
            self._add("determinism", node,
                      f"global-state {dotted}() in a step-indexed/replay "
                      "module")
        elif dotted.startswith(("np.random.", "numpy.random.")):
            fn = dotted.rsplit(".", 1)[1]
            if fn not in NP_RANDOM_OK:
                self._add("determinism", node,
                          f"np.random.{fn} uses the global numpy RNG "
                          "state")

    # -- thread-discipline -------------------------------------------------

    def _check_thread(self, node: ast.Call) -> None:
        kwargs = {kw.arg for kw in node.keywords}
        if None in kwargs:  # **kw — can't see through it
            return
        if "name" not in kwargs:
            self._add("thread-discipline", node,
                      "anonymous threading.Thread — leak reports and "
                      "the lock sanitizer cannot attribute it")
        if "daemon" not in kwargs and not self._has_join:
            self._add("thread-discipline", node,
                      "thread is neither daemon= nor joined in this "
                      "module")

    # -- visitors ----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "open" and self._atomic:
            self._check_open(node)
        dotted = _dotted(func)
        if dotted in ("threading.Thread",) or (
                isinstance(func, ast.Name) and func.id == "Thread"):
            self._check_thread(node)
        if self._atomic and dotted.startswith(("np.", "numpy.")):
            fn = dotted.split(".", 1)[1]
            if fn in ("save", "savez", "savez_compressed"):
                self._check_np_save(node, fn)
        if self._det and dotted:
            self._check_determinism(node, dotted)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._add("typed-error", node,
                      "bare except: swallows SystemExit/KeyboardInterrupt "
                      "and hides the fault type")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if self._assert:
            self._add("typed-error", node,
                      "assert in service-layer code vanishes under "
                      "`python -O`")
        self.generic_visit(node)


def _collect_pragmas(rel: str, source: str) -> tuple[dict, list[Finding]]:
    """line -> (rule, reason) for every pragma; malformed ones (missing
    reason, unknown rule) are findings themselves."""
    pragmas: dict[int, tuple[str, str]] = {}
    findings: list[Finding] = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = PRAGMA_RE.search(text)
        if not m:
            continue
        rule, reason = m.group(1), m.group(2)
        if rule not in RULES:
            findings.append(Finding("pragma", rel, i, "strict",
                                    f"allow[{rule}] names no known rule"))
            continue
        if not reason:
            findings.append(Finding("pragma", rel, i, "strict",
                                    f"allow[{rule}] without a reason"))
            continue
        pragmas[i] = (rule, reason)
    return pragmas, findings


def lint_file(path: str, rel: str, config: LintConfig) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    pragmas, findings = _collect_pragmas(rel, source)
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        findings.append(Finding("typed-error", rel, e.lineno or 1,
                                "strict", f"file does not parse: {e.msg}"))
        return findings
    checker = _FileChecker(rel, source, config)
    checker.visit(tree)
    lines = source.splitlines()
    for f_ in checker.findings:
        allowed = False
        for at in (f_.line, f_.line - 1):
            entry = pragmas.get(at)
            if entry and entry[0] == f_.rule:
                # a standalone pragma line covers the NEXT line; an
                # end-of-line pragma covers its own
                if at == f_.line or lines[at - 1].lstrip().startswith("#"):
                    allowed = True
                    break
        if not allowed:
            findings.append(f_)
    return findings


def _iter_py(root: str, sub: str, config: LintConfig):
    top = os.path.join(root, sub)
    if os.path.isfile(top):
        yield top, sub.replace(os.sep, "/")
        return
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = [d for d in dirnames if d not in config.skip_parts]
        for name in sorted(filenames):
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                yield full, os.path.relpath(full, root).replace(os.sep, "/")


def run_lint(root: str, config: LintConfig | None = None,
             paths: list[str] | None = None,
             grammar: bool = True) -> list[Finding]:
    """Lint the repo at ``root`` (or just ``paths``, repo-relative).

    Explicit paths open every rule's scope gate (``all_scopes``) and skip
    the repo-level grammar check — that is the fixture-testing mode."""
    config = config or LintConfig()
    findings: list[Finding] = []
    if paths is not None:
        config = dataclasses.replace(config, all_scopes=True)
        targets = [(os.path.join(root, p), p.replace(os.sep, "/"))
                   for p in paths]
        for full, rel in targets:
            findings.extend(lint_file(full, rel, config))
        return findings

    for sub in config.strict_roots:
        for full, rel in _iter_py(root, sub, config):
            findings.extend(lint_file(full, rel, config))
    for sub in config.warn_roots:
        for full, rel in _iter_py(root, sub, config):
            for f_ in lint_file(full, rel, config):
                f_.level = "warn"
                findings.append(f_)
    if grammar:
        from .grammar import lint_grammar

        findings.extend(lint_grammar(root, config))
    findings.sort(key=lambda f_: (f_.path, f_.line, f_.rule))
    return findings


def format_report(findings: list[Finding], files: int | None = None) -> str:
    out = [f.format() for f in findings]
    strict = sum(1 for f in findings if f.level == "strict")
    warn = len(findings) - strict
    tail = f"lint: {strict} finding(s), {warn} warning(s)"
    if files is not None:
        tail += f" over {files} file(s)"
    if strict:
        hints = {f.rule: f.hint for f in findings
                 if f.level == "strict" and f.hint}
        for rule, hint in sorted(hints.items()):
            out.append(f"  fix[{rule}]: {hint}")
    out.append(tail)
    return "\n".join(out)
