"""Machine-checked invariants (docs/static_analysis.md).

Two halves, one conviction: the guarantees PRs 1-8 advertise — bit-exact
resume, zero lost games, zero-recompile hot reload — rest on code
conventions (atomic writes, injectable clocks, named threads, typed
errors, documented grammar) that review alone cannot hold at scale.

  * :mod:`linter` / :mod:`grammar` — the AST invariant linter behind
    ``cli lint`` / ``make lint``: per-rule checkers with file:line
    findings, a reasoned inline-pragma allowlist, and JSON for CI.
  * :mod:`lockcheck` — the opt-in (``DEEPGO_LOCKCHECK=1``) runtime
    lock-order sanitizer: instrumented locks record the per-thread
    acquisition graph across the dispatcher/supervisor/fleet/replay/obs
    threads and report order-inversion cycles and long-hold hazards
    through the flight recorder.
  * :mod:`xlacheck` — the opt-in (``DEEPGO_XLACHECK=1``) runtime XLA
    performance-contract sanitizer: the recompile sentinel (zero
    post-warmup compile budget, typed ``RecompileStorm`` findings with
    the triggering abstract shapes), the implicit-transfer guard, and
    the sharding-claim checker. Its static twin is the linter's
    jit-boundary/hot-sync/donation/constant-upload rules.

Only :mod:`lockcheck` and :mod:`xlacheck` are imported eagerly — they
sit on production construction paths and stay import-light (no jax at
import time); the linter halves load on demand from the CLI and tests.
"""

from . import xlacheck  # noqa: F401
from .lockcheck import enabled as lockcheck_enabled  # noqa: F401
from .lockcheck import make_lock, make_rlock  # noqa: F401
