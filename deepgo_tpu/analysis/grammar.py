"""Code <-> docs grammar drift checker (rule id ``grammar-drift``).

The observability story only works if the grammar is closed: every
``deepgo_*`` metric, ``obs_*``/``loop_*``/``fleet_*`` event, and
``DEEPGO_FAULTS`` site the code emits must be documented (dashboards and
runbooks are built off the tables in docs/observability.md,
docs/robustness.md, docs/loop.md), and every token those tables promise
must still be emitted (a renamed metric silently orphans every alert
built on the old name). This module checks both directions.

Code side (AST, never regex-over-source):

  * metrics — the first string argument of ``registry.counter/gauge/
    histogram(...)`` calls;
  * events — the first string argument of ``*.write(...)`` calls with a
    grammar prefix;
  * fault sites — the first string argument of ``faults.check(...)``.

Docs side: backticked tokens with a grammar prefix anywhere in the
designated docs, plus the fault-site table (the ``| site | location |``
table in robustness.md). Two docs idioms are understood:

  * label sets are stripped — ``deepgo_fleet_shed_total{tier,reason}``
    documents ``deepgo_fleet_shed_total``;
  * suffix continuations expand against the preceding full token on the
    same line — ``deepgo_serving_boards_total`` / ``_dispatches_total``
    documents ``deepgo_serving_dispatches_total`` (matched by shared
    2-part prefix + suffix, so the compression the tables already use
    keeps working).
"""

from __future__ import annotations

import ast
import os
import re

from .config import LintConfig

# the checked-in policy owns the prefix list (analysis/config.py); this
# module-level alias keeps the historical import surface working
GRAMMAR_PREFIXES = LintConfig().grammar_prefixes

_BACKTICK_RE = re.compile(r"`([^`]+)`")
_TOKEN_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


# ---------------------------------------------------------------------------
# code side

def _first_str(node: ast.Call) -> str | None:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


class _CodeGrammar(ast.NodeVisitor):
    """tokens -> (rel, line) of the first emission site."""

    def __init__(self, rel: str, prefixes: tuple = GRAMMAR_PREFIXES):
        self.rel = rel
        # every prefix except the metric namespace is an event namespace
        self._event_prefixes = tuple(p for p in prefixes
                                     if p != "deepgo_")
        self.metrics: dict[str, tuple] = {}
        self.events: dict[str, tuple] = {}
        self.sites: dict[str, tuple] = {}

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            arg = _first_str(node)
            if arg:
                where = (self.rel, node.lineno)
                if func.attr in ("counter", "gauge", "histogram") \
                        and arg.startswith("deepgo_"):
                    self.metrics.setdefault(arg, where)
                elif func.attr == "write" \
                        and arg.startswith(self._event_prefixes):
                    self.events.setdefault(arg, where)
                elif func.attr == "check" \
                        and isinstance(func.value, ast.Name) \
                        and func.value.id in ("faults", "faults_mod"):
                    self.sites.setdefault(arg, where)
        self.generic_visit(node)


def _walk_py(root: str, sub: str, config: LintConfig):
    top = os.path.join(root, sub)
    if os.path.isfile(top):
        yield top, sub
        return
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = [d for d in dirnames if d not in config.skip_parts]
        for name in sorted(filenames):
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                yield full, os.path.relpath(full, root)


def extract_code_grammar(root: str, config: LintConfig) -> dict:
    metrics: dict[str, tuple] = {}
    events: dict[str, tuple] = {}
    sites: dict[str, tuple] = {}
    for sub in config.grammar_code_roots:
        for full, rel in _walk_py(root, sub, config):
            rel = rel.replace(os.sep, "/")
            try:
                with open(full, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=rel)
            except (OSError, SyntaxError):
                continue  # the linter proper reports parse failures
            v = _CodeGrammar(rel, config.grammar_prefixes)
            v.visit(tree)
            for src, dst in ((v.metrics, metrics), (v.events, events),
                             (v.sites, sites)):
                for tok, where in src.items():
                    dst.setdefault(tok, where)
    return {"metrics": metrics, "events": events, "sites": sites}


# ---------------------------------------------------------------------------
# docs side

def _clean(token: str) -> str | None:
    """`deepgo_x_total{a,b}` -> deepgo_x_total; None for non-tokens
    (wildcards, dotted paths, flags)."""
    token = token.split("{")[0]
    if not _TOKEN_RE.match(token):
        return None
    return token


def extract_doc_grammar(root: str, config: LintConfig) -> dict:
    """full tokens, (full, continuation) pairs, fault-site table tokens —
    each mapped to (doc, line) — plus the concatenated raw text."""
    full: dict[str, tuple] = {}
    conts: list[tuple] = []  # (full_token, continuation, doc, line)
    sites: dict[str, tuple] = {}
    raw_parts = []
    for doc in config.grammar_docs:
        path = os.path.join(root, doc)
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        raw_parts.append(text)
        in_site_table = False
        for lineno, line in enumerate(text.splitlines(), start=1):
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if line.lstrip().startswith("|"):
                header = [c.strip("` *").lower() for c in cells]
                if header[:2] == ["site", "location"]:
                    in_site_table = True
                    continue
                if in_site_table:
                    if set(cells[0]) <= {"-", " ", ":"}:
                        continue  # the |---|---| separator row
                    m = _BACKTICK_RE.search(cells[0])
                    tok = _clean(m.group(1)) if m else None
                    if tok:
                        sites.setdefault(tok, (doc, lineno))
                    continue
            else:
                in_site_table = False
            last_full = None
            for m in _BACKTICK_RE.finditer(line):
                tok = _clean(m.group(1))
                if tok is None:
                    continue
                if tok.startswith(config.grammar_prefixes):
                    full.setdefault(tok, (doc, lineno))
                    last_full = tok
                elif tok.startswith("_") and last_full is not None:
                    conts.append((last_full, tok, doc, lineno))
    return {"full": full, "continuations": conts, "sites": sites,
            "raw": "\n".join(raw_parts)}


def _shared_parts(a: str, b: str) -> int:
    pa, pb = a.split("_"), b.split("_")
    n = 0
    for x, y in zip(pa, pb):
        if x != y:
            break
        n += 1
    return n


def _continuation_covers(token: str, conts: list[tuple]) -> bool:
    return any(token.endswith(cont) and _shared_parts(token, base) >= 2
               for base, cont, _doc, _line in conts)


def _word_in(token: str, text: str) -> bool:
    return re.search(rf"(?<![A-Za-z0-9_]){re.escape(token)}(?![A-Za-z0-9_])",
                     text) is not None


# ---------------------------------------------------------------------------
# the check

def lint_grammar(root: str, config: LintConfig | None = None) -> list:
    from .linter import Finding

    config = config or LintConfig()
    code = extract_code_grammar(root, config)
    docs = extract_doc_grammar(root, config)
    findings: list[Finding] = []
    doc_names = ", ".join(os.path.basename(d) for d in config.grammar_docs)

    # code -> docs: everything emitted must be documented
    for kind, label in (("metrics", "metric"), ("events", "event"),
                        ("sites", "fault site")):
        for token, (rel, line) in sorted(code[kind].items()):
            documented = (
                token in docs["full"]
                or token in docs["sites"]
                or _continuation_covers(token, docs["continuations"])
                or _word_in(token, docs["raw"])
            )
            if not documented:
                findings.append(Finding(
                    "grammar-drift", rel, line, "strict",
                    f"{label} {token!r} is emitted here but appears in "
                    f"none of the grammar docs ({doc_names})"))

    # docs -> code: everything promised must still be emitted
    code_all = set(code["metrics"]) | set(code["events"]) | set(code["sites"])
    for token, (doc, line) in sorted(docs["full"].items()):
        if token in config.grammar_ignore:
            continue
        if token not in code_all:
            findings.append(Finding(
                "grammar-drift", doc, line, "strict",
                f"documented token {token!r} is never emitted in code "
                "(renamed or removed without a doc update?)"))
    for base, cont, doc, line in docs["continuations"]:
        if any(t.endswith(cont) and _shared_parts(t, base) >= 2
               for t in code_all):
            continue
        findings.append(Finding(
            "grammar-drift", doc, line, "strict",
            f"documented continuation {base!r} / {cont!r} expands to no "
            "emitted token"))
    for token, (doc, line) in sorted(docs["sites"].items()):
        if token in config.grammar_ignore:
            continue
        if token not in code["sites"]:
            findings.append(Finding(
                "grammar-drift", doc, line, "strict",
                f"documented fault site {token!r} has no faults.check() "
                "in code"))
    return findings
