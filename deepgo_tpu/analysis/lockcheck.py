"""Runtime lock-order sanitizer (``DEEPGO_LOCKCHECK=1``).

The serving dispatcher, supervisor, fleet router, replay buffer, and obs
registry each guard their state with one or two locks. Individually every
acquisition is trivially correct; what nothing checked until now is the
*global* order — a dispatcher thread taking ``engine -> registry`` while
an exporter scrape takes ``registry -> engine`` is a deadlock that only
fires under production interleavings, the exact bug class a chaos soak
exists to surface.

Opt-in instrumentation: :func:`make_lock` / :func:`make_rlock` return a
plain ``threading.Lock``/``RLock`` unless the sanitizer is enabled (so
the hot paths — the obs registry is touched every step — pay nothing by
default), and a :class:`TrackedLock` when it is. Tracked locks maintain a
per-thread stack of held locks and a global acquired-while-holding graph:

  * edge ``A -> B`` is recorded the first time any thread acquires ``B``
    while holding ``A`` (with file:line of both acquisitions and the
    thread name — threads are named precisely so this report can
    attribute them);
  * a new edge that closes a directed cycle is an **order inversion**:
    a typed ``lock_order_cycle`` record is appended to the report and
    dumped through the obs flight recorder (flight-NNNN.json) so the
    postmortem carries the registry/span context around the detection;
  * a lock held longer than ``hold_warn_s`` (default 0.2 s) is a
    **lock-held-across-blocking-call hazard** — the cheap runtime proxy
    for "don't do I/O or a forward pass under a mutex" — reported once
    per acquisition site.

Detection never raises and never blocks the production path: the
sanitizer's own mutex is a leaf (nothing else is acquired under it), and
re-entry from the flight-recorder dump is cut by a thread-local guard.

``bench.py --mode serving|loop --faults`` enables this automatically, so
every chaos soak doubles as a race hunt; ``report()['cycles']`` lands in
the bench JSON and must stay empty.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_ENV = "DEEPGO_LOCKCHECK"
_HOLD_ENV = "DEEPGO_LOCKCHECK_HOLD_S"
_force: bool | None = None


def enabled() -> bool:
    """Is the sanitizer on? Programmatic :func:`enable` wins over the
    ``DEEPGO_LOCKCHECK`` environment variable."""
    if _force is not None:
        return _force
    return os.environ.get(_ENV, "0") not in ("", "0")


def enable(on: bool = True) -> None:
    """Programmatic override (tests, bench). ``enable(None)`` restores
    environment-variable control."""
    global _force
    _force = on


def _caller_site() -> str:
    """file:line of the nearest frame outside this module — the
    acquisition site the report attributes edges to."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


class _Sanitizer:
    """Global acquisition graph + per-thread held stacks."""

    def __init__(self, clock=time.monotonic, hold_warn_s: float | None = None):
        self.clock = clock
        if hold_warn_s is None:
            hold_warn_s = float(os.environ.get(_HOLD_ENV, "0.2"))
        self.hold_warn_s = hold_warn_s
        # leaf mutex: nothing is ever acquired while this is held
        self._mu = threading.Lock()
        self._edges: dict[str, dict[str, dict]] = {}
        self._cycles: list[dict] = []
        self._hazards: list[dict] = []
        self._seen_cycles: set[tuple] = set()
        self._seen_hazards: set[tuple] = set()
        self._locks: set[str] = set()
        self._tls = threading.local()

    # -- per-thread state --------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _reentered(self) -> bool:
        return getattr(self._tls, "busy", False)

    # -- lock registration -------------------------------------------------

    def register(self, name: str) -> None:
        with self._mu:
            self._locks.add(name)

    # -- acquisition tracking ----------------------------------------------

    def note_acquired(self, name: str, site: str) -> None:
        if self._reentered():
            return
        stack = self._stack()
        thread = threading.current_thread().name
        new_cycle = None
        with self._mu:
            for held, held_site, _t in stack:
                if held == name:  # RLock re-entry: never a self-edge
                    continue
                edge = self._edges.setdefault(held, {}).get(name)
                if edge is None:
                    edge = self._edges[held][name] = {
                        "count": 0, "site": site, "held_site": held_site,
                        "thread": thread,
                    }
                    cycle = self._find_path(name, held)
                    if cycle is not None:
                        new_cycle = self._record_cycle(
                            held, name, cycle, site, held_site, thread)
                edge["count"] += 1
        stack.append((name, site, self.clock()))
        if new_cycle is not None:
            self._report_cycle(new_cycle)

    def note_released(self, name: str) -> None:
        if self._reentered():
            return
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                _, site, t0 = stack.pop(i)
                held_s = self.clock() - t0
                if held_s > self.hold_warn_s:
                    self._record_hazard(name, site, held_s)
                return

    # -- graph analysis ----------------------------------------------------

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        """DFS for src -> ... -> dst in the edge graph (called with _mu
        held, BEFORE the new dst->src... i.e. held->name edge would close
        it). A path means the new edge completes a cycle."""
        seen = set()
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def _record_cycle(self, held: str, name: str, path: list[str],
                      site: str, held_site: str, thread: str) -> dict | None:
        key = tuple(sorted(set(path) | {held}))
        if key in self._seen_cycles:
            return None
        self._seen_cycles.add(key)
        record = {
            "kind": "lock_order_cycle",
            "cycle": [held] + path,  # held -> name -> ... -> held
            "edge": {"from": held, "to": name,
                     "site": site, "held_site": held_site},
            "thread": thread,
            "time": self.clock(),
        }
        self._cycles.append(record)
        return record

    def _record_hazard(self, name: str, site: str, held_s: float) -> None:
        with self._mu:
            if (name, site) in self._seen_hazards:
                return
            self._seen_hazards.add((name, site))
            self._hazards.append({
                "kind": "lock_held_across_blocking_call",
                "lock": name,
                "site": site,
                "held_s": round(held_s, 4),
                "threshold_s": self.hold_warn_s,
                "thread": threading.current_thread().name,
            })

    def _report_cycle(self, record: dict) -> None:
        """Dump the inversion through the flight recorder (outside _mu;
        the recorder's registry snapshot re-enters tracked locks, which
        the thread-local guard turns into no-ops instead of recursion)."""
        print(f"lockcheck: ORDER INVERSION {' -> '.join(record['cycle'])} "
              f"(edge {record['edge']['from']} -> {record['edge']['to']} "
              f"at {record['edge']['site']}, thread {record['thread']})",
              file=sys.stderr, flush=True)
        self._tls.busy = True
        try:
            from ..obs.sentinel import flight_dump

            flight_dump("lock_order_cycle", **record)
        except Exception:  # noqa: BLE001 — detection must never raise out
            pass
        finally:
            self._tls.busy = False

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            return {
                "enabled": enabled(),
                "locks": sorted(self._locks),
                "edges": {a: {b: e["count"] for b, e in outs.items()}
                          for a, outs in self._edges.items()},
                "cycles": list(self._cycles),
                "hazards": list(self._hazards),
            }


class TrackedLock:
    """A ``threading.Lock``/``RLock`` that reports to the sanitizer."""

    __slots__ = ("name", "_inner", "_san")

    def __init__(self, name: str, inner, san: _Sanitizer):
        self.name = name
        self._inner = inner
        self._san = san
        san.register(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._san.note_acquired(self.name, _caller_site())
        return ok

    def release(self) -> None:
        self._san.note_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TrackedLock({self.name!r})"


_sanitizer: _Sanitizer | None = None
_sanitizer_mu = threading.Lock()


def _get() -> _Sanitizer:
    global _sanitizer
    if _sanitizer is None:
        with _sanitizer_mu:
            if _sanitizer is None:
                _sanitizer = _Sanitizer()
    return _sanitizer


def make_lock(name: str):
    """A named mutex: plain ``threading.Lock`` when the sanitizer is off
    (zero overhead — this sits on the obs-registry hot path), tracked
    when ``DEEPGO_LOCKCHECK=1``."""
    if not enabled():
        return threading.Lock()
    return TrackedLock(name, threading.Lock(), _get())


def make_rlock(name: str):
    """Reentrant flavor of :func:`make_lock` (the replay buffer's seal
    path re-enters its own mutex)."""
    if not enabled():
        return threading.RLock()
    return TrackedLock(name, threading.RLock(), _get())


def report() -> dict:
    """Snapshot of the acquisition graph, cycles, and hazards."""
    return _get().report()


def reset(clock=time.monotonic, hold_warn_s: float | None = None) -> None:
    """Discard all recorded state (tests; each scenario gets a fresh
    graph). Locks made before the reset keep reporting — into the new
    sanitizer."""
    global _sanitizer
    with _sanitizer_mu:
        _sanitizer = _Sanitizer(clock=clock, hold_warn_s=hold_warn_s)
