"""Runtime XLA performance-contract sanitizer (``DEEPGO_XLACHECK=1``).

Every speed story in this repo rests on hand-enforced XLA contracts: the
bucket ladder's "zero steady-state compiles" (serving/buckets.py, the
FireCaffe discipline), donated step buffers (training/steps.py), and
named-mesh shardings that must not silently fall back to full
replication (parallel/tensor.py, zero.py — the failure mode
arXiv:2004.13336 exists to prevent). The static half of this contract
lives in the linter (``jit-boundary`` / ``hot-sync`` / ``donation`` /
``constant-upload`` rules, analysis/linter.py); this module is the
dynamic half — the lockcheck pattern applied to XLA:

  * **recompile sentinel** — :func:`watch_compiles` wraps a jitted
    forward with a per-function compile counter (the engine's existing
    ``compile_cache_size`` plumbing, read before/after every call).
    :func:`mark_warm` at the warmup boundary sets the budget to ZERO:
    any later compile is a steady-state compile, recorded as a typed
    :class:`RecompileStorm` finding carrying the triggering abstract
    shapes and dumped through the obs flight recorder — the postmortem
    names the exact shape that broke the ladder.
  * **transfer guard** — :func:`transfer_guard` wraps hot sections in
    ``jax.transfer_guard("disallow")`` so an implicit h2d/d2h raises at
    the exact line; :func:`stage_h2d` is the explicit ``device_put``
    for DECLARED transfer points (the engine's dispatch stages its
    padded batch through it). Violations are counted and recorded on
    their way out.
  * **sharding-claim checker** — :func:`check_sharding` verifies a
    declared sharding pytree against the ``.sharding`` of live arrays,
    so "sharded" can never silently mean "replicated" again. Wired into
    the placement paths (``tensor.shard_params`` /
    ``zero.shard_opt_state``) on their dryrun/real runs alike.

Opt-in like lockcheck: everything here is a no-op (identity-returning,
``nullcontext``) unless ``DEEPGO_XLACHECK=1`` (or programmatic
:func:`enable`), so the hot paths pay nothing by default — the only
always-on cost is one attribute check per engine dispatch.
``bench.py --mode serving|loop --faults`` arms it automatically and any
finding lands in the bench JSON as an error; ``bench --gate`` folds a
``steady_state_compiles == 0`` sentinel into its verdict.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import threading
import time

_ENV = "DEEPGO_XLACHECK"
_force: bool | None = None


def enabled() -> bool:
    """Is the sanitizer on? Programmatic :func:`enable` wins over the
    ``DEEPGO_XLACHECK`` environment variable."""
    if _force is not None:
        return _force
    return os.environ.get(_ENV, "0") not in ("", "0")


def enable(on: bool | None = True) -> None:
    """Programmatic override (tests, bench). ``enable(None)`` restores
    environment-variable control."""
    global _force
    _force = on


def _abstract(value) -> str:
    """The abstract shape a storm report names: ``uint8[8,9,19,19]`` for
    arrays, ``pytree[N]`` for containers, the type name otherwise."""
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if isinstance(value, dict):
        try:
            import jax

            return f"pytree[{len(jax.tree.leaves(value))}]"
        except Exception:  # noqa: BLE001 — description only
            return f"dict[{len(value)}]"
    if isinstance(value, (list, tuple)):
        return f"{type(value).__name__}[{len(value)}]"
    return type(value).__name__


@dataclasses.dataclass(frozen=True)
class RecompileStorm:
    """One steady-state (post-warmup) compile, typed for the report."""

    fn: str
    shapes: tuple[str, ...]
    cache_before: int
    cache_after: int
    thread: str
    time: float

    def to_dict(self) -> dict:
        return {"kind": "recompile_storm", "fn": self.fn,
                "shapes": list(self.shapes),
                "cache_before": self.cache_before,
                "cache_after": self.cache_after,
                "thread": self.thread, "time": self.time}


class _Checker:
    """Global finding store + the ``deepgo_xlacheck_*`` metrics."""

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        # leaf mutex: nothing is acquired while this is held
        self._mu = threading.Lock()
        self._storms: list[RecompileStorm] = []
        self._transfers: list[dict] = []
        self._sharding: list[dict] = []
        self._seen_sharding: set[tuple] = set()
        self._watched: list["_CompileWatch"] = []
        from ..obs import get_registry

        reg = get_registry()
        self._obs_recompiles = reg.counter(
            "deepgo_xlacheck_recompiles_total",
            "steady-state (post-warmup) XLA compiles caught by the "
            "recompile sentinel")
        self._obs_transfers = reg.counter(
            "deepgo_xlacheck_transfer_violations_total",
            "implicit host<->device transfers raised inside guarded hot "
            "sections")
        self._obs_sharding = reg.counter(
            "deepgo_xlacheck_sharding_mismatches_total",
            "declared-vs-actual sharding mismatches on live arrays")

    # -- recompile sentinel ------------------------------------------------

    def register(self, watch: "_CompileWatch") -> None:
        with self._mu:
            self._watched.append(watch)

    def record_storm(self, storm: RecompileStorm) -> None:
        with self._mu:
            self._storms.append(storm)
        self._obs_recompiles.inc(storm.cache_after - storm.cache_before,
                                 fn=storm.fn)
        print(f"xlacheck: RECOMPILE STORM {storm.fn} compiled post-warmup "
              f"(cache {storm.cache_before} -> {storm.cache_after}) for "
              f"shapes [{', '.join(storm.shapes)}] on thread "
              f"{storm.thread}", file=sys.stderr, flush=True)
        self._flight("recompile_storm", **storm.to_dict())

    # -- transfer guard ----------------------------------------------------

    def record_transfer(self, tag: str, error: BaseException) -> None:
        record = {"kind": "implicit_transfer", "tag": tag,
                  "error": str(error)[:400],
                  "thread": threading.current_thread().name,
                  "time": self.clock()}
        with self._mu:
            self._transfers.append(record)
        self._obs_transfers.inc(tag=tag)
        self._flight("implicit_transfer", **record)

    # -- sharding claims ---------------------------------------------------

    def record_sharding(self, tag: str, path: str, problem: str,
                        declared, actual) -> dict | None:
        key = (tag, path)
        record = {"kind": "sharding_claim", "tag": tag, "path": path,
                  "problem": problem, "declared": str(declared),
                  "actual": str(actual), "time": self.clock()}
        with self._mu:
            if key in self._seen_sharding:
                return record  # report once per (tag, leaf), like hazards
            self._seen_sharding.add(key)
            self._sharding.append(record)
        self._obs_sharding.inc(tag=tag)
        print(f"xlacheck: SHARDING CLAIM {tag}{path}: {problem} "
              f"(declared {declared}, actual {actual})",
              file=sys.stderr, flush=True)
        self._flight("sharding_claim", **record)
        return record

    def _flight(self, reason: str, **detail) -> None:
        try:
            from ..obs.sentinel import flight_dump

            flight_dump(reason, **detail)
        except Exception:  # noqa: BLE001 — detection must never raise out
            pass

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            watched: dict[str, dict] = {}
            for w in self._watched:
                agg = watched.setdefault(
                    w.name, {"compiles": 0, "steady_state_compiles": 0,
                             "warm": False})
                agg["compiles"] += w.compiles
                agg["steady_state_compiles"] += w.steady_state_compiles
                agg["warm"] = agg["warm"] or w.warm
            return {
                "enabled": enabled(),
                "watched": watched,
                "steady_state_compiles": sum(
                    v["steady_state_compiles"] for v in watched.values()),
                "storms": [s.to_dict() for s in self._storms],
                "transfers": list(self._transfers),
                "sharding": list(self._sharding),
            }


class _CompileWatch:
    """A jitted callable with a compile counter and a warmup boundary.

    Reads the wrapped function's jit-cache size before/after each call
    (the same ``_cache_size`` plumbing ``compile_cache_size`` exposes up
    the engine/supervisor/fleet stack); growth after :meth:`mark_warm`
    is a steady-state compile — a :class:`RecompileStorm`."""

    def __init__(self, fn, name: str, checker: _Checker):
        self._fn = fn
        self.name = name
        self._checker = checker
        self.warm = False
        self.compiles = 0
        self.steady_state_compiles = 0
        # the engine stack discovers the cache via getattr(fn,
        # "_cache_size"), so the wrapper keeps that surface
        self._cache_size = self.cache_size
        checker.register(self)

    def cache_size(self) -> int | None:
        probe = getattr(self._fn, "_cache_size", None)
        try:
            return probe() if callable(probe) else None
        except Exception:  # noqa: BLE001 — a dying fn must not mask calls
            return None

    def mark_warm(self) -> None:
        """Warmup is over: the compile budget is now zero."""
        self.warm = True

    def __call__(self, *args, **kwargs):
        before = self.cache_size()
        out = self._fn(*args, **kwargs)
        after = self.cache_size()
        if before is not None and after is not None and after > before:
            self.compiles += after - before
            if self.warm:
                self.steady_state_compiles += after - before
                self._checker.record_storm(RecompileStorm(
                    fn=self.name,
                    shapes=tuple(_abstract(a) for a in args),
                    cache_before=before, cache_after=after,
                    thread=threading.current_thread().name,
                    time=self._checker.clock()))
        return out

    def __repr__(self) -> str:
        return f"_CompileWatch({self.name!r}, warm={self.warm})"


class _TransferGuard:
    """``jax.transfer_guard("disallow")`` that records violations on
    their way out (the exception still propagates — the finding raises
    at the exact line, the engine's containment types it)."""

    def __init__(self, tag: str, checker: _Checker):
        self.tag = tag
        self._checker = checker
        self._cm = None

    def __enter__(self) -> "_TransferGuard":
        import jax

        self._cm = jax.transfer_guard("disallow")
        self._cm.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._cm.__exit__(exc_type, exc, tb)
        if exc is not None and "Disallowed" in str(exc) \
                and "transfer" in str(exc):
            self._checker.record_transfer(self.tag, exc)
        return False


_checker: _Checker | None = None
_checker_mu = threading.Lock()


def _get() -> _Checker:
    global _checker
    if _checker is None:
        with _checker_mu:
            if _checker is None:
                _checker = _Checker()
    return _checker


def watch_compiles(fn, name: str):
    """Wrap a jitted forward with the recompile sentinel; returns ``fn``
    unchanged when the sanitizer is off (zero hot-path cost)."""
    if not enabled():
        return fn
    return _CompileWatch(fn, name, _get())


def mark_warm(fn) -> None:
    """Declare warmup complete for a watched forward (no-op on an
    unwrapped fn — the off-mode engine calls this unconditionally)."""
    if isinstance(fn, _CompileWatch):
        fn.mark_warm()


def transfer_guard(tag: str):
    """Guard a hot section against implicit transfers: a no-op context
    manager when off, ``jax.transfer_guard("disallow")`` (with violation
    recording) when armed."""
    if not enabled():
        return contextlib.nullcontext()
    return _TransferGuard(tag, _get())


def stage_h2d(*values):
    """Explicit ``device_put`` at a DECLARED transfer point — identity
    when off. Inside a :func:`transfer_guard` section only transfers
    staged through here (or ``jax.device_get``) are legal."""
    if not enabled():
        return values
    import jax

    return tuple(jax.device_put(v) for v in values)


def _equivalent(declared, actual, ndim: int) -> bool:
    try:
        return bool(declared.is_equivalent_to(actual, ndim))
    except Exception:  # noqa: BLE001 — fall back to spec comparison
        return str(getattr(declared, "spec", declared)) == \
            str(getattr(actual, "spec", actual))


def check_sharding(tag: str, tree, shardings) -> list[dict]:
    """Verify declared shardings against the ``.sharding`` of live
    arrays; returns the mismatch records (empty when off or in parity).

    The headline failure this catches: a leaf DECLARED sharded that is
    actually fully replicated — the silent fallback that makes every
    "fits because it is sharded" claim a lie. Host-resident leaves
    (never placed) and plain placement mismatches are findings too."""
    if not enabled():
        return []
    import jax
    from jax.tree_util import keystr, tree_flatten_with_path

    checker = _get()
    leaves, _ = tree_flatten_with_path(tree)
    decls = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "is_fully_replicated"))
    findings: list[dict] = []
    for (path, leaf), declared in zip(leaves, decls):
        actual = getattr(leaf, "sharding", None)
        problem = None
        if actual is None:
            problem = "leaf has no sharding (host array, never placed)"
        else:
            declared_rep = declared.is_fully_replicated
            actual_rep = actual.is_fully_replicated
            if not declared_rep and actual_rep:
                problem = ("declared sharded but actually FULLY "
                           "REPLICATED — the silent-fallback failure")
            elif declared_rep != actual_rep or not _equivalent(
                    declared, actual, getattr(leaf, "ndim", 0)):
                problem = "placement does not match the declared sharding"
        if problem is not None:
            rec = checker.record_sharding(tag, keystr(path), problem,
                                          declared, actual)
            if rec is not None:
                findings.append(rec)
    return findings


def report() -> dict:
    """Snapshot of watched forwards, storms, transfer violations, and
    sharding-claim mismatches."""
    return _get().report()


def reset(clock=time.monotonic) -> None:
    """Discard all recorded state (tests; each scenario gets a fresh
    checker). Watches made before the reset keep counting — into their
    original checker, which report() no longer reads."""
    global _checker
    with _checker_mu:
        _checker = _Checker(clock=clock)
