"""Lint policy: which rules bind where (docs/static_analysis.md).

The scopes below are the checked-in exemption policy the satellite work
agreed on — changing them is a reviewed decision, not a per-run flag:

  * **strict roots** fail ``make lint`` (exit 1) on any finding;
  * **warn roots** (``tools/`` — legacy one-off scripts) are surfaced
    but never block;
  * the **determinism** and **assert** scopes name the module families
    whose guarantees actually depend on those rules: step-indexed /
    replay / serving-dispatch code for determinism, the service layers
    (typed-error discipline since PR 1) for asserts. Numeric kernels
    (``go/``, ``ops/``, ``models/``, transcription) keep their inline
    shape asserts — they are invariant checks on math, not control flow.
"""

from __future__ import annotations

import dataclasses
import re

# pragma grammar: `# lint: allow[RULE] reason` — on the offending line
# or alone on the line above. The reason is mandatory; an allow without
# one is itself a finding (the allowlist stays narrow and auditable).
PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\[([a-z-]+)\]\s*(.*?)\s*$")

RULES = ("atomic-write", "determinism", "thread-discipline",
         "typed-error", "grammar-drift", "pragma", "bare-sleep",
         # the XLA performance-contract rules (ISSUE 11; the dynamic
         # half lives in analysis/xlacheck.py)
         "jit-boundary", "hot-sync", "donation", "constant-upload")

# np.random entry points that create explicitly-seeded, owned streams —
# everything else on np.random is hidden global state
NP_RANDOM_OK = frozenset({
    "default_rng", "Generator", "SeedSequence", "BitGenerator",
    "PCG64", "Philox",
})


@dataclasses.dataclass(frozen=True)
class LintConfig:
    # path classes (repo-root-relative, posix)
    strict_roots: tuple = ("deepgo_tpu", "bench.py")
    warn_roots: tuple = ("tools",)  # legacy one-offs: report, never block
    skip_parts: tuple = ("__pycache__",)

    # atomic-write: raw write-mode open()/np.save-to-path is only legal
    # inside the atomic writer itself
    atomic_exempt: tuple = ("deepgo_tpu/utils/atomicio.py",)

    # determinism: modules whose behavior must be a pure function of
    # (seed, step) — the bit-exact-resume and replay surfaces
    determinism_scope: tuple = (
        "deepgo_tpu/data/loader.py",
        "deepgo_tpu/data/dataset.py",
        "deepgo_tpu/experiments/checkpoint.py",
        "deepgo_tpu/loop/",
        "deepgo_tpu/serving/",
    )

    # bare-sleep: serving code never calls time.sleep directly — a bare
    # sleep in a dispatcher/router/supervisor thread is an invisible
    # stall (no span, no fault site, not injectable under test). Delays
    # there go through an injected sleep= hook or a waitable event;
    # chaos brownouts go through utils/faults.maybe_slow (which owns
    # the one legal sleep).
    sleep_scope: tuple = (
        "deepgo_tpu/serving/",
        "deepgo_tpu/sessions/",
    )

    # typed-error: service layers raise typed errors that survive
    # `python -O`; asserts there are findings
    assert_scope: tuple = (
        "deepgo_tpu/serving/",
        "deepgo_tpu/sessions/",
        "deepgo_tpu/loop/",
        "deepgo_tpu/obs/",
        "deepgo_tpu/parallel/",
        "deepgo_tpu/utils/",
        "deepgo_tpu/experiments/",
        "deepgo_tpu/analysis/",
        "deepgo_tpu/data/loader.py",
    )

    # hot-sync: (file, top-level function) scopes where a host<->device
    # sync (np.asarray / .item() / block_until_ready / device_get /
    # float(<forward call>)) stalls a dispatcher thread, a train-step
    # loop, or a per-request path. Syncs there are legal only at the
    # DECLARED materialization points, pragma'd with a reason
    # (docs/static_analysis.md). Explicit-path mode treats every
    # function as hot (fixture testing).
    hot_sync_scope: tuple = (
        ("deepgo_tpu/serving/engine.py", "_dispatch"),
        ("deepgo_tpu/serving/engine.py", "_dispatch_loop"),
        ("deepgo_tpu/serving/engine.py", "_collect"),
        ("deepgo_tpu/serving/fleet.py", "_dispatch"),
        ("deepgo_tpu/serving/fleet.py", "_router_loop"),
        ("deepgo_tpu/loop/learner.py", "train_window"),
        ("deepgo_tpu/experiments/experiment.py", "_train"),
    )

    # jit-boundary: (file, function) bodies that execute under trace
    # even though no decorator says so at the def site (helpers called
    # from inside jitted steps) — module/instance-state reads there are
    # baked into compiled programs exactly like in a decorated jit
    traced_scope: tuple = (
        ("deepgo_tpu/ops/augment.py", "augment_batch"),
        ("deepgo_tpu/training/steps.py", "_one_step"),
    )

    # grammar drift: the docs that hold the authoritative metric/event/
    # fault-site tables (serving.md only cross-references them)
    grammar_docs: tuple = ("docs/observability.md", "docs/robustness.md",
                           "docs/loop.md")
    # event/metric prefixes the drift checker enforces bidirectionally;
    # the first entry MUST stay "deepgo_" (the metric namespace — the
    # rest are JSONL event-kind namespaces). trace_* (request exemplars)
    # and lineage_* (the loop provenance chain) joined in ISSUE 10;
    # cost_* (the AOT device cost ledger) in ISSUE 12; ts_* and
    # anomaly_* (the fleet telemetry plane: sample/scrape-failure
    # events; the `anomaly` event itself is prefix-free by name and
    # documented next to them) in ISSUE 14; workload_* (the workload
    # observatory capture streams: request/position/capture-summary
    # records) in ISSUE 15; cache_* (the position cache's invalidation
    # event) in ISSUE 17; reshard_* (the resharding restore's event
    # stream next to the deepgo_reshard_* metrics) in ISSUE 18;
    # session_* (the durable game-session WAL records and the bulk-scan
    # annotation stream) in ISSUE 19; search_* (the PUCT search verdict
    # stream `cli trace` joins on) in ISSUE 20.
    grammar_prefixes: tuple = ("deepgo_", "obs_", "loop_", "fleet_",
                               "trace_", "lineage_", "cost_", "ts_",
                               "anomaly_", "workload_", "cache_",
                               "reshard_", "session_", "search_")
    # doc tokens that share a grammar prefix but are not metrics/events:
    # bench JSON keys and similar
    grammar_ignore: frozenset = frozenset({
        "obs_registry", "loop_games_per_hour", "trace_id",
        # the bench --mode search headline metric key (a BENCH json
        # field, not a JSONL event kind), and search_id (a record field
        # inside search_request, same shape as trace_id)
        "search_simulations_per_sec", "search_id",
        # flight-dump section / JSON keys that share the trace_ prefix
        # but are not JSONL event kinds
        "trace_exemplars",
        # position-cache marks on the trace_request timeline — event
        # names INSIDE an exemplar's `events` list, not JSONL kinds
        "cache_hit", "cache_miss", "cache_coalesced", "cache_promoted",
    })
    # files whose emissions feed the grammar check
    grammar_code_roots: tuple = ("deepgo_tpu", "bench.py")

    # explicit-path mode (`cli lint FILE...` and the fixture tests):
    # scope gates open up — every rule applies to every named file
    all_scopes: bool = False

    def in_scope(self, rel: str, scope: tuple) -> bool:
        if self.all_scopes:
            return True
        return any(rel == p or rel.startswith(p) for p in scope)
