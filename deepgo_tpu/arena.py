"""Compatibility shim: the original single-module arena API.

The 759-line module split into ``deepgo_tpu.agents`` (the player zoo)
and ``deepgo_tpu.match`` (the batched match harness + CLI) in round 5;
every public and test-visible private name is re-exported here so
``from deepgo_tpu import arena`` call sites — tools, tests, notebook —
keep working unchanged, and ``python -m deepgo_tpu.arena`` still runs
the match CLI.
"""

from .agents import (  # noqa: F401
    Agent, HeuristicAgent, OnePlyAgent, PolicyAgent, PolicySearchAgent,
    RandomAgent, SearchAgent, TwoPlyAgent, Value2PlyAgent,
    ValueSearchAgent, W_KILL, W_LADDER, W_LIB,
    W_OPP_LIB, W_SAVE, W_SELF_ATARI, _apply_and_summarize,
    _argmax_random_tiebreak, _make_agent, _no_own_eyes, _oneply_scores,
    _play_candidates, _policy_engine_for, _tactical_grids, _topk_mask,
    _veto_select,
)
from .match import main, play_match  # noqa: F401
from .selfplay import GameState  # noqa: F401
# serving-engine surface, so arena-level tools can opt their agents into
# the shared micro-batching evaluator (and its resilience supervisor)
# without a second import path
from .serving import (  # noqa: F401
    EngineConfig, InferenceEngine, SupervisedEngine, SupervisorConfig,
    close_shared_engines, shared_policy_engine, shared_value_engine,
)

if __name__ == "__main__":
    main()
