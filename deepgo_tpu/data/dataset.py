"""On-disk dataset format and random-access sampling.

Replaces the reference's one-torch-file-per-move layout plus
``<split>_game_counts.txt`` index (reference data.lua:53-80,
count_game_moves.sh) with a TPU-friendly memory-mapped shard per split:

  <root>/<split>/planes.bin   raw uint8, N x 9 x 19 x 19 packed records
  <root>/<split>/meta.npy     int32 (N, 6): player, x, y, black_rank,
                              white_rank, game_id
  <root>/<split>/games.json   ordered list of {name, start, count}

One 3.2 KB read per sampled position (memmap, zero-copy into the batch)
instead of open+deserialize of a torch file; the expensive 37-plane
expansion happens on device (deepgo_tpu.ops.expand).

Sampling schemes:
  * ``game``     uniform game, then uniform move within it — exact parity
    with the reference (data.lua:29-37), which oversamples moves from short
    games relative to the position-uniform distribution.
  * ``uniform``  uniform over positions (the corrected option,
    SURVEY.md section 7.6).
  * ``winner``   uniform over positions where the side to move went on to
    win the game — outcome-conditioned imitation (train on the winner's
    moves only). Requires a ``winner.npy`` sidecar built by
    tools/winner_index.py from the split's SGF results; the reference has
    no outcome information in its format at all.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..features import PACKED_CHANNELS
from ..utils import faults
from ..utils.atomicio import atomic_write
from ..utils.retry import retry_with_backoff
from .. import BOARD_SIZE

RECORD_SHAPE = (PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE)
RECORD_BYTES = int(np.prod(RECORD_SHAPE))

# meta columns
M_PLAYER, M_X, M_Y, M_BLACK_RANK, M_WHITE_RANK, M_GAME = range(6)
META_COLS = 6


class GoDataset:
    """Random-access view over one transcribed split."""

    def __init__(self, root: str, split: str):
        self.dir = os.path.join(root, split)
        planes_path = os.path.join(self.dir, "planes.bin")
        if not os.path.exists(planes_path):
            raise FileNotFoundError(
                f"no transcribed data at {self.dir} — run "
                f"python -m deepgo_tpu.data.transcribe first"
            )
        self.meta = np.load(os.path.join(self.dir, "meta.npy"))
        n = self.meta.shape[0]
        self.planes = np.memmap(planes_path, dtype=np.uint8, mode="r",
                                shape=(n, *RECORD_SHAPE))
        with open(os.path.join(self.dir, "games.json")) as f:
            games = json.load(f)
        self.game_names = [g["name"] for g in games]
        # (G, 2) start/count — games with zero moves are excluded at
        # transcription time (the reference filters them at load, data.lua:74)
        self.game_ranges = np.array([[g["start"], g["count"]] for g in games],
                                    dtype=np.int64)
        assert (self.game_ranges[:, 1] > 0).all()
        # optional per-position game-winner sidecar (1 black / 2 white /
        # 0 unknown or draw), built by tools/winner_index.py
        wpath = os.path.join(self.dir, "winner.npy")
        self.winner = np.load(wpath) if os.path.exists(wpath) else None
        self._winner_positions: np.ndarray | None = None

    def __len__(self) -> int:
        return int(self.meta.shape[0])

    @property
    def num_games(self) -> int:
        return len(self.game_names)

    def sample_indices(self, rng: np.random.Generator, n: int,
                       scheme: str = "game") -> np.ndarray:
        if scheme == "uniform":
            return rng.integers(0, len(self), size=n)
        if scheme == "game":
            games = rng.integers(0, self.num_games, size=n)
            starts = self.game_ranges[games, 0]
            counts = self.game_ranges[games, 1]
            return starts + (rng.random(n) * counts).astype(np.int64)
        if scheme == "winner":
            cand = self.winner_positions()
            return cand[rng.integers(0, cand.size, size=n)]
        raise ValueError(f"unknown sampling scheme {scheme!r}")

    def winner_positions(self) -> np.ndarray:
        """Indices of positions whose side to move won the game (decided
        games only). Cached; requires the winner.npy sidecar."""
        if self._winner_positions is None:
            if self.winner is None:
                raise FileNotFoundError(
                    f"scheme='winner' needs {self.dir}/winner.npy — build it "
                    "with python tools/winner_index.py")
            assert self.winner.shape[0] == len(self)
            self._winner_positions = np.flatnonzero(
                self.winner == self.meta[:, M_PLAYER])
            assert self._winner_positions.size > 0, (
                "no decided-game positions in this split")
        return self._winner_positions

    def batch_at(self, indices: np.ndarray):
        """Gather (packed_planes, to_move_player, rank_of_player, target).

        The memmap gather is the one spot where shared-storage flakiness
        (EIO on a cold page, the loader_io fault point) reaches training,
        so it runs under the bounded-backoff retry policy: transient
        OSErrors are absorbed with a logged note, anything persistent
        propagates after the attempts run out. Full jitter because this
        site retries from EVERY loader thread at once when shared storage
        blips — deterministic delays would re-synchronize the herd into
        periodic bursts against the same recovering mount."""
        def gather():
            faults.check("loader_io")
            return self.planes[indices], self.meta[indices]

        # (B, 9, 19, 19) uint8 copy out of the memmap
        packed, meta = retry_with_backoff(gather, attempts=5, base_delay=0.05,
                                          jitter=True)
        player = meta[:, M_PLAYER]
        rank = np.where(player == 1, meta[:, M_BLACK_RANK], meta[:, M_WHITE_RANK])
        target = meta[:, M_X] * BOARD_SIZE + meta[:, M_Y]
        return packed, player.astype(np.int32), rank.astype(np.int32), target.astype(np.int32)

    def sample_batch(self, rng: np.random.Generator, n: int, scheme: str = "game"):
        return self.batch_at(self.sample_indices(rng, n, scheme))

    def first_n(self, n: int):
        """Deterministic prefix batch (fixed validation sets)."""
        return self.batch_at(np.arange(min(n, len(self))))

    def even_indices(self, n: int) -> np.ndarray:
        """Deterministic sample of n positions spread evenly across games.

        Waterfill: every game contributes equally until its moves run out,
        so the sample covers min(num_games, n) games; within a game the
        quota is evenly spaced over the move sequence. No randomness — the
        same split always yields the same set. This replaces the round-1
        file-prefix validation set, which was biased to a handful of games
        when ``n`` was small (and improves on the reference, which drew ONE
        random minibatch per run, train.lua:62-67).
        """
        n = min(n, len(self))
        counts = self.game_ranges[:, 1]
        quota = np.zeros_like(counts)
        remaining = n
        while remaining > 0:
            active = np.flatnonzero(quota < counts)
            share = remaining // len(active)
            if share == 0:
                quota[active[:remaining]] += 1
                break
            add = np.minimum(counts[active] - quota[active], share)
            quota[active] += add
            remaining -= int(add.sum())
        out = []
        for g in np.flatnonzero(quota):
            pos = np.round(
                np.linspace(0, counts[g] - 1, quota[g])
            ).astype(np.int64)
            out.append(self.game_ranges[g, 0] + pos)
        return np.concatenate(out) if out else np.zeros(0, np.int64)

    def even_n(self, n: int):
        """Deterministic, game-balanced batch (fixed validation sets)."""
        return self.batch_at(self.even_indices(n))


class DatasetWriter:
    """Streaming writer for one split: append games, then finalize."""

    def __init__(self, out_dir: str):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        # lint: allow[atomic-write] streamed .tmp + fsync + os.replace in finalize() is the atomic pattern, sized beyond one buffer
        self._planes_f = open(os.path.join(out_dir, "planes.bin.tmp"), "wb")
        self._meta: list[np.ndarray] = []
        self._games: list[dict] = []
        self._count = 0

    def add_game(self, name: str, packed: np.ndarray, meta: np.ndarray) -> None:
        """packed: (M, 9, 19, 19) uint8; meta: (M, 6) int32 with game_id
        column ignored (rewritten to this game's index)."""
        m = packed.shape[0]
        if m == 0:
            return
        assert packed.dtype == np.uint8 and packed.shape[1:] == RECORD_SHAPE
        meta = meta.astype(np.int32, copy=True)
        meta[:, M_GAME] = len(self._games)
        self._planes_f.write(packed.tobytes())
        self._meta.append(meta)
        self._games.append({"name": name, "start": self._count, "count": m})
        self._count += m

    def finalize(self) -> int:
        # durable before visible, same contract as utils.atomicio: a crash
        # during transcription must never leave a plausible-looking but
        # partially-flushed planes.bin under the final name
        self._planes_f.flush()
        os.fsync(self._planes_f.fileno())
        self._planes_f.close()
        os.replace(os.path.join(self.out_dir, "planes.bin.tmp"),
                   os.path.join(self.out_dir, "planes.bin"))
        meta = (np.concatenate(self._meta) if self._meta
                else np.zeros((0, META_COLS), dtype=np.int32))
        with atomic_write(os.path.join(self.out_dir, "meta.npy")) as f:
            np.save(f, meta)
        # games.json is the shard's index-commit point: readers treat its
        # appearance as "this shard is complete", so it must flip atomically
        with atomic_write(os.path.join(self.out_dir, "games.json"),
                          mode="w") as f:
            json.dump(self._games, f)
        # a winner.npy sidecar describes the OLD shard; a re-transcription
        # with the same position count would otherwise silently keep stale
        # outcome labels (rebuild with tools/winner_index.py)
        stale = os.path.join(self.out_dir, "winner.npy")
        if os.path.exists(stale):
            os.remove(stale)
        return self._count
