"""Datasets, transcription, sampling, and the async input pipeline."""

from .dataset import GoDataset  # noqa: F401
