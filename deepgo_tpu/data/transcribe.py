"""Transcription pipeline: SGF game records -> memory-mapped training shards.

The reference's equivalent is transcribe_in_parallel (makedata.lua:517-533):
32 Lua threads each replaying a shard of the SGF file list and writing one
torch file per move. Here a multiprocessing pool replays games (the Go rules
engine releases no GIL, so processes, not threads) and the parent streams
results into one shard per split (deepgo_tpu.data.dataset.DatasetWriter).

Games without qualifying dan ranks are skipped entirely, like the reference
(makedata.lua:550). Transcription is idempotent per split: an existing
planes.bin is not rebuilt unless --force is given (reference targets_for
idempotency check, makedata.lua:364-367).

Usage:
  python -m deepgo_tpu.data.transcribe --src data/sgf --out data/processed \
      [--splits train,validation,test] [--workers N] [--force] [--engine auto]
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import sys
import time

import numpy as np

from .. import sgf
from .dataset import META_COLS, DatasetWriter


def transcribe_game(path: str, engine: str = "auto"):
    """Replay one SGF file -> (packed (M,9,19,19) uint8, meta (M,6) int32)
    or None if the game is skipped (no qualifying ranks / no moves).

    engine: "native" (C++ via ctypes, ~50x faster), "python", or "auto"
    (native when buildable, else python)."""
    from ..go import native, replay_positions

    game = sgf.parse_file(path)
    if game.ranks is None or not game.moves:
        return None
    use_native = engine == "native" or (engine == "auto" and native.available())
    if use_native:
        packed = native.transcribe_game_native(game.handicaps, game.moves)
    else:
        packed = np.stack([p for p, _ in replay_positions(game)])
    meta = np.array(
        [
            (m.player, m.x, m.y, game.ranks[0], game.ranks[1], 0)
            for m in game.moves
        ],
        dtype=np.int32,
    ).reshape(-1, META_COLS)
    return packed, meta


def _worker(args):
    path, engine = args
    try:
        result = transcribe_game(path, engine)
    except Exception as e:  # a corrupt SGF shouldn't kill the whole run
        return path, None, f"{type(e).__name__}: {e}"
    return path, result, None


def find_sgfs(src: str) -> list[str]:
    out = []
    for root, _, files in os.walk(src):
        for f in sorted(files):
            if f.endswith(".sgf"):
                out.append(os.path.join(root, f))
    return sorted(out)


def transcribe_split(src: str, out_dir: str, workers: int = 0,
                     force: bool = False, verbose: bool = True,
                     engine: str = "auto") -> int:
    """Transcribe every .sgf under ``src`` into one shard at ``out_dir``.
    Returns the number of examples written (or already present)."""
    done_marker = os.path.join(out_dir, "planes.bin")
    if os.path.exists(done_marker) and not force:
        meta = np.load(os.path.join(out_dir, "meta.npy"), mmap_mode="r")
        if verbose:
            print(f"{out_dir}: already transcribed ({meta.shape[0]} examples); "
                  f"use --force to rebuild")
        return int(meta.shape[0])

    paths = find_sgfs(src)
    writer = DatasetWriter(out_dir)
    start = time.time()

    if engine == "auto":
        from ..go import native

        engine = "native" if native.available() else "python"
    jobs = [(p, engine) for p in paths]
    workers = workers or max(1, (os.cpu_count() or 2) - 1)
    if workers > 1 and len(paths) > 1:
        with mp.Pool(workers) as pool:
            results = pool.imap(_worker, jobs)
            _consume(results, src, writer, verbose)
    else:
        _consume(map(_worker, jobs), src, writer, verbose)

    total = writer.finalize()
    if verbose:
        dt = time.time() - start
        print(f"{out_dir}: {total} examples from {len(paths)} games "
              f"in {dt:.1f}s ({total / max(dt, 1e-9):.0f} positions/sec)")
    return total


def _consume(results, src, writer, verbose):
    for path, result, err in results:
        name = os.path.relpath(path, src)
        if err is not None:
            print(f"SKIP {name}: {err}", file=sys.stderr)
        elif result is None:
            if verbose:
                print(f"skip {name}: no qualifying ranks or no moves")
        else:
            packed, meta = result
            writer.add_game(name, packed, meta)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--src", required=True, help="directory of .sgf files, or "
                    "a parent containing one subdirectory per split")
    ap.add_argument("--out", required=True)
    ap.add_argument("--splits", default="",
                    help="comma-separated split subdirectories (default: "
                    "treat --src as a single split)")
    ap.add_argument("--workers", type=int, default=0)
    ap.add_argument("--force", action="store_true")
    ap.add_argument("--engine", default="auto",
                    choices=["auto", "native", "python"])
    args = ap.parse_args()

    if args.splits:
        for split in args.splits.split(","):
            transcribe_split(os.path.join(args.src, split),
                             os.path.join(args.out, split),
                             workers=args.workers, force=args.force,
                             engine=args.engine)
    else:
        transcribe_split(args.src, args.out, workers=args.workers,
                         force=args.force, engine=args.engine)


if __name__ == "__main__":
    main()
