"""Asynchronous input pipeline: host sampling threads feeding the device.

The reference dedicates 32 Lua threads to loading+preprocessing because its
per-sample 37-plane expansion is host-side and slow (data.lua:11-24,
dataloader.lua:113-125). Here the host only gathers packed uint8 records
from a memmap (~3.2 KB/position), so a couple of sampler threads saturate
the pipeline; expansion happens on device inside the jitted step.

Batches are handed to JAX with ``jax.device_put`` as soon as they are
pulled, so the transfer of batch N+1 overlaps with the computation of
batch N (double buffering) — replacing the reference's synchronous
per-iteration CudaTensor copies (train.lua:99-103).

``num_threads=0`` degenerates to fully synchronous in-caller sampling, the
deterministic debugging mode the reference gets from
``prepare_data_loaders(1)`` (data.lua:20-24).
"""

from __future__ import annotations

import queue
import threading

import jax
import numpy as np

from .dataset import GoDataset


def make_host_batch(dataset: GoDataset, rng: np.random.Generator, batch_size: int,
                    scheme: str = "game", augment: bool = False) -> dict:
    packed, player, rank, target = dataset.sample_batch(rng, batch_size, scheme)
    batch = {"packed": packed, "player": player, "rank": rank, "target": target}
    if augment:
        # per-sample dihedral symmetry index, applied on device
        batch["sym"] = rng.integers(0, 8, size=batch_size).astype(np.int32)
    return batch


class AsyncLoader:
    """Bounded-queue prefetching sampler over a GoDataset split."""

    def __init__(
        self,
        dataset: GoDataset,
        batch_size: int,
        scheme: str = "game",
        seed: int = 0,
        num_threads: int = 2,
        prefetch: int = 4,
        sharding=None,
        augment: bool = False,
        stack: int = 0,
        stack_sharding=None,
    ):
        """``stack=K`` (K >= 1) makes ``get()`` return superbatches: K host
        batches stacked to (K, B, ...) and transferred in one device_put,
        for the scan-based multi-step train program
        (training.make_train_step_many). ``stack_sharding`` places them
        (parallel.superbatch_sharding); ``stack=0`` keeps the one-batch
        behavior."""
        self.dataset = dataset
        self.batch_size = batch_size
        self.scheme = scheme
        if scheme == "winner":
            # fail fast here, not inside a worker thread: a sampler raise
            # in a worker dies silently and get() then blocks forever on
            # the empty queue (missing winner.npy would otherwise burn a
            # whole run's timeout)
            dataset.winner_positions()
        self.sharding = sharding
        self.augment = augment
        self.stack = stack
        if stack >= 1 and stack_sharding is None and sharding is not None:
            # derive the superbatch placement from a single-batch
            # NamedSharding (P(spec) -> P(None, *spec)); other sharding
            # types cannot be lifted generically, so refuse rather than
            # silently drop the caller's placement
            from jax.sharding import NamedSharding, PartitionSpec as P

            assert isinstance(sharding, NamedSharding), (
                "stack >= 1 with a non-NamedSharding `sharding` requires an "
                "explicit `stack_sharding`")
            stack_sharding = NamedSharding(sharding.mesh,
                                           P(None, *sharding.spec))
        self.stack_sharding = stack_sharding
        self.num_threads = num_threads
        self._seq = np.random.SeedSequence(seed)
        if num_threads > 0:
            # prefetch is in units of get() calls: scale the single-batch
            # queue by the stack depth so a whole superbatch can be buffered
            # while the device runs the previous K-step program
            self._queue: queue.Queue = queue.Queue(
                maxsize=prefetch * max(1, stack))
            self._stop = threading.Event()
            self._threads = [
                threading.Thread(
                    target=self._worker,
                    args=(np.random.default_rng(s),),
                    daemon=True,
                )
                for s in self._seq.spawn(num_threads)
            ]
            for t in self._threads:
                t.start()
        else:
            self._rng = np.random.default_rng(self._seq)

    def _worker(self, rng: np.random.Generator) -> None:
        while not self._stop.is_set():
            batch = make_host_batch(self.dataset, rng, self.batch_size,
                                    self.scheme, self.augment)
            while not self._stop.is_set():
                try:
                    self._queue.put(batch, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def _host_batch(self) -> dict:
        if self.num_threads > 0:
            return self._queue.get()
        return make_host_batch(self.dataset, self._rng, self.batch_size,
                               self.scheme, self.augment)

    def get(self, stack: int | None = None) -> dict:
        """Next (super)batch, already dispatched to device (async transfer).

        ``stack`` overrides the constructor's stack depth for this call
        (used for a final partial window when iters % K != 0)."""
        stack = self.stack if stack is None else stack
        if stack < 1:
            batch = self._host_batch()
            if self.sharding is not None:
                return jax.device_put(batch, self.sharding)
            return jax.device_put(batch)
        parts = [self._host_batch() for _ in range(stack)]
        batch = {k: np.stack([p[k] for p in parts]) for k in parts[0]}
        if self.stack_sharding is not None:
            return jax.device_put(batch, self.stack_sharding)
        return jax.device_put(batch)

    def __iter__(self):
        while True:
            yield self.get()

    def close(self) -> None:
        if self.num_threads > 0:
            self._stop.set()
            for t in self._threads:
                t.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
