"""Asynchronous input pipeline: host sampling threads feeding the device.

The reference dedicates 32 Lua threads to loading+preprocessing because its
per-sample 37-plane expansion is host-side and slow (data.lua:11-24,
dataloader.lua:113-125). Here the host only gathers packed uint8 records
from a memmap (~3.2 KB/position), so a couple of sampler threads saturate
the pipeline; expansion happens on device inside the jitted step.

Batches are handed to JAX with ``jax.device_put`` as soon as they are
pulled, so the transfer of batch N+1 overlaps with the computation of
batch N (double buffering) — replacing the reference's synchronous
per-iteration CudaTensor copies (train.lua:99-103).

``num_threads=0`` degenerates to fully synchronous in-caller sampling, the
deterministic debugging mode the reference gets from
``prepare_data_loaders(1)`` (data.lua:20-24).
"""

from __future__ import annotations

import queue
import threading
import time

import jax
import numpy as np

from ..obs import get_registry
from .dataset import GoDataset


class LoaderClosed(RuntimeError):
    """get()/_drain called on (or blocked in) a closed AsyncLoader."""


def step_rng(seed: int, step: int) -> np.random.Generator:
    """The generator for training step ``step``: a pure function of
    (seed, step), independent of loader history. This is what makes the
    synchronous data stream *restartable* — a resume at step t draws
    exactly the batches the uninterrupted run would have drawn — and
    *scan-depth-invariant* (a K-step superbatch contains bitwise the same
    per-step batches as K single-step gets)."""
    return np.random.default_rng(np.random.SeedSequence((seed, step)))


def make_step_batch(dataset: GoDataset, seed: int, step: int, batch_size: int,
                    scheme: str = "game", augment: bool = False,
                    wire: str = "packed", stack: int = 0) -> dict:
    """Deterministic (super)batch covering steps [step, step + max(1, stack)).

    Each covered step samples from its own ``step_rng``; the gather and the
    optional nibble pass still run once over all k*B positions (the
    superbatch economics of make_host_superbatch). ``stack=0`` returns a
    flat (B, ...) batch, ``stack>=1`` a (K, B, ...) superbatch."""
    k = max(1, stack)
    idx_parts, sym_parts = [], []
    for t in range(step, step + k):
        rng = step_rng(seed, t)
        idx_parts.append(dataset.sample_indices(rng, batch_size, scheme))
        if augment:
            sym_parts.append(rng.integers(0, 8, size=batch_size).astype(np.int32))
    packed, player, rank, target = dataset.batch_at(np.concatenate(idx_parts))
    if wire == "nibble":
        from ..ops.wire import nibble_pack_np

        packed = nibble_pack_np(packed)

    def fold(a: np.ndarray) -> np.ndarray:
        if stack < 1:
            return a
        return a.reshape(k, batch_size, *a.shape[1:])

    batch = {"packed": fold(packed), "player": fold(player),
             "rank": fold(rank), "target": fold(target)}
    if augment:
        sym = np.concatenate(sym_parts)
        batch["sym"] = fold(sym)
    return batch


def make_host_batch(dataset: GoDataset, rng: np.random.Generator, batch_size: int,
                    scheme: str = "game", augment: bool = False,
                    wire: str = "packed") -> dict:
    packed, player, rank, target = dataset.sample_batch(rng, batch_size, scheme)
    if wire == "nibble":
        # transfer encoding: two cells per byte, halving relay bytes
        # (deepgo_tpu.ops.wire; the jitted step decodes symmetrically)
        from ..ops.wire import nibble_pack_np

        packed = nibble_pack_np(packed)
    batch = {"packed": packed, "player": player, "rank": rank, "target": target}
    if augment:
        # per-sample dihedral symmetry index, applied on device
        batch["sym"] = rng.integers(0, 8, size=batch_size).astype(np.int32)
    return batch


def make_host_superbatch(dataset: GoDataset, rng: np.random.Generator,
                         batch_size: int, stack: int, scheme: str = "game",
                         augment: bool = False, wire: str = "packed") -> dict:
    """One (K, B, ...) superbatch from a single K*B-position gather.

    Distributionally identical to np.stack-ing K ``make_host_batch``
    results (sampling is i.i.d.), but materially cheaper on the host: one
    memmap gather and one nibble pass over K*B positions, and the (K, B)
    shape falls out of a free reshape instead of a full stack copy. The
    round-4 streamed-feed measurement ran 2x under the chip's resident
    ceiling with the assembly serialized in the uploader thread
    (VERDICT item 5); feeding is host-bound on a small host, so the fix
    is fewer passes over the bytes, not more threads.
    """
    n = batch_size * stack
    packed, player, rank, target = dataset.sample_batch(rng, n, scheme)
    if wire == "nibble":
        from ..ops.wire import nibble_pack_np

        packed = nibble_pack_np(packed)

    def fold(a: np.ndarray) -> np.ndarray:
        return a.reshape(stack, batch_size, *a.shape[1:])

    batch = {"packed": fold(packed), "player": fold(player),
             "rank": fold(rank), "target": fold(target)}
    if augment:
        batch["sym"] = rng.integers(
            0, 8, size=(stack, batch_size)).astype(np.int32)
    return batch


class AsyncLoader:
    """Bounded-queue prefetching sampler over a GoDataset split."""

    def __init__(
        self,
        dataset: GoDataset,
        batch_size: int,
        scheme: str = "game",
        seed: int = 0,
        start_step: int = 0,
        num_threads: int = 2,
        prefetch: int = 4,
        sharding=None,
        augment: bool = False,
        stack: int = 0,
        stack_sharding=None,
        wire: str = "packed",
        device_prefetch: int = 0,
    ):
        """``stack=K`` (K >= 1) makes ``get()`` return superbatches: K host
        batches stacked to (K, B, ...) and transferred in one device_put,
        for the scan-based multi-step train program
        (training.make_train_step_many). ``stack_sharding`` places them
        (parallel.superbatch_sharding); ``stack=0`` keeps the one-batch
        behavior.

        ``wire="nibble"`` ships packed records two-cells-per-byte (half the
        host->device bytes; the step must be built with the same wire=).
        ``device_prefetch=N`` (with ``num_threads > 0``) adds an uploader
        thread that assembles and ``device_put``s up to N (super)batches
        ahead, so the transfer of batch n+1 runs while the device computes
        batch n even when ``device_put`` itself blocks (as it does through
        the relay tunnel).

        ``start_step`` is the training step this loader begins feeding.
        With ``num_threads=0`` the stream is *step-indexed*: batch for
        step t is a pure function of (seed, t) via ``step_rng``, so a
        resumed run replays the uninterrupted stream bit-exactly
        (docs/robustness.md). Threaded mode keeps the free-running i.i.d.
        stream (thread scheduling already makes its order nondeterministic;
        there start_step only offsets the worker seeds, continuing the
        stream statistically rather than bitwise)."""
        self.dataset = dataset
        self.batch_size = batch_size
        self.scheme = scheme
        self.wire = wire
        # hot-path aggregates (docs/observability.md): how long get()
        # callers actually block, and how full the prefetch queues run —
        # THE feed-bound-vs-compute-bound diagnostic. Metric objects are
        # cached here so the per-get cost is one observe() (no name
        # lookups on the hot path).
        reg = get_registry()
        self._obs_wait = reg.histogram(
            "deepgo_loader_wait_seconds",
            "time the consumer blocked in AsyncLoader.get()")
        self._obs_depth = reg.gauge(
            "deepgo_loader_queue_depth",
            "prefetch queue occupancy at the last get() (host = sampled "
            "batches, device = device_put-dispatched batches)")
        # host->device transfer time, split by whose clock paid for it:
        # path=inline blocks the consumer (a sub-bucket of loader wait in
        # the attribution table), path=uploader overlaps with compute
        self._obs_h2d = reg.histogram(
            "deepgo_h2d_seconds",
            "host->device transfer dispatch time "
            "(path=inline blocks the consumer, path=uploader overlaps)")
        if scheme == "winner":
            # fail fast here, not inside a worker thread: a sampler raise
            # in a worker dies silently and get() then blocks forever on
            # the empty queue (missing winner.npy would otherwise burn a
            # whole run's timeout)
            dataset.winner_positions()
        self.sharding = sharding
        self.augment = augment
        self.stack = stack
        if stack >= 1 and stack_sharding is None and sharding is not None:
            # derive the superbatch placement from a single-batch
            # NamedSharding (P(spec) -> P(None, *spec)); other sharding
            # types cannot be lifted generically, so refuse rather than
            # silently drop the caller's placement
            from jax.sharding import NamedSharding, PartitionSpec as P

            if not isinstance(sharding, NamedSharding):
                raise TypeError(
                    "stack >= 1 with a non-NamedSharding `sharding` "
                    "requires an explicit `stack_sharding`")
            stack_sharding = NamedSharding(sharding.mesh,
                                           P(None, *sharding.spec))
        self.stack_sharding = stack_sharding
        self.num_threads = num_threads
        self._seed = seed
        self._cursor = start_step  # next step to feed (step-indexed mode)
        self._seq = np.random.SeedSequence(seed + start_step)
        self._worker_error: BaseException | None = None
        self._dev_queue: queue.Queue | None = None
        if num_threads > 0:
            # the queue holds units at the default depth — whole
            # superbatches when stack >= 1 — so maxsize is directly in
            # units of get() calls
            self._queue: queue.Queue = queue.Queue(maxsize=prefetch)
            self._stop = threading.Event()
            worker_seeds = self._seq.spawn(num_threads)
            # off-depth get(stack=K') calls (the final partial window)
            # sample synchronously with their own stream rather than
            # re-slicing queued full-depth units
            self._sync_rng = np.random.default_rng(self._seq.spawn(1)[0])
            self._threads = [
                threading.Thread(
                    target=self._worker,
                    args=(np.random.default_rng(s),),
                    name=f"loader-worker-{i}",
                    daemon=True,
                )
                for i, s in enumerate(worker_seeds)
            ]
            for t in self._threads:
                t.start()
            if device_prefetch > 0:
                self._dev_queue = queue.Queue(maxsize=device_prefetch)
                self._uploader = threading.Thread(target=self._upload_loop,
                                                  name="loader-uploader",
                                                  daemon=True)
                self._threads.append(self._uploader)
                self._uploader.start()
        else:
            self._sync_rng = None  # sync mode is step-indexed, rng-free

    def _produce(self, stack: int, rng: np.random.Generator | None) -> dict:
        """Sample one unit at the given depth: a (B, ...) batch when
        ``stack < 1``, a (K, B, ...) superbatch otherwise. ``rng=None``
        (sync mode) draws step-indexed from the loader's step cursor."""
        if rng is None:
            batch = make_step_batch(self.dataset, self._seed, self._cursor,
                                    self.batch_size, self.scheme,
                                    self.augment, self.wire, stack=stack)
            self._cursor += max(1, stack)
            return batch
        if stack < 1:
            return make_host_batch(self.dataset, rng, self.batch_size,
                                   self.scheme, self.augment, self.wire)
        return make_host_superbatch(self.dataset, rng, self.batch_size,
                                    stack, self.scheme, self.augment,
                                    self.wire)

    def _worker(self, rng: np.random.Generator) -> None:
        try:
            while not self._stop.is_set():
                batch = self._produce(self.stack, rng)
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — surfaced via get()
            # a raise here used to kill the thread silently; with every
            # worker dead, get() then blocked on the empty queue forever.
            # Stash the first error (and stop the pool) so the consumer's
            # next get() re-raises it instead of deadlocking.
            if self._worker_error is None:
                self._worker_error = e
            self._stop.set()

    def _drain(self, q: queue.Queue):
        """Shutdown-aware blocking get: re-raises a stashed worker error,
        raises LoaderClosed once close() has been called (so neither a
        consumer nor the uploader thread can spin forever on a queue whose
        producers have exited), otherwise returns the next item."""
        while True:
            if self._worker_error is not None:
                raise RuntimeError(
                    "AsyncLoader worker thread died"
                ) from self._worker_error
            if self._stop.is_set():
                raise LoaderClosed("AsyncLoader is closed")
            try:
                return q.get(timeout=0.5)
            except queue.Empty:
                continue

    def _assemble(self, stack: int, path: str = "inline"):
        """One device_put-dispatched (super)batch at the given depth.

        The default depth pulls ready-made units from the worker queue;
        an off-depth request (final partial window) samples synchronously
        — workers only ever build full-depth units, so there is nothing
        to re-slice. ``path`` labels whose clock the transfer ran on
        (inline = the consumer's; uploader = overlapped)."""
        if self.num_threads > 0 and stack == self.stack:
            batch = self._drain(self._queue)
        else:
            batch = self._produce(stack, self._sync_rng)
        t0 = time.monotonic()
        if stack < 1:
            sharding = self.sharding
        else:
            sharding = self.stack_sharding
        if sharding is not None:
            out = jax.device_put(batch, sharding)
        else:
            out = jax.device_put(batch)
        self._obs_h2d.observe(time.monotonic() - t0, path=path)
        return out

    def _upload_loop(self) -> None:
        """Uploader thread: keep the device queue full of ready-to-run
        (super)batches at the default stack depth. device_put blocking (the
        relay tunnel) then costs this thread's time, not the train loop's."""
        try:
            while not self._stop.is_set():
                batch = self._assemble(self.stack, path="uploader")
                while not self._stop.is_set():
                    try:
                        self._dev_queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except LoaderClosed:
            return  # normal shutdown
        except BaseException as e:  # noqa: BLE001 — surfaced via get()
            if self._worker_error is None:
                self._worker_error = e
            self._stop.set()

    def get(self, stack: int | None = None) -> dict:
        """Next (super)batch, already dispatched to device (async transfer).

        ``stack`` overrides the constructor's stack depth for this call
        (used for a final partial window when iters % K != 0; such
        off-depth requests bypass the device-prefetch queue — sampling is
        i.i.d., so ordering against prefetched batches is immaterial)."""
        stack = self.stack if stack is None else stack
        t0 = time.monotonic()
        if self._dev_queue is not None and stack == self.stack:
            batch = self._drain(self._dev_queue)
        else:
            batch = self._assemble(stack)
        self._obs_wait.observe(time.monotonic() - t0)
        if self.num_threads > 0:
            self._obs_depth.set(self._queue.qsize(), queue="host")
            if self._dev_queue is not None:
                self._obs_depth.set(self._dev_queue.qsize(), queue="device")
        return batch

    def __iter__(self):
        while True:
            yield self.get()

    def _drain_dev_queue(self) -> None:
        """Discard everything staged on the device queue. An uploader
        blocked in ``_dev_queue.put()`` at close time can only exit once
        a slot frees up — nobody is consuming anymore, so close() must
        consume for it."""
        if self._dev_queue is None:
            return
        while True:
            try:
                self._dev_queue.get_nowait()
            except queue.Empty:
                return

    def close(self, timeout: float = 2.0) -> None:
        """Stop and join the thread pool.

        Joins used to time out silently, leaking an uploader blocked
        inside ``jax.device_put`` (the relay tunnel can block it for
        minutes) while close() returned as if the shutdown were clean.
        Now the device queue is drained while joining — unblocking an
        uploader parked in ``put()`` — and any thread that still won't
        exit is reported LOUDLY on stderr: a leaked thread is a fact the
        operator must see, not a secret. Leaked threads are daemons, so
        they die with the process either way."""
        if self.num_threads <= 0:
            return
        import sys

        self._stop.set()
        self._drain_dev_queue()
        for t in self._threads:
            deadline = time.monotonic() + timeout
            while t.is_alive():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                t.join(timeout=min(0.1, remaining))
                # keep the exit path clear: the uploader may have staged
                # another batch between drains
                self._drain_dev_queue()
        leaked = [t.name for t in self._threads if t.is_alive()]
        if leaked:
            print(
                f"AsyncLoader.close: {len(leaked)} thread(s) still alive "
                f"after {timeout}s: {', '.join(leaked)} — likely blocked "
                "inside jax.device_put (wedged device/relay). Leaking "
                "them; daemon threads die with the process.",
                file=sys.stderr, flush=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
