"""Dataset splitter: shuffle a directory of SGF games into split directories.

Equivalent of the reference's scatter_to_categories (makedata.lua:580-598):
files are shuffled once and dealt into the requested splits by count,
preserving relative subpaths. Operates on the raw SGF corpus (our pipeline
splits *before* transcription; the reference split after).

Usage:
  python -m deepgo_tpu.data.split --src raw_sgf --out data/sgf \
      --sizes train=180000,validation=2000,test=2000 [--seed 0]
"""

from __future__ import annotations

import argparse
import os
import random
import shutil

from .transcribe import find_sgfs


def scatter(src: str, out: str, sizes: dict[str, int], seed: int = 0) -> dict[str, int]:
    files = find_sgfs(src)
    rng = random.Random(seed)
    rng.shuffle(files)
    placed: dict[str, int] = {}
    i = 0
    for split, size in sizes.items():
        taken = files[i:i + size]
        i += size
        for path in taken:
            rel = os.path.relpath(path, src)
            dst = os.path.join(out, split, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copyfile(path, dst)
        placed[split] = len(taken)
        if len(taken) < size:
            break  # corpus exhausted (reference returns early too)
    return placed


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--sizes", required=True,
                    help="comma-separated split=count, dealt in order")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    sizes = {}
    for part in args.sizes.split(","):
        split, count = part.split("=")
        sizes[split] = int(count)
    placed = scatter(args.src, args.out, sizes, seed=args.seed)
    for split, n in placed.items():
        print(f"{split}: {n} games")


if __name__ == "__main__":
    main()
