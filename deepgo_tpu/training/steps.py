"""Jitted train and eval steps.

One fused XLA program per training step: on-device plane expansion ->
forward -> NLL -> backward -> optimizer update, with params and optimizer
state donated in place. This replaces the reference's separate
forward/criterion/backward/optimizer calls plus its accidental double
forward-backward per iteration (reference train.lua:106-111) — here each
step does exactly one fwd+bwd.

Batches are dicts of host arrays:
  packed  (B, 9, 19, 19) uint8
  player  (B,) int32      rank (B,) int32      target (B,) int32
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..models import policy_cnn
from ..ops import expand_planes, get_expand_fn
from ..utils import faults
from .optimizers import Optimizer


def _with_collective_site(step, site: str | None):
    """Host-side fault point at the step-dispatch boundary.

    For an elastic multi-host run every dispatch is a collective (the
    gradient all-reduce rides inside the fused program), so this is where
    the ``dist_collective`` chaos site lives: OUTSIDE the jit (fault
    injection is host control flow, never traced), right before the
    dispatch that would hang on a dead peer. ``site=None`` returns the
    step untouched — single-host training pays nothing."""
    if site is None:
        return step

    def checked(*args):
        faults.check(site)
        return step(*args)

    return checked


def nll_from_logits(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean negative log-likelihood over 361 classes, in float32
    (reference nn.ClassNLLCriterion over LogSoftMax, experiments.lua:45,150)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, targets[:, None], axis=-1)[:, 0]
    return -picked.mean()


def _unwire(packed, wire: str):
    """Decode the transfer encoding of the "packed" batch entry (see
    deepgo_tpu.ops.wire): "packed" = raw (B, 9, 19, 19) records, "nibble" =
    (B, 1625) two-cells-per-byte. First op of every jitted step so the
    rest of the program always sees raw packed records."""
    if wire == "nibble":
        from ..ops.wire import nibble_unpack

        return nibble_unpack(packed)
    if wire != "packed":  # no assert: must fail under python -O too
        raise ValueError(f"unknown wire format {wire!r}")
    return packed


def _one_step(params, opt_state, batch, cfg, optimizer, expand_planes,
              augment, anchor=None, wire="packed"):
    packed, target = _unwire(batch["packed"], wire), batch["target"]
    if augment:
        from ..ops.augment import augment_batch

        packed, target = augment_batch(packed, target, batch["sym"])
    planes = expand_planes(
        packed, batch["player"], batch["rank"],
        dtype=jnp.dtype(cfg.compute_dtype),
    )

    def loss_fn(p):
        logits = policy_cnn.apply(p, planes, cfg)
        loss = nll_from_logits(logits, target)
        if anchor is not None:
            # KL-anchored fine-tune: add weight * CE(anchor_probs, model).
            # CE differs from KL(anchor || model) only by the anchor's
            # (constant) entropy, so the gradients are the KL gradients;
            # the anchor forward runs inside the same fused program. The
            # reported loss includes the anchor term.
            a_params, a_cfg, weight = anchor
            a_logits = policy_cnn.apply(a_params, planes, a_cfg)
            a_prob = jax.lax.stop_gradient(
                jax.nn.softmax(a_logits.astype(jnp.float32), axis=-1))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            loss = loss + weight * (-(a_prob * logp).sum(axis=-1).mean())
        return loss

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params, opt_state = optimizer.update(params, grads, opt_state)
    return params, opt_state, loss


def make_train_step(cfg: policy_cnn.ModelConfig, optimizer: Optimizer,
                    expand_backend: str = "xla", augment: bool = False,
                    anchor=None, wire: str = "packed",
                    collective_site: str | None = None):
    """Returns step(params, opt_state, batch) -> (params, opt_state, loss).

    With ``augment=True`` the batch carries a per-sample "sym" entry and the
    packed record + target are dihedral-transformed on device before
    expansion (the augmentation the reference stubbed, dataloader.lua:41-44).

    ``anchor=(anchor_params, anchor_cfg, weight)`` adds a KL-to-anchor
    regularizer (see _one_step): the fine-tune stays near a frozen
    reference policy — the guard against the distribution collapse the
    expert-iteration study measured (RESULTS.md). The anchor params are
    closed over and become constants of the fused program.

    ``collective_site`` names a fault point checked host-side before each
    dispatch (elastic multi-host runs pass "dist_collective" so the chaos
    grammar reaches the collective boundary); None costs nothing.
    """
    expand_planes = get_expand_fn(expand_backend)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch):
        return _one_step(params, opt_state, batch, cfg, optimizer,
                         expand_planes, augment, anchor, wire)

    return _with_collective_site(step, collective_site)


def make_train_step_many(cfg: policy_cnn.ModelConfig, optimizer: Optimizer,
                         expand_backend: str = "xla", augment: bool = False,
                         anchor=None, wire: str = "packed",
                         collective_site: str | None = None):
    """Returns step(params, opt_state, batches) -> (params, opt_state, losses).

    ``batches`` is a superbatch: the same dict as ``make_train_step`` takes
    but with every array carrying a leading steps dimension (K, B, ...).
    One dispatch executes K chained optimizer steps via ``lax.scan`` and
    returns the K per-step losses as one device array. Numerically identical
    to K single steps; the point is dispatch amortization — through the TPU
    relay each dispatch costs a host round-trip, which at small model sizes
    dominates the actual compute (round-1 finding: 3L/64 training ran ~60x
    below the chip's inference bound). The reference has no analogue: its
    loop is host-driven per iteration (train.lua:93-132).
    """
    expand_planes = get_expand_fn(expand_backend)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batches):
        def body(carry, batch):
            params, opt_state, loss = _one_step(
                carry[0], carry[1], batch, cfg, optimizer, expand_planes,
                augment, anchor, wire)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), batches)
        return params, opt_state, losses

    return _with_collective_site(step, collective_site)


def make_eval_step(cfg: policy_cnn.ModelConfig, expand_backend: str = "xla",
                   wire: str = "packed"):
    """Returns eval(params, batch) -> (sum_nll, num_correct) over the batch
    (the building block of validation; reference eval_validation,
    train.lua:14-45). An optional float "mask" entry (1 = real example)
    supports padding partial batches to a fixed shape."""
    expand_planes = get_expand_fn(expand_backend)

    @jax.jit
    # lint: allow[donation] eval reuses params across every validation batch — donation would consume the caller's copy
    def step(params, batch):
        planes = expand_planes(
            _unwire(batch["packed"], wire), batch["player"], batch["rank"],
            dtype=jnp.dtype(cfg.compute_dtype),
        )
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(batch["target"].shape, jnp.float32)
        logits = policy_cnn.apply(params, planes, cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(logp, batch["target"][:, None], axis=-1)[:, 0]
        correct = ((jnp.argmax(logits, axis=-1) == batch["target"]) * mask).sum()
        return -(picked * mask).sum(), correct

    return step
