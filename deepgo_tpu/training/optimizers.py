"""Minimal functional optimizers (optax-style init/update pairs).

The reference trains with plain SGD whose learning rate decays
multiplicatively every step (reference optimizer.lua:16-27), and ships a
(broken) Adagrad (optimizer.lua:1-14 — it reads a global; fixed here).
Both are provided, plus SGD-with-momentum. State is a pytree, so the whole
optimizer step jits and shards with the params.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple[Any, Any]]  # (params, grads, state)


def sgd(rate: float, rate_decay: float = 0.0, momentum: float = 0.0) -> Optimizer:
    """params -= rate * grads; rate *= (1 - rate_decay) each step
    (reference SGD:step, optimizer.lua:24-27). Optional classical momentum."""

    def init(params):
        state = {"rate": jnp.asarray(rate, jnp.float32)}
        if momentum:
            state["velocity"] = jax.tree.map(jnp.zeros_like, params)
        return state

    def update(params, grads, state):
        r = state["rate"]
        if momentum:
            velocity = jax.tree.map(
                lambda v, g: momentum * v + g, state["velocity"], grads
            )
            params = jax.tree.map(lambda p, v: p - r * v, params, velocity)
            new_state = {"rate": r * (1.0 - rate_decay), "velocity": velocity}
        else:
            params = jax.tree.map(lambda p, g: p - r * g, params, grads)
            new_state = {"rate": r * (1.0 - rate_decay)}
        return params, new_state

    return Optimizer(init, update)


def adagrad(rate: float, decay: float = 0.95, eps: float = 1e-10) -> Optimizer:
    """RMS-accumulator Adagrad, the working version of optimizer.lua:1-14:
    accum = decay*accum + (1-decay)*g^2; params -= rate * g / sqrt(accum)."""

    def init(params):
        return {
            "rate": jnp.asarray(rate, jnp.float32),
            "accum": jax.tree.map(jnp.ones_like, params),
        }

    def update(params, grads, state):
        accum = jax.tree.map(
            lambda a, g: decay * a + (1.0 - decay) * g * g, state["accum"], grads
        )
        params = jax.tree.map(
            lambda p, g, a: p - state["rate"] * g / jnp.sqrt(a + eps),
            params,
            grads,
            accum,
        )
        return params, {"rate": state["rate"], "accum": accum}

    return Optimizer(init, update)


OPTIMIZERS = {"sgd": sgd, "adagrad": adagrad}
