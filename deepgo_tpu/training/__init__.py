"""Training: optimizers, jitted train/eval steps, the training loop."""

from .optimizers import adagrad, sgd  # noqa: F401
from .steps import (  # noqa: F401
    make_eval_step,
    make_train_step,
    make_train_step_many,
)
